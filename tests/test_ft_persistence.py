"""GCS file persistence + object spilling (VERDICT round-1 item #2).

Reference: ``GcsTableStorage`` over ``RedisStoreClient``
(``src/ray/gcs/store_client/redis_store_client.h:111``) and
``LocalObjectManager`` spilling (``src/ray/raylet/local_object_manager.h:42``).
"""

import asyncio
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import (
    HybridObjectStore,
    SpillStore,
    arena_name_for,
)


# ------------------------------------------------------------- GCS snapshot


def _mk_session(tmp):
    os.makedirs(os.path.join(tmp, "sockets"), exist_ok=True)
    os.makedirs(os.path.join(tmp, "logs"), exist_ok=True)
    return tmp


def test_gcs_snapshot_roundtrip(monkeypatch, tmp_path):
    """Tables written by one GcsServer instance are visible in a fresh one
    pointed at the same storage path."""
    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer

    session = _mk_session(str(tmp_path))
    config.reload({"gcs_storage": "file"})
    try:
        loop = asyncio.new_event_loop()

        async def phase1():
            gcs = GcsServer(session)
            await gcs.start(port=0)
            # populate a few tables through handlers
            await gcs.handle_register_node(
                node_id="n1", addr="tcp:127.0.0.1:1", resources={"CPU": 4},
                labels={})
            await gcs.handle_kv_put(ns="test", key="k", value=b"v")
            await gcs.handle_add_job(job_id=7, info={"driver_pid": 1})
            # wait for a snapshot write
            for _ in range(40):
                await asyncio.sleep(0.1)
                if os.path.exists(gcs._storage_path):
                    break
            assert os.path.exists(gcs._storage_path)
            await gcs.stop()

        loop.run_until_complete(phase1())

        async def phase2():
            gcs2 = GcsServer(session)  # loads the snapshot in __init__
            assert "n1" in gcs2.nodes
            assert gcs2.nodes["n1"]["total"] == {"CPU": 4}
            assert await gcs2.handle_kv_get(ns="test", key="k") == b"v"
            assert 7 in gcs2.jobs

        loop.run_until_complete(phase2())
        loop.close()
    finally:
        config.reload()


def test_gcs_large_kv_offloaded_to_blob_files(tmp_path):
    """ADVICE r2: 100MB runtime-env packages must not be re-pickled every
    snapshot tick — large kv values live in content-addressed side files,
    survive a restart, and are GC'd when deleted."""
    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer

    session = _mk_session(str(tmp_path))
    config.reload({"gcs_storage": "file"})
    try:
        loop = asyncio.new_event_loop()
        big = os.urandom(256 * 1024)

        async def phase1():
            gcs = GcsServer(session)
            await gcs.handle_kv_put(ns="packages", key="pkg://x", value=big)
            gcs._write_snapshot()
            # the snapshot pickle must NOT embed the big payload
            assert os.path.getsize(gcs._storage_path) < len(big) // 2
            blobs = os.listdir(gcs._blob_dir())
            assert len(blobs) == 1
            # unchanged content: second snapshot reuses the same blob file
            gcs._dirty = True
            gcs._write_snapshot()
            assert os.listdir(gcs._blob_dir()) == blobs

        loop.run_until_complete(phase1())

        async def phase2():
            gcs2 = GcsServer(session)  # restores from snapshot + blobs
            assert await gcs2.handle_kv_get(ns="packages",
                                            key="pkg://x") == big
            # deletion GCs the orphaned blob at the next snapshot
            await gcs2.handle_kv_del(ns="packages", key="pkg://x")
            gcs2._write_snapshot()
            assert os.listdir(gcs2._blob_dir()) == []
            # re-adding the SAME content must re-upload the blob (the
            # known-names cache is pruned at GC; a stale entry would
            # leave the new snapshot referencing a deleted blob)
            await gcs2.handle_kv_put(ns="packages", key="pkg://x",
                                     value=big)
            gcs2._dirty = True
            gcs2._write_snapshot()
            assert len(os.listdir(gcs2._blob_dir())) == 1

        loop.run_until_complete(phase2())
        loop.close()
    finally:
        config.reload()


def test_gcs_unpicklable_kv_does_not_kill_persistence(tmp_path):
    """ADVICE r2 / VERDICT weak #8: one unpicklable kv value must not
    silently abort every subsequent snapshot — it is dropped loudly and
    the rest of the state keeps persisting."""
    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer

    session = _mk_session(str(tmp_path))
    config.reload({"gcs_storage": "file"})
    try:
        loop = asyncio.new_event_loop()

        async def run():
            gcs = GcsServer(session)
            await gcs.handle_kv_put(ns="t", key="good", value=b"keep-me")
            gcs.kv[("t", "bad")] = lambda: None  # unpicklable
            gcs._write_snapshot()
            gcs2 = GcsServer(session)
            assert await gcs2.handle_kv_get(ns="t", key="good") == b"keep-me"
            assert await gcs2.handle_kv_get(ns="t", key="bad") is None

        loop.run_until_complete(run())
        loop.close()
    finally:
        config.reload()


def test_gcs_idle_snapshot_skipped(tmp_path):
    """Dirty-flag gating: with no state change, the persist tick does not
    re-serialize (an idle cluster pays nothing)."""
    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer

    session = _mk_session(str(tmp_path))
    config.reload({"gcs_storage": "file"})
    try:
        loop = asyncio.new_event_loop()

        async def run():
            gcs = GcsServer(session)
            await gcs.handle_kv_put(ns="t", key="k", value=b"v")
            gcs._write_snapshot()
            gcs._dirty = False
            calls = []
            orig = gcs._snapshot_state
            gcs._snapshot_state = lambda: calls.append(1) or orig()
            # simulate persist ticks that are not backstop ticks
            for tick in range(1, 6):
                if not gcs._dirty and tick % 20:
                    continue
                gcs._write_snapshot()
            assert calls == []
            # a mutation makes the next tick write again
            await gcs.handle_kv_put(ns="t", key="k2", value=b"v2")
            assert gcs._dirty

        loop.run_until_complete(run())
        loop.close()
    finally:
        config.reload()


def test_gcs_process_restart_actors_survive(no_cluster, tmp_path):
    """Kill -9 the standalone GCS, restart it on the same port with the
    same storage: the driver reconnects, named actors resolve, and the
    still-running actor keeps serving calls."""
    import ray_tpu

    session = _mk_session(str(tmp_path / "session"))
    os.makedirs(session, exist_ok=True)
    _mk_session(session)
    env = dict(os.environ)
    env["RAY_TPU_GCS_STORAGE"] = "file"
    env["RAY_TPU_DASHBOARD"] = "0"

    def start_gcs(port):
        p = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.gcs_proc",
             "--session-dir", session, "--port", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
            start_new_session=True)
        line = p.stdout.readline().decode().strip()
        info = json.loads(line)
        return p, info["addr"], info["port"]

    gcs_proc, gcs_addr, gcs_port = start_gcs(0)
    raylet_log = open(os.path.join(session, "logs", "raylet.log"), "ab")
    raylet = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.raylet_proc",
         "--session-dir", session, "--gcs-addr", gcs_addr,
         "--resources", json.dumps({"CPU": 4}),
         "--labels", "{}", "--node-name", "head"],
        stdout=subprocess.PIPE, stderr=raylet_log, env=env,
        start_new_session=True)
    raylet.stdout.readline()  # ready line
    try:
        ray_tpu.init(address=gcs_addr)

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        c = Counter.options(name="survivor").remote()
        assert ray_tpu.get(c.incr.remote()) == 1
        time.sleep(1.0)  # let a snapshot land

        # hard-kill the GCS and restart on the SAME port
        gcs_proc.kill()
        gcs_proc.wait(timeout=10)
        gcs_proc, gcs_addr2, _ = start_gcs(gcs_port)
        assert gcs_addr2 == gcs_addr

        # actor state survived (the actor process never died) and the
        # restarted GCS still resolves it by name
        time.sleep(2.0)  # raylet heartbeat re-attach window
        c2 = ray_tpu.get_actor("survivor")
        assert ray_tpu.get(c2.incr.remote(), timeout=60) == 2
        nodes = ray_tpu.nodes()
        assert any(n["alive"] for n in nodes)
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        for p in (gcs_proc, raylet):
            try:
                p.kill()
                p.wait(timeout=5)
            except Exception:
                pass


def _start_store(tmp, name="store.pkl"):
    """Spawn a standalone external GCS store process; -> (proc, addr)."""
    p = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.gcs_store",
         "--port", "0", "--path", os.path.join(tmp, name)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        start_new_session=True)
    line = p.stdout.readline().decode().strip()
    assert line.startswith("GCS_STORE_ADDR "), line
    return p, line.split(" ", 1)[1]


def test_external_store_client_roundtrip(tmp_path):
    """StoreClient seam (VERDICT r4 missing #3): snapshot + WAL + blobs
    through the standalone store process, including the store's OWN
    durability file (restart the store, state intact)."""
    from ray_tpu._private.gcs_store import ExternalStoreClient

    proc, addr = _start_store(str(tmp_path))
    try:
        c = ExternalStoreClient(addr)
        assert c.read_snapshot() is None
        c.write_snapshot(b"snap-1")
        assert c.read_snapshot() == b"snap-1"
        assert c.wal_size() == 0
        c.wal_append(b"abc")
        c.wal_append(b"defg", at=3)
        assert c.wal_size() == 7
        assert c.wal_read() == b"abcdefg"
        # offset-checked appends are exactly-once under client retries:
        # a duplicate is acked without applying, a gap raises
        c.wal_append(b"defg", at=3)  # duplicate of the append above
        assert c.wal_read() == b"abcdefg"
        with pytest.raises(Exception, match="cursor mismatch"):
            c.wal_append(b"zz", at=99)
        c.wal_truncate()
        assert c.wal_size() == 0
        assert not c.has_blob("b1")
        c.put_blob("b1", b"payload")
        assert c.has_blob("b1")
        assert c.get_blob("b1") == b"payload"
        assert c.list_blobs() == ["b1"]
        c.del_blob("b1")
        assert c.get_blob("b1") is None
        # store-side durability: every mutation is on the store's disk
        # BEFORE the ack, so a kill at any instant loses nothing
        c.write_snapshot(b"snap-2")
        c.put_blob("b2", b"x" * 100)
        c.close()
        proc.kill()
        proc.wait(timeout=10)
        proc, addr = _start_store(str(tmp_path))
        c2 = ExternalStoreClient(addr)
        assert c2.read_snapshot() == b"snap-2"
        assert c2.get_blob("b2") == b"x" * 100
        c2.close()
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_external_store_killed_and_restarted_midrun(tmp_path):
    """The store process is SIGKILLed while the GCS is live, then
    restarted on the same port: the sync client reconnects, the WAL
    cursor resyncs (offset-checked appends reject nothing), and
    mutations made DURING the outage are journaled once the store is
    back — a fresh GCS then restores them."""
    import socket

    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer

    session = _mk_session(str(tmp_path))
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    def start_store():
        p = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.gcs_store",
             "--port", str(port),
             "--path", os.path.join(str(tmp_path), "store.pkl")],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            start_new_session=True)
        line = p.stdout.readline().decode().strip()
        assert line.startswith("GCS_STORE_ADDR "), line
        return p, line.split(" ", 1)[1]

    store_proc, addr = start_store()
    config.reload({"gcs_storage": "external",
                   "gcs_external_store_addr": addr})
    try:
        loop = asyncio.new_event_loop()

        async def run():
            nonlocal store_proc
            gcs = GcsServer(session)
            await gcs.start(port=0)
            await gcs.handle_kv_put(ns="t", key="before", value=b"1")
            # wait until the pre-kill mutation is durable in the store
            from ray_tpu._private.gcs_store import ExternalStoreClient

            probe = ExternalStoreClient(addr)
            deadline = time.time() + 15
            while time.time() < deadline:
                if probe.read_snapshot() or probe.wal_size() > 0:
                    break
                await asyncio.sleep(0.2)
            probe.close()
            # SIGKILL the store; the GCS must stay healthy (persistence
            # retries quietly off the event loop)
            store_proc.kill()
            store_proc.wait(timeout=10)
            await gcs.handle_kv_put(ns="t", key="during", value=b"2")
            await asyncio.sleep(1.5)  # a few failed persist ticks
            assert await gcs.handle_kv_get(ns="t", key="during") == b"2"
            # restart the store on the SAME port (its own disk restores)
            store_proc, addr2 = start_store()
            assert addr2 == addr
            # the outage-window mutation must become durable
            probe = ExternalStoreClient(addr)
            deadline = time.time() + 30
            ok = False
            while time.time() < deadline:
                wal = probe.wal_read()
                snap = probe.read_snapshot() or b""
                if b"during" in wal or b"during" in snap:
                    ok = True
                    break
                await asyncio.sleep(0.3)
            probe.close()
            assert ok, "outage-window mutation never reached the store"
            await gcs.stop()

        loop.run_until_complete(run())

        async def verify():
            gcs2 = GcsServer(session)  # restores via the external store
            assert await gcs2.handle_kv_get(ns="t", key="before") == b"1"
            assert await gcs2.handle_kv_get(ns="t", key="during") == b"2"

        loop.run_until_complete(verify())
        loop.close()
    finally:
        config.reload()
        try:
            store_proc.kill()
            store_proc.wait(timeout=10)
        except Exception:
            pass


def test_gcs_restart_from_external_store_head_disk_lost(no_cluster,
                                                        tmp_path):
    """The Redis-for-GCS-FT role (reference redis_store_client.h:111):
    cluster state lives in the external store, so killing the GCS AND
    wiping every head-local gcs file still restores the cluster — the
    named actor survives and keeps serving."""
    import glob

    import ray_tpu

    session = _mk_session(str(tmp_path / "session"))
    os.makedirs(session, exist_ok=True)
    store_proc, store_addr = _start_store(str(tmp_path))
    env = dict(os.environ)
    env["RAY_TPU_GCS_STORAGE"] = "external"
    env["RAY_TPU_GCS_EXTERNAL_STORE_ADDR"] = store_addr
    env["RAY_TPU_DASHBOARD"] = "0"

    def start_gcs(port):
        p = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.gcs_proc",
             "--session-dir", session, "--port", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
            start_new_session=True)
        line = p.stdout.readline().decode().strip()
        info = json.loads(line)
        return p, info["addr"], info["port"]

    gcs_proc, gcs_addr, gcs_port = start_gcs(0)
    raylet_log = open(os.path.join(session, "logs", "raylet.log"), "ab")
    raylet = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.raylet_proc",
         "--session-dir", session, "--gcs-addr", gcs_addr,
         "--resources", json.dumps({"CPU": 4}),
         "--labels", "{}", "--node-name", "head"],
        stdout=subprocess.PIPE, stderr=raylet_log, env=env,
        start_new_session=True)
    raylet.stdout.readline()  # ready line
    try:
        ray_tpu.init(address=gcs_addr)

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        c = Counter.options(name="survivor-ext").remote()
        assert ray_tpu.get(c.incr.remote()) == 1
        time.sleep(1.0)  # let a snapshot land in the external store

        # hard-kill the GCS and WIPE the head's local gcs state: the
        # file-backend layout must not exist (or must not matter)
        gcs_proc.kill()
        gcs_proc.wait(timeout=10)
        for f in glob.glob(os.path.join(session, "gcs_state.pkl*")):
            if os.path.isdir(f):
                import shutil
                shutil.rmtree(f, ignore_errors=True)
            else:
                os.unlink(f)
        gcs_proc, gcs_addr2, _ = start_gcs(gcs_port)
        assert gcs_addr2 == gcs_addr

        time.sleep(2.0)  # raylet heartbeat re-attach window
        c2 = ray_tpu.get_actor("survivor-ext")
        assert ray_tpu.get(c2.incr.remote(), timeout=60) == 2
        nodes = ray_tpu.nodes()
        assert any(n["alive"] for n in nodes)
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        for p in (gcs_proc, raylet, store_proc):
            try:
                p.kill()
                p.wait(timeout=5)
            except Exception:
                pass


# ---------------------------------------------------------------- spilling


def test_spill_store_roundtrip(tmp_path):
    sp = SpillStore(str(tmp_path))
    oid = ObjectID.from_random()
    sp.put_bytes(oid, b"hello-spill")
    assert sp.contains(oid)
    assert bytes(sp.get_buffer(oid)) == b"hello-spill"
    st = sp.stats()
    assert st["spilled_objects"] == 1 and st["spilled_bytes"] == 11
    sp.delete(oid)
    assert not sp.contains(oid)
    assert sp.get_buffer(oid) is None


@pytest.fixture
def small_arena_store(tmp_path):
    """Hybrid store with a tiny arena so pressure paths are reachable."""
    from ray_tpu._private.config import config
    from ray_tpu._private import native_store

    if not native_store.available():
        pytest.skip("native store unavailable")
    config.reload({"arena_store_bytes": 4 * 1024 * 1024,
                   "object_spill_dir": str(tmp_path / "spill")})
    session = str(tmp_path / "sess")
    os.makedirs(session, exist_ok=True)
    store = HybridObjectStore(session)
    yield store
    store.close(unlink_created=True)
    try:
        from multiprocessing import shared_memory

        seg = shared_memory.SharedMemory(name=arena_name_for(session))
        seg.close()
        seg.unlink()
    except Exception:
        pass
    config.reload()


def test_pressure_spills_cold_objects_instead_of_destroying(
        small_arena_store):
    """Released (refcount-0) objects under arena pressure are persisted to
    the spill dir and remain readable — LRU eviction no longer loses data."""
    store = small_arena_store
    assert store.arena is not None
    payload = os.urandom(256 * 1024)
    cold = []
    for i in range(8):
        oid = ObjectID.from_random()
        store.put_serialized(oid, payload)
        store.arena.release(oid)  # drop creator pin: cold + unreferenced
        cold.append(oid)
    # fill the arena past capacity: pressure must spill the cold ones
    for i in range(16):
        store.put_serialized(ObjectID.from_random(), payload)
    spilled = [oid for oid in cold if store.spill.contains(oid)]
    assert spilled, "pressure did not spill any cold objects"
    # spilled objects are still readable through the store (restore path)
    for oid in spilled:
        assert bytes(store.get_buffer(oid)) == payload


def test_shm_exhausted_falls_back_to_spill_dir(small_arena_store,
                                               monkeypatch):
    """When the segment tier cannot allocate (shm full), puts land in the
    spill directory and reads restore transparently."""
    store = small_arena_store

    def boom(*a, **kw):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(store.segments, "put_into", boom)
    oid = ObjectID.from_random()
    big = os.urandom(2 * 1024 * 1024)  # > arena_max (4MiB/4=1MiB): segments tier
    name = store.put_serialized(oid, big)
    assert name == "spill"
    assert store.contains(oid)
    assert bytes(store.get_buffer(oid)) == big


def test_put_larger_than_arena_completes(small_arena_store):
    """The VERDICT acceptance case: a workload bigger than the arena
    completes, objects stay readable."""
    store = small_arena_store
    oids = []
    payload = os.urandom(512 * 1024)
    for i in range(20):  # 10 MiB through a 4 MiB arena
        oid = ObjectID.from_random()
        store.put_serialized(oid, payload)
        oids.append(oid)
    for oid in oids:
        assert bytes(store.get_buffer(oid)) == payload


def test_gcs_wal_replayed_nodes_keep_volatile_fields(tmp_path):
    """ADVICE r4 (high): node records are journaled with volatile fields
    (last_heartbeat/pending_demand) stripped; replaying such a record must
    not leave the restored node without ``last_heartbeat`` — that killed
    the health-check loop with KeyError on its first iteration, so dead
    nodes were never detected after a restart."""
    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer

    session = _mk_session(str(tmp_path))
    config.reload({"gcs_storage": "file"})
    try:
        loop = asyncio.new_event_loop()

        async def phase1():
            gcs = GcsServer(session)
            await gcs.start(port=0)
            await gcs.handle_register_node(
                node_id="n1", addr="tcp:127.0.0.1:1", resources={"CPU": 4},
                labels={})
            for _ in range(40):
                await asyncio.sleep(0.1)
                if os.path.exists(gcs._storage_path):
                    break
            # mutate `available` so the node is re-journaled into the WAL
            # (with volatile fields stripped)
            gcs.nodes["n1"]["available"]["CPU"] = 1
            gcs._dirty = True
            for _ in range(40):
                await asyncio.sleep(0.1)
                if os.path.exists(gcs._wal_path()) and \
                        os.path.getsize(gcs._wal_path()) > 0:
                    break
            assert os.path.getsize(gcs._wal_path()) > 0
            await gcs.stop()

        loop.run_until_complete(phase1())

        async def phase2():
            gcs2 = GcsServer(session)  # snapshot + WAL replay
            node = gcs2.nodes["n1"]
            assert node["available"]["CPU"] == 1  # WAL record applied
            assert "last_heartbeat" in node
            assert "pending_demand" in node
            # one health-check iteration must not raise (regression: it
            # died with KeyError and left dead nodes undetectable forever)
            now = time.time()
            for node_id, n in list(gcs2.nodes.items()):
                assert not (n["alive"] and now - n["last_heartbeat"] > 1e9)

        loop.run_until_complete(phase2())
        loop.close()
    finally:
        config.reload()


def test_gcs_wal_del_sentinel_value_roundtrips(tmp_path):
    """ADVICE r4 (low): a kv value that happens to equal the WAL deletion
    marker string must replay as a value, not a deletion (the sentinel is
    a structured tuple matched by exact shape, not a bare string)."""
    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer

    session = _mk_session(str(tmp_path))
    config.reload({"gcs_storage": "file"})
    try:
        loop = asyncio.new_event_loop()

        async def phase1():
            gcs = GcsServer(session)
            await gcs.start(port=0)
            await gcs.handle_kv_put(ns="t", key="seed", value=b"x")
            for _ in range(40):
                await asyncio.sleep(0.1)
                if os.path.exists(gcs._storage_path):
                    break
            # journal a value equal to the legacy string marker
            await gcs.handle_kv_put(ns="t", key="tricky",
                                    value="__wal_del__")
            for _ in range(40):
                await asyncio.sleep(0.1)
                if os.path.exists(gcs._wal_path()) and \
                        os.path.getsize(gcs._wal_path()) > 0:
                    break
            # the value must actually be IN the WAL (not a snapshot) or
            # phase2 would pass without exercising the replay path
            assert os.path.getsize(gcs._wal_path()) > 0
            await gcs.stop()

        loop.run_until_complete(phase1())

        async def phase2():
            gcs2 = GcsServer(session)
            assert await gcs2.handle_kv_get(
                ns="t", key="tricky") == "__wal_del__"

        loop.run_until_complete(phase2())
        loop.close()
    finally:
        config.reload()


def test_gcs_wal_journals_deltas_and_replays(tmp_path):
    """Incremental persistence (VERDICT r3 weak #8): between full
    snapshots, mutations land in the append-only WAL as per-key records
    (no whole-state re-pickle); restart = snapshot + WAL replay; WAL
    compaction truncates after the next full snapshot."""
    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer

    session = _mk_session(str(tmp_path))
    config.reload({"gcs_storage": "file"})
    try:
        loop = asyncio.new_event_loop()

        async def phase1():
            gcs = GcsServer(session)
            await gcs.start(port=0)
            await gcs.handle_kv_put(ns="t", key="k0", value=b"v0")
            # first dirty tick -> full snapshot (interval elapsed at boot)
            for _ in range(40):
                await asyncio.sleep(0.1)
                if os.path.exists(gcs._storage_path):
                    break
            assert os.path.exists(gcs._storage_path)
            snap_mtime = os.path.getmtime(gcs._storage_path)
            # further mutations inside the interval -> WAL, snapshot
            # untouched
            await gcs.handle_kv_put(ns="t", key="k1", value=b"v1")
            await gcs.handle_add_job(job_id=3, info={"driver_pid": 2})
            await gcs.handle_kv_del(ns="t", key="k0")
            for _ in range(40):
                await asyncio.sleep(0.1)
                if os.path.exists(gcs._wal_path()) and \
                        os.path.getsize(gcs._wal_path()) > 0:
                    break
            assert os.path.getsize(gcs._wal_path()) > 0
            assert os.path.getmtime(gcs._storage_path) == snap_mtime, \
                "mutations inside the interval must journal, not snapshot"
            await gcs.stop()

        loop.run_until_complete(phase1())

        async def phase2():
            gcs2 = GcsServer(session)  # snapshot + WAL replay
            assert await gcs2.handle_kv_get(ns="t", key="k1") == b"v1"
            assert await gcs2.handle_kv_get(ns="t", key="k0") is None
            assert 3 in gcs2.jobs
            # compaction: a forced full snapshot truncates the WAL
            gcs2._write_snapshot()
            gcs2._wal_truncate()
            assert not os.path.exists(gcs2._wal_path())

        loop.run_until_complete(phase2())
        loop.close()
    finally:
        config.reload()
