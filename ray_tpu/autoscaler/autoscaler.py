"""Autoscaler reconciler: demand in, node launches/terminations out.

Reference: ``python/ray/autoscaler/v2/autoscaler.py:42`` (reconciler over
an instance manager) and the bin-packing demand logic of
``autoscaler/_private/resource_demand_scheduler.py``.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private.rpc import RpcClient, run_sync
from ray_tpu.autoscaler.instance_manager import InstanceManager, InstanceState
from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class NodeTypeConfig:
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig] = dataclasses.field(default_factory=dict)
    idle_timeout_s: float = 60.0
    upscale_interval_s: float = 2.0
    max_launches_per_round: int = 4
    # slice-reclaim guard: an instance whose member nodes host a PLACED
    # (or mid-preemption) gang at or above this priority is NEVER
    # idle-reclaimed — the gang's reservation is a commitment even while
    # its workers are momentarily between leases (restart window).
    # Default 0: any gang pins its slice.
    reclaim_priority: int = 0


def _fits(demand: Dict[str, float], resources: Dict[str, float]) -> bool:
    return all(resources.get(k, 0.0) >= v for k, v in demand.items())


class Autoscaler:
    """Reconciler over an InstanceManager (the v2 design): demand and
    min/max intents become instance REQUESTs; idleness becomes DRAINING;
    the instance manager converges records with provider reality."""

    def __init__(self, gcs_addr: str, provider: NodeProvider,
                 config: AutoscalerConfig):
        self.gcs_addr = gcs_addr
        self.provider = provider
        self.config = config
        self.instance_manager = InstanceManager(
            provider, drain_node_fn=self._drain_node)
        self._idle_since: Dict[str, float] = {}
        self._failure_reported: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one reconcile round ------------------------------------------------

    def _get_nodes(self) -> List[Dict[str, Any]]:
        async def go():
            c = RpcClient(self.gcs_addr)
            try:
                return await c.call("get_all_nodes")
            finally:
                await c.close()

        return run_sync(go())

    def _get_gangs(self) -> List[Dict[str, Any]]:
        """The GCS gang table (best-effort: an autoscaler must keep
        reconciling node demand even when the gang verb is unavailable,
        e.g. against an older head)."""
        async def go():
            c = RpcClient(self.gcs_addr)
            try:
                return await c.call("list_gangs", timeout=5.0)
            finally:
                await c.close()

        try:
            return run_sync(go()) or []
        except Exception:  # noqa: BLE001
            logger.debug("list_gangs failed", exc_info=True)
            return []

    @staticmethod
    def _gang_bundles(gang: Dict[str, Any]) -> List[Dict[str, float]]:
        return [dict(b) for b in gang.get("bundles") or ()]

    def _drain_node(self, node_id: str, reason: str,
                    deadline_s: Optional[float]):
        """Instance drains go through the cluster drain protocol: the GCS
        broadcasts node_draining, schedulers soft-avoid the node, and
        train/serve consumers checkpoint/migrate before the terminate."""
        async def go():
            c = RpcClient(self.gcs_addr)
            try:
                return await c.call("drain_node", node_id=node_id,
                                    reason=reason, deadline_s=deadline_s,
                                    timeout=5.0)
            finally:
                await c.close()

        run_sync(go())

    def _report_dead_instances(self, im) -> None:
        """Report member nodes of provider-died instances to the GCS as
        FINAL deaths (observed hardware loss, not a heartbeat blip)."""
        for inst in im.by_state(InstanceState.FAILED):
            if inst.instance_id in self._failure_reported:
                continue
            if not inst.node_ids or "died" not in (inst.failure or ""):
                continue
            self._failure_reported.add(inst.instance_id)

            async def go(node_ids=list(inst.node_ids),
                         cause=f"instance {inst.instance_id}: "
                               f"{inst.failure}"):
                c = RpcClient(self.gcs_addr)
                try:
                    for nid in node_ids:
                        await c.call("report_node_failure", node_id=nid,
                                     reason=cause, timeout=5.0)
                finally:
                    await c.close()

            try:
                run_sync(go())
            except Exception:  # noqa: BLE001 — retried next round
                self._failure_reported.discard(inst.instance_id)
                logger.debug("report_node_failure failed", exc_info=True)

    def reconcile_once(self) -> Dict[str, Any]:
        """Returns a summary of the decisions taken this round."""
        im = self.instance_manager
        nodes = [n for n in self._get_nodes() if n.get("alive")]
        alive_ids = {n["node_id"] for n in nodes}
        launched: List[str] = []
        terminated: List[str] = []

        # 0. converge existing instances with provider/cluster reality
        im.reconcile(alive_ids)
        # provider-observed deaths are FINAL: report member nodes so the
        # GCS fate-shares their gangs now (no heartbeat-timeout wait)
        # and refuses resurrection from a lingering raylet process
        self._report_dead_instances(im)

        # 1. unmet demand: pending shapes that fit NO alive node's total.
        #    Pending GANGS contribute their bundle shapes too — a
        #    STRICT_PACK_SLICE gang waiting for a slice that does not
        #    exist yet is exactly the demand whole-slice provisioning
        #    answers (one instance = every host of the slice).
        gangs = self._get_gangs()
        demand: List[Dict[str, float]] = []
        for n in nodes:
            demand.extend(n.get("pending_demand", []))
        for g in gangs:
            if g.get("state") in ("PENDING", "RESERVING"):
                demand.extend(self._gang_bundles(g))
        # QUARANTINED nodes still heartbeat (drain in progress) but the
        # scheduler refuses them — their capacity must not satisfy
        # demand here, or the replacement for a quarantined straggler
        # would never be provisioned
        schedulable = [n for n in nodes
                       if n.get("health") != "QUARANTINED"]
        unmet = [d for d in demand
                 if not any(_fits(d, n["total"]) for n in schedulable)]
        # plus shapes that fit somewhere but everything is saturated: any
        # pending demand at all means the cluster is short on slots
        congested = [d for d in demand if d not in unmet]

        # 2. active capacity per type (REQUESTED/LAUNCHING count so one
        #    demand burst can't over-request while instances come up)
        per_type = {t: 0 for t in self.config.node_types}
        per_type.update(im.count_by_type())

        # 3. scale up: min_workers first, then demand-driven bin packing
        budget = self.config.max_launches_per_round
        for t, cfg in self.config.node_types.items():
            while per_type.get(t, 0) < cfg.min_workers and budget > 0:
                im.request(t, cfg.resources, cfg.labels)
                per_type[t] = per_type.get(t, 0) + 1
                budget -= 1
                launched.append(t)
        # launch-in-flight gate: while an instance is still coming up its
        # capacity isn't visible in heartbeats — requesting again for the
        # same (still-pending) demand would overshoot to max_workers
        joining = im.by_state(InstanceState.REQUESTED,
                              InstanceState.LAUNCHING)
        if joining:
            im.reconcile(alive_ids)  # kick REQUESTED -> LAUNCHING now
            return {"launched": launched, "terminated": terminated,
                    "unmet_demand": len(unmet), "pending": len(demand),
                    "joining": len(joining),
                    "instances": im.summary()}
        for d in unmet + congested:
            if budget <= 0:
                break
            # smallest node type that fits the shape
            candidates = sorted(
                ((t, cfg) for t, cfg in self.config.node_types.items()
                 if _fits(d, cfg.resources)
                 and per_type.get(t, 0) < cfg.max_workers),
                key=lambda tc: sum(tc[1].resources.values()))
            if candidates:
                t, cfg = candidates[0]
                im.request(t, cfg.resources, cfg.labels)
                per_type[t] = per_type.get(t, 0) + 1
                budget -= 1
                launched.append(t)

        # 4. scale down: RUNNING instances idle past the timeout drain
        #    (idle = every member node fully available, no pending demand)
        now = time.monotonic()
        by_node_id = {n["node_id"]: n for n in nodes}
        # reclaim guard: nodes hosting (or claimed by) a gang at or
        # above reclaim_priority pin their whole instance — a slice
        # carrying a PLACED gang must never be idle-reclaimed out from
        # under it, even during a restart window between leases
        pinned_nodes: set = set()
        for g in gangs:
            if g.get("state") not in ("PLACED", "PREEMPTING", "RESERVING"):
                continue
            if g.get("priority", 0) < self.config.reclaim_priority:
                continue
            pinned_nodes.update(g.get("placement") or ())
            pinned_nodes.update(g.get("claim_nodes") or ())
        for inst in im.by_state(InstanceState.RUNNING):
            cfg = self.config.node_types.get(inst.node_type)
            members = [by_node_id.get(nid) for nid in inst.node_ids]
            idle = (not demand
                    and not (pinned_nodes & set(inst.node_ids))
                    and all(m is not None and m["available"] == m["total"]
                            for m in members))
            if not idle:
                self._idle_since.pop(inst.instance_id, None)
                continue
            first = self._idle_since.setdefault(inst.instance_id, now)
            above_min = (cfg is None
                         or per_type.get(inst.node_type, 0) > cfg.min_workers)
            if now - first >= self.config.idle_timeout_s and above_min:
                # broadcast a deadline the terminate path actually
                # honors: the provider SIGKILLs ~10s after SIGTERM, so
                # advertising the 30s protocol default would promise
                # consumers a window that does not exist.  The node is
                # idle by precondition, so the short window is real
                # slack, not lost work.
                im.drain(inst, deadline_s=10.0)
                self._idle_since.pop(inst.instance_id, None)
                per_type[inst.node_type] = per_type.get(
                    inst.node_type, 1) - 1
                terminated.append(inst.provider_id or inst.instance_id)
        im.reconcile(alive_ids)  # apply new REQUESTs + DRAIN terminations
        return {"launched": launched, "terminated": terminated,
                "unmet_demand": len(unmet), "pending": len(demand),
                "instances": im.summary()}

    # -- loop ---------------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-autoscaler")
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.reconcile_once()
            except Exception:
                logger.debug("reconcile failed", exc_info=True)
            self._stop.wait(self.config.upscale_interval_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
