"""Scalability envelope: the reference's stress matrix scaled to one host.

Reference rows (``release/benchmarks/README.md:9-31`` +
``release/perf_metrics/scalability/single_node.json``): 1M queued tasks,
10k object args, 3k returns, 10k-object ``ray.get``, 100 GiB objects, 40k
actors, PG churn.  This driver runs the same shapes scaled to the CI box
(1 vCPU) with pass/fail gates; numbers land in ``benchmarks/README.md``
next to the reference's.

    python benchmarks/envelope.py [--quick] [--only SECTION,...]

Sections: queued_tasks, actors, many_objects, task_args, task_returns,
big_object, pg_churn.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu._private.bench_emit import emit_final_record


def _timer():
    t0 = time.perf_counter()
    return lambda: time.perf_counter() - t0


def bench_queued_tasks(ray_tpu, n: int) -> dict:
    """Submit ``n`` trivial tasks as fast as possible (they queue far ahead
    of the 1-core execution), then drain them all.  Gates: submission must
    stay O(1) per task and the queue must drain without error."""

    @ray_tpu.remote
    def nop():
        return 1

    el = _timer()
    refs = [nop.remote() for _ in range(n)]
    submit_s = el()
    el = _timer()
    total = sum(ray_tpu.get(refs))
    drain_s = el()
    assert total == n
    return {"n": n, "submit_s": round(submit_s, 2),
            "submit_per_s": round(n / submit_s, 0),
            "drain_s": round(drain_s, 2),
            "end_to_end_per_s": round(n / (submit_s + drain_s), 0)}


def bench_actors(ray_tpu, n: int) -> dict:
    """``n`` live actor processes at once (reference: 40k across a
    cluster; scaled).  Gates: all respond to a ping; creation rate
    recorded."""

    @ray_tpu.remote
    class A:
        def ping(self):
            return os.getpid()

    actors = []
    try:
        el = _timer()
        actors = [A.remote() for _ in range(n)]
        # budget scales with n: worker spawn pays a full interpreter
        # start (~2.4 s, serial on 1 vCPU) per actor
        pids = ray_tpu.get([a.ping.remote() for a in actors],
                           timeout=max(1200, n * 8))
        create_s = el()
        assert len(set(pids)) == n, f"{len(set(pids))} distinct actor procs"
        el = _timer()
        ray_tpu.get([a.ping.remote() for a in actors], timeout=600)
        ping_s = el()
        return {"n": n, "create_s": round(create_s, 1),
                "actors_per_s": round(n / create_s, 1),
                "ping_all_s": round(ping_s, 2)}
    finally:
        # ALWAYS reap: a thousand live actor processes would poison every
        # later section (and the box) on failure
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass


def bench_many_objects(ray_tpu, n: int) -> dict:
    """``ray.get`` on ``n`` objects at once (reference single_node:
    10k objects in 24.09 s)."""
    el = _timer()
    refs = [ray_tpu.put(np.full(256, i, np.int64)) for i in range(n)]
    put_s = el()
    el = _timer()
    vals = ray_tpu.get(refs, timeout=600)
    get_s = el()
    assert int(vals[n - 1][0]) == n - 1
    return {"n": n, "put_s": round(put_s, 2), "get_s": round(get_s, 2),
            "get_per_s": round(n / get_s, 0)}


def bench_task_args(ray_tpu, n: int) -> dict:
    """One task taking ``n`` ObjectRef args (reference: 10k args in
    18.76 s)."""

    @ray_tpu.remote
    def consume(*parts):
        return sum(int(p[0]) for p in parts)

    refs = [ray_tpu.put(np.full(8, i, np.int64)) for i in range(n)]
    el = _timer()
    out = ray_tpu.get(consume.remote(*refs), timeout=600)
    run_s = el()
    assert out == n * (n - 1) // 2
    return {"n": n, "s": round(run_s, 2)}


def bench_task_returns(ray_tpu, n: int) -> dict:
    """One task returning ``n`` values (reference: 3k returns in 5.84 s)."""

    @ray_tpu.remote(num_returns=n)
    def produce():
        return list(range(n))

    el = _timer()
    refs = produce.remote()
    vals = ray_tpu.get(refs, timeout=600)
    run_s = el()
    assert vals[-1] == n - 1
    return {"n": n, "s": round(run_s, 2)}


def bench_big_object(ray_tpu, gib: float) -> dict:
    """A multi-GiB object end-to-end — exceeds the arena, lands in
    segments/spill, reads back intact (reference: 100 GiB ray.get)."""
    nbytes = int(gib * 1024**3)
    arr = np.empty(nbytes, np.uint8)
    arr[::4096] = 7  # touch pages; avoid 3 GiB of rand
    el = _timer()
    ref = ray_tpu.put(arr)
    put_s = el()
    del arr
    el = _timer()
    out = ray_tpu.get(ref)
    get_s = el()
    assert out.nbytes == nbytes and int(out[4096]) == 7
    del out
    return {"gib": gib, "put_s": round(put_s, 2),
            "put_gib_s": round(gib / put_s, 2),
            "get_s": round(get_s, 2),
            "get_gib_s": round(gib / get_s, 2)}


def bench_pg_churn(ray_tpu, n: int) -> dict:
    """Create+ready+remove ``n`` placement groups (reference stress:
    1.52 ms create / 1.23 ms remove; nightly many_pgs 13.7 PGs/s)."""
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    el = _timer()
    for _ in range(n):
        pg = placement_group([{"CPU": 1}])
        ray_tpu.get(pg.ready(), timeout=60)
        remove_placement_group(pg)
    s = el()
    return {"n": n, "s": round(s, 2), "pgs_per_s": round(n / s, 1)}


# full sizes == the reference's single-node envelope
# (release/benchmarks/README.md:27-31: 10k args, 3k returns, 10k-object
# get, 1M queued tasks; 100 GiB object is RAM-bound — 10 GiB here
# proves the same arena->segment->spill path on this 125 GB box)
SECTIONS = {
    "queued_tasks": (bench_queued_tasks, 1_000_000, 10_000),
    "actors": (bench_actors, 1_000, 100),
    "many_objects": (bench_many_objects, 10_000, 2_000),
    "task_args": (bench_task_args, 10_000, 200),
    "task_returns": (bench_task_returns, 3_000, 200),
    "big_object": (bench_big_object, 10.0, 1.0),
    "pg_churn": (bench_pg_churn, 200, 30),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="reduced sizes (CI smoke)")
    ap.add_argument("--only", default="",
                    help="comma-separated section subset")
    args = ap.parse_args()
    only = set(filter(None, args.only.split(",")))

    # a 1k-actor creation burst spawns worker processes serially (~2.4 s
    # interpreter start on this box); callers must wait out the burst
    os.environ.setdefault("RAY_TPU_ACTOR_RESOLVE_TIMEOUT_S", "3600")

    import ray_tpu

    ray_tpu.init(num_cpus=16, num_tpus=0)
    results = {}
    failures = {}
    try:
        for name, (fn, full, quick) in SECTIONS.items():
            if only and name not in only:
                continue
            size = quick if args.quick else full
            t0 = time.perf_counter()
            try:
                results[name] = fn(ray_tpu, size)
                results[name]["wall_s"] = round(
                    time.perf_counter() - t0, 1)
                print(f"[envelope] {name}: {results[name]}",
                      file=sys.stderr)
            except BaseException as e:  # noqa: BLE001 - keep going, report
                failures[name] = repr(e)[:500]
                print(f"[envelope] {name} FAILED: {e!r}", file=sys.stderr)
    finally:
        ray_tpu.shutdown()
    emit_final_record({"benchmark": "scalability_envelope",
                       "results": results, "failures": failures,
                       "quick": args.quick})
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
