"""Autoscaler reconciler: demand in, node launches/terminations out.

Reference: ``python/ray/autoscaler/v2/autoscaler.py:42`` (reconciler over
an instance manager) and the bin-packing demand logic of
``autoscaler/_private/resource_demand_scheduler.py``.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private.rpc import RpcClient, run_sync
from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class NodeTypeConfig:
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig] = dataclasses.field(default_factory=dict)
    idle_timeout_s: float = 60.0
    upscale_interval_s: float = 2.0
    max_launches_per_round: int = 4


def _fits(demand: Dict[str, float], resources: Dict[str, float]) -> bool:
    return all(resources.get(k, 0.0) >= v for k, v in demand.items())


class Autoscaler:
    def __init__(self, gcs_addr: str, provider: NodeProvider,
                 config: AutoscalerConfig):
        self.gcs_addr = gcs_addr
        self.provider = provider
        self.config = config
        self._idle_since: Dict[str, float] = {}
        self._launched_for: Dict[str, str] = {}  # provider id -> node type
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one reconcile round ------------------------------------------------

    def _get_nodes(self) -> List[Dict[str, Any]]:
        async def go():
            c = RpcClient(self.gcs_addr)
            try:
                return await c.call("get_all_nodes")
            finally:
                await c.close()

        return run_sync(go())

    def reconcile_once(self) -> Dict[str, Any]:
        """Returns a summary of the decisions taken this round."""
        nodes = [n for n in self._get_nodes() if n.get("alive")]
        launched: List[str] = []
        terminated: List[str] = []

        # 1. unmet demand: pending shapes that fit NO alive node's total
        demand: List[Dict[str, float]] = []
        for n in nodes:
            demand.extend(n.get("pending_demand", []))
        unmet = [d for d in demand
                 if not any(_fits(d, n["total"]) for n in nodes)]
        # plus shapes that fit somewhere but everything is saturated: any
        # pending demand at all means the cluster is short on slots
        congested = [d for d in demand if d not in unmet]

        # 2. count current workers per type
        per_type: Dict[str, int] = {t: 0 for t in self.config.node_types}
        for pid in self.provider.non_terminated_nodes():
            t = self._launched_for.get(pid)
            if t in per_type:
                per_type[t] += 1

        # 3. scale up: min_workers first, then demand-driven bin packing
        budget = self.config.max_launches_per_round
        for t, cfg in self.config.node_types.items():
            while per_type[t] < cfg.min_workers and budget > 0:
                self._launch(t, cfg)
                per_type[t] += 1
                budget -= 1
                launched.append(t)
        # launch-in-flight gate: while a launched node hasn't registered and
        # heartbeated yet, its capacity isn't visible — launching again for
        # the same (still-pending) demand would overshoot to max_workers
        alive_ids = {n["node_id"] for n in nodes}
        joining = [pid for pid in self.provider.non_terminated_nodes()
                   if pid in self._launched_for
                   and self.provider.node_id_of(pid) not in alive_ids]
        if joining:
            return {"launched": launched, "terminated": terminated,
                    "unmet_demand": len(unmet), "pending": len(demand),
                    "joining": len(joining)}
        for d in unmet + congested:
            if budget <= 0:
                break
            # smallest node type that fits the shape
            candidates = sorted(
                ((t, cfg) for t, cfg in self.config.node_types.items()
                 if _fits(d, cfg.resources) and per_type[t] < cfg.max_workers),
                key=lambda tc: sum(tc[1].resources.values()))
            if candidates:
                t, cfg = candidates[0]
                self._launch(t, cfg)
                per_type[t] += 1
                budget -= 1
                launched.append(t)

        # 4. scale down: autoscaler-launched nodes idle past the timeout
        #    (idle = fully available and no pending demand anywhere)
        now = time.monotonic()
        by_node_id = {self.provider.node_id_of(pid): pid
                      for pid in self.provider.non_terminated_nodes()}
        for n in nodes:
            pid = by_node_id.get(n["node_id"])
            if pid is None:
                continue
            t = self._launched_for.get(pid)
            if t is None:
                # unknown provenance (pre-existing node, or an autoscaler
                # restart lost the launch map): never terminate it
                continue
            cfg = self.config.node_types.get(t)
            idle = (not demand and n["available"] == n["total"])
            if not idle:
                self._idle_since.pop(pid, None)
                continue
            first = self._idle_since.setdefault(pid, now)
            above_min = (cfg is None
                         or per_type.get(t, 0) > cfg.min_workers)
            if now - first >= self.config.idle_timeout_s and above_min:
                logger.info("terminating idle node %s (%s)", pid, t)
                self.provider.terminate_node(pid)
                self._idle_since.pop(pid, None)
                if t in per_type:
                    per_type[t] -= 1
                terminated.append(pid)
        return {"launched": launched, "terminated": terminated,
                "unmet_demand": len(unmet), "pending": len(demand)}

    def _launch(self, node_type: str, cfg: NodeTypeConfig):
        logger.info("launching node of type %s", node_type)
        pid = self.provider.create_node(node_type, dict(cfg.resources),
                                       dict(cfg.labels))
        self._launched_for[pid] = node_type

    # -- loop ---------------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-autoscaler")
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.reconcile_once()
            except Exception:
                logger.debug("reconcile failed", exc_info=True)
            self._stop.wait(self.config.upscale_interval_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
