"""Collective supervision: flight recorder, watchdog threads, abort.

The spine that turns a silent collective hang into an attributable,
recoverable failure (reference: PyTorch distributed's NCCL watchdog +
``TORCH_NCCL_TRACE_BUFFER`` flight recorder; MegaScale §hang detection):

- every op on every member gets a monotonically increasing **sequence
  number** and a bounded in-memory **flight recorder** entry
  (seq, op, group, rank, shape/dtype, t_start, t_end, status);
- a per-group **watchdog thread** aborts the group when an op exceeds the
  configured ``timeout_s`` (group init option, ``RAY_TPU_COLLECTIVE_TIMEOUT``
  env, or the ``collective_op_timeout_s`` config flag), when a GCS node or
  actor **death** covers a member, or when a member's node **drain**
  deadline expires with an op still in flight (a drain alone never aborts
  an idle group — the train controller's graceful checkpoint leg runs
  first, see docs/fault_tolerance.md);
- ``abort()`` closes the transport under any blocked op, marks the group
  ``ABORTED``, and makes current and future ops raise
  :class:`~ray_tpu.exceptions.CollectiveAbortError` carrying the
  diagnosis of which rank/seq is behind;
- the watchdog heartbeats each member's progress (state, last completed
  seq, in-flight op) into the GCS KV so ``util.state.
  list_collective_groups``, ``raytpu status``, and the dashboard's
  collective panel can show group health cluster-wide.

``destroy_group`` + ``init_collective_group`` on an aborted group is the
supported re-init path: rendezvous keys are epoch-versioned, so a
re-formed group can never connect to a stale leader.
"""

from __future__ import annotations

import functools
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.exceptions import CollectiveAbortError
from ray_tpu.util.collective.types import GroupState, ReduceOp
from ray_tpu.util.fault_injection import fault_point

logger = logging.getLogger(__name__)

ENV_TIMEOUT = "RAY_TPU_COLLECTIVE_TIMEOUT"
ENV_TRACE_BUFFER = "RAY_TPU_COLLECTIVE_TRACE_BUFFER"

# errors meaning the transport under a collective died (peer/leader gone,
# watchdog closed the socket, rendezvous KV vanished) — any of these
# mid-op aborts the group; application errors (bad shapes caught before
# dispatch, unknown ops) surface as themselves
_TRANSPORT_ERRS = (ConnectionError, OSError, EOFError, TimeoutError)


def resolve_timeout(timeout_s: Optional[float] = None) -> float:
    """Effective per-op timeout: explicit arg > ``RAY_TPU_COLLECTIVE_TIMEOUT``
    env > ``collective_op_timeout_s`` config flag."""
    if timeout_s is not None:
        return float(timeout_s)
    env = os.environ.get(ENV_TIMEOUT)
    if env:
        return float(env)
    from ray_tpu._private.config import config

    return float(config.collective_op_timeout_s)


def _shape_of(t) -> Optional[tuple]:
    s = getattr(t, "shape", None)
    if s is None:
        return None
    try:
        return tuple(s)
    except TypeError:
        return None


def _dtype_of(t) -> Optional[str]:
    d = getattr(t, "dtype", None)
    return str(d) if d is not None else None


class FlightRecorder:
    """Process-wide bounded per-group trace of collective ops."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._by_group: Dict[str, deque] = {}

    def start(self, group: str, rank: int, op: str, seq: int,
              shape, dtype) -> Dict[str, Any]:
        entry = {
            "group": group, "rank": rank, "op": op, "seq": seq,
            "shape": shape, "dtype": dtype,
            "t_start": time.time(), "t_end": None, "status": "in_flight",
        }
        with self._lock:
            q = self._by_group.setdefault(group, deque(maxlen=self.capacity))
            q.append(entry)
        return entry

    def finish(self, entry: Dict[str, Any], status: str) -> None:
        entry["t_end"] = time.time()
        entry["status"] = status

    def dump(self, group_name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            if group_name is not None:
                return [dict(e) for e in self._by_group.get(group_name, ())]
            out: List[Dict[str, Any]] = []
            for q in self._by_group.values():
                out.extend(dict(e) for e in q)
            return out

    def drop(self, group_name: str) -> None:
        with self._lock:
            self._by_group.pop(group_name, None)


_recorder = FlightRecorder(int(os.environ.get(ENV_TRACE_BUFFER, "256") or 256))


def flight_recorder_dump(group_name: Optional[str] = None
                         ) -> List[Dict[str, Any]]:
    """This process's flight-recorder entries (all groups, or one)."""
    return _recorder.dump(group_name)


def format_flight_tail(group_name: str, n: int = 8) -> str:
    """Human-readable tail of the recorder for abort diagnoses/logs."""
    entries = _recorder.dump(group_name)[-n:]
    if not entries:
        return "  (flight recorder empty)"
    lines = []
    for e in entries:
        dur = (f"{(e['t_end'] - e['t_start']) * 1000:.1f}ms"
               if e["t_end"] else
               f"in flight {time.time() - e['t_start']:.1f}s")
        lines.append(
            f"  seq={e['seq']} op={e['op']} rank={e['rank']} "
            f"shape={e['shape']} dtype={e['dtype']} "
            f"status={e['status']} ({dur})")
    return "\n".join(lines)


def _status_key(group_name: str, rank: int) -> bytes:
    return f"collective/{group_name}/status/{rank}".encode()


def parse_rendezvous_entry(raw: bytes) -> Dict[str, Any]:
    """Decode an epoch-versioned rendezvous entry (``{"epoch", "addr"}``)
    — the ONE parser behind the TCP leader key and the XLA coordinator
    key, tolerating the pre-epoch bare-address format."""
    try:
        entry = json.loads(raw)
        if isinstance(entry, dict) and "addr" in entry:
            entry.setdefault("epoch", 0)
            return entry
    except ValueError:
        pass
    return {"epoch": 0, "addr": raw.decode()}


def drop_group_status_keys(group_name: str) -> None:
    """Sweep a group's member status records — a new incarnation's
    leader calls this after bumping the epoch so ghosts of ranks that
    died without cleanup (their keys linger forever otherwise) cannot
    haunt the re-formed group's membership view or death checks."""
    try:
        from ray_tpu.experimental import internal_kv

        prefix = f"collective/{group_name}/status/"
        for k in internal_kv._internal_kv_list(prefix,
                                               namespace="collective"):
            key = k if isinstance(k, str) else k.decode()
            internal_kv._internal_kv_del(key.encode(),
                                         namespace="collective")
    except Exception:  # noqa: BLE001 — best-effort hygiene
        pass


def drop_group_keys(group_name: str) -> None:
    """Best-effort sweep of a group's KV footprint (leader/coordinator
    entries, member status records, unconsumed p2p payloads).  The epoch
    COUNTER is deliberately preserved: a straggler from a failed or
    destroyed generation may still be polling rendezvous — if the counter
    reset, the name's next incarnation would restart at epoch 1 and the
    straggler would pass the epoch check and join it as a cross-
    generation duplicate rank."""
    try:
        from ray_tpu.experimental import internal_kv

        prefix = f"collective/{group_name}/"
        epoch_key = f"{prefix}epoch"
        for k in internal_kv._internal_kv_list(prefix,
                                               namespace="collective"):
            key = k if isinstance(k, str) else k.decode()
            if key == epoch_key:
                continue
            internal_kv._internal_kv_del(key.encode(),
                                         namespace="collective")
    except Exception:  # noqa: BLE001 — cluster may already be down
        pass


def aggregate_status_records(records) -> List[Dict[str, Any]]:
    """Fold per-member status records (the watchdog KV heartbeats) into
    per-group summaries — the ONE aggregation behind
    ``util.state.list_collective_groups``, ``raytpu status``, and the
    dashboard's ``/api/collective`` panel, so the three surfaces can
    never drift apart on schema or state-promotion rules."""
    # ghosts first: records of a dead incarnation that escaped the
    # leader's sweep must not merge into (or ABORT-promote) the current
    # epoch's summary
    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("group_name"):
            by_name.setdefault(rec["group_name"], []).append(rec)
    records = []
    for recs in by_name.values():
        top = max(r.get("epoch", 0) for r in recs)
        records.extend(r for r in recs if r.get("epoch", 0) == top)
    groups: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        name = rec.get("group_name")
        if not name:
            continue
        g = groups.setdefault(name, {
            "group_name": name,
            "world_size": rec.get("world_size"),
            "backend": rec.get("backend", ""),
            "epoch": rec.get("epoch", 0),
            "state": "READY",
            "members": [],
        })
        g["members"].append(rec)
        g["epoch"] = max(g["epoch"], rec.get("epoch", 0))
        if rec.get("state") == "ABORTED":
            g["state"] = "ABORTED"
            if rec.get("abort_reason"):
                g["abort_reason"] = rec["abort_reason"]
    for g in groups.values():
        g["members"].sort(key=lambda m: m.get("rank") or 0)
        g["joined"] = len(g["members"])
    return sorted(groups.values(), key=lambda g: g["group_name"])


def _note_op_span(group: "SupervisedGroup", op: str,
                  entry: Dict[str, Any]) -> None:
    """Flight-recorder entry → trace span + ``collective_wait`` ledger
    time.  Runs in the op's finally (success AND failure paths) so a hung
    op that finally aborts still shows its full wall time in the trace."""
    try:
        from ray_tpu._private import tracing

        t0 = entry["t_start"]
        t1 = time.time()
        tracing.note_duration("collective_wait", t1 - t0)
        if not tracing.is_enabled():
            return
        ctx = tracing.current_or_root().child()
        tracing.record_span(
            f"collective.{op}", t0, t1, ctx, kind="collective",
            attrs={"group": group.group_name, "rank": group.rank,
                   "seq": entry.get("seq"),
                   "shape": str(entry.get("shape"))})
    except Exception:  # noqa: BLE001 — tracing must never fail an op
        pass


def _supervised(fn):
    """Route a group op through the supervision spine (seq number, flight
    recorder, ``collective.op`` fault site, abort-aware error mapping)."""

    @functools.wraps(fn)
    def wrapper(self: "SupervisedGroup", *args, **kwargs):
        return self._execute(fn.__name__, fn, args, kwargs)

    wrapper.__supervised__ = True
    return wrapper


class SupervisedGroup:
    """Wraps a backend group (TCP/XLA) with the supervision spine.

    Every op: sequence number + flight-recorder entry + the
    ``collective.op`` fault site; transport failures and watchdog aborts
    surface as ``CollectiveAbortError`` with a diagnosis.  A per-group
    :class:`Watchdog` enforces the op timeout and reacts to GCS
    node/actor death and drain events covering members.
    """

    def __init__(self, inner, *, timeout_s: Optional[float] = None,
                 backend: str = ""):
        self._inner = inner
        self._timeout_s = resolve_timeout(timeout_s)
        self._backend = str(backend)
        self._state = GroupState.READY
        self._abort_info: Optional[Dict[str, Any]] = None
        self._seq = 0
        self._last_done_seq = 0  # entry-stamped seq of the last success
        self._lock = threading.Lock()
        self._inflight: Optional[Dict[str, Any]] = None
        # identity captured NOW, in the joining task's context — the
        # watchdog thread has no execution context to read it from later
        self._self_node_id = ""
        self._self_actor_id = ""
        try:
            from ray_tpu.runtime_context import get_runtime_context

            ctx = get_runtime_context()
            self._self_node_id = ctx.get_node_id() or ""
            self._self_actor_id = ctx.get_actor_id() or ""
        except Exception:  # noqa: BLE001 — standalone (no cluster) use
            pass
        self._publish_status()
        self._watchdog = Watchdog(self)
        self._watchdog.start()

    # -- delegated identity -------------------------------------------------
    @property
    def rank(self) -> int:
        return self._inner.rank

    @property
    def world_size(self) -> int:
        return self._inner.world_size

    @property
    def group_name(self) -> str:
        return self._inner.group_name

    @property
    def state(self) -> GroupState:
        return self._state

    @property
    def timeout_s(self) -> float:
        return self._timeout_s

    def __getattr__(self, name):
        # backend extras (XlaMeshGroup.permute, .mesh, ...) pass through
        if name.startswith("__") or name == "_inner":
            raise AttributeError(name)
        return getattr(self.__dict__["_inner"], name)

    # -- supervised ops -----------------------------------------------------
    # every public collective op routes through _execute (seq + flight
    # recorder + ``collective.op`` site + abort mapping); a tooling test
    # asserts the full BaseGroup op surface carries the marker so a new
    # op cannot silently skip supervision

    @_supervised
    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        return self._inner.allreduce(tensor, op)

    @_supervised
    def barrier(self) -> None:
        return self._inner.barrier()

    @_supervised
    def reduce(self, tensor, dst_rank: int = 0,
               op: ReduceOp = ReduceOp.SUM):
        return self._inner.reduce(tensor, dst_rank, op)

    @_supervised
    def broadcast(self, tensor, src_rank: int = 0):
        return self._inner.broadcast(tensor, src_rank)

    @_supervised
    def allgather(self, tensor):
        return self._inner.allgather(tensor)

    @_supervised
    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        return self._inner.reducescatter(tensor, op)

    @_supervised
    def send(self, tensor, dst_rank: int, tag: int = 0) -> None:
        return self._inner.send(tensor, dst_rank, tag)

    @_supervised
    def recv(self, shape=None, dtype=None, src_rank: int = 0, tag: int = 0):
        return self._inner.recv(shape, dtype, src_rank, tag)

    # -- the spine ----------------------------------------------------------
    def _execute(self, op: str, fn, args, kwargs):
        with self._lock:
            if self._state is not GroupState.READY:
                raise self._abort_error(op, None)
            self._seq += 1
            seq = self._seq
        # stamp collectives with the backend's WIRE seq when it has one
        # (TCP): leader hang/desync diagnoses cite that number, and the
        # two counters diverge once p2p ops (which consume a supervised
        # seq but no wire seq) have run — attribution must match
        if op not in ("send", "recv"):
            proto = getattr(self._inner, "_seq", None)
            if isinstance(proto, int):
                seq = proto + 1
        tensor = args[0] if args else None
        entry = _recorder.start(self.group_name, self.rank, op, seq,
                                _shape_of(tensor), _dtype_of(tensor))
        self._inflight = entry
        try:
            fault_point("collective.op")
            try:
                out = fn(self, *args, **kwargs)
            finally:
                # supervision seq → span event: every op becomes a span in
                # the caller's trace (child of the enclosing task span),
                # and its wall time feeds the step ledger's
                # collective_wait bucket.  One enabled-check when tracing
                # is off; zero behavioral coupling to the op itself.
                _note_op_span(self, op, entry)
            if self._state is GroupState.ABORTED:
                # the watchdog fired while this op was still running and
                # the backend's abort() could not interrupt it (XLA): the
                # group is poisoned cluster-wide, so a locally-completed
                # result must not read as success on this rank only
                _recorder.finish(entry, "aborted")
                raise self._abort_error(op, seq)
            _recorder.finish(entry, "done")
            self._last_done_seq = seq
            return out
        except CollectiveAbortError as e:
            # the backend itself diagnosed the abort (leader broadcast);
            # adopt it so future ops raise too
            _recorder.finish(entry, "aborted")
            self._mark_aborted(e.reason or str(e), diagnosis=e.diagnosis)
            raise
        except BaseException as e:  # noqa: BLE001 — classified below
            if self._state is GroupState.ABORTED:
                # the watchdog aborted while this op was blocked: the
                # transport error is the abort surfacing, not the cause
                _recorder.finish(entry, "aborted")
                raise self._abort_error(op, seq) from e
            if isinstance(e, _TRANSPORT_ERRS):
                self.abort(f"transport failure during {op} seq={seq}: "
                           f"{e!r}")
                _recorder.finish(entry, "aborted")
                raise self._abort_error(op, seq) from e
            _recorder.finish(entry, "error")
            raise
        finally:
            self._inflight = None

    def _abort_error(self, op: str, seq: Optional[int]
                     ) -> CollectiveAbortError:
        info = self._abort_info or {}
        return CollectiveAbortError(
            group_name=self.group_name, rank=self.rank, seq=seq,
            reason=info.get("reason", f"group aborted (op {op} rejected)"),
            diagnosis=info.get("diagnosis", ""))

    def _mark_aborted(self, reason: str, diagnosis: str = "") -> bool:
        with self._lock:
            if self._state is not GroupState.READY:
                return False
            self._state = GroupState.ABORTED
            self._abort_info = {"reason": reason, "diagnosis": diagnosis,
                                "t": time.time()}
        return True

    def abort(self, reason: str, diagnosis: str = "") -> None:
        """Abort the group: close the transport (unblocking any op stuck
        in it), mark ABORTED, dump the flight recorder to logs."""
        if not diagnosis:
            diagnosis = ("flight recorder (this rank):\n"
                         + format_flight_tail(self.group_name))
        if not self._mark_aborted(reason, diagnosis):
            return
        try:
            self._inner.abort(reason)
        except Exception:  # noqa: BLE001 — transport may already be gone
            pass
        logger.error(
            "collective group %r rank %d ABORTED: %s\n%s",
            self.group_name, self.rank, reason, diagnosis)
        self._publish_status()

    # -- lifecycle ----------------------------------------------------------
    def destroy_group(self) -> None:
        with self._lock:
            self._state = GroupState.DESTROYED
        self._watchdog.stop()
        try:
            from ray_tpu.experimental import internal_kv

            internal_kv._internal_kv_del(
                _status_key(self.group_name, self.rank),
                namespace="collective")
        except Exception:  # noqa: BLE001 — cluster may be down
            pass
        _recorder.drop(self.group_name)
        self._inner.destroy_group()

    # -- cluster-visible status ---------------------------------------------
    def _status_record(self) -> Dict[str, Any]:
        inflight = self._inflight
        rec = {
            "group_name": self.group_name,
            "rank": self.rank,
            "world_size": self.world_size,
            "backend": self._backend,
            "epoch": getattr(self._inner, "epoch", 0),
            "state": self._state.value,
            "node_id": self._self_node_id,
            "actor_id": self._self_actor_id,
            "pid": os.getpid(),
            # both numbers come from the SAME entry-stamped sequence the
            # leader's diagnoses and the flight recorder use, so "idle
            # after seq=N" and a peer's "in flight seq=M" are comparable
            "last_done_seq": self._last_done_seq,
            "op_count": self._seq,
            "inflight": ({"op": inflight["op"], "seq": inflight["seq"],
                          "t_start": inflight["t_start"]}
                         if inflight else None),
            "timeout_s": self._timeout_s,
            "t": time.time(),
        }
        if self._abort_info:
            rec["abort_reason"] = self._abort_info["reason"]
        return rec

    def _publish_status(self) -> None:
        if self._state is GroupState.DESTROYED:
            # destroy_group deleted our status key; a late watchdog tick
            # must not resurrect it as a permanent ghost entry
            return
        try:
            from ray_tpu.experimental import internal_kv

            internal_kv._internal_kv_put(
                _status_key(self.group_name, self.rank),
                json.dumps(self._status_record()).encode(),
                namespace="collective")
        except Exception:  # noqa: BLE001 — best-effort surfacing
            pass


class Watchdog(threading.Thread):
    """Per-group supervisor: op-timeout abort, GCS death/drain abort,
    progress heartbeats into the KV.

    The leader's in-server monitor (TCP backend) usually diagnoses first
    and names the lagging rank authoritatively; this thread is the
    member-side backstop that fires even when the leader itself is the
    thing that died — its threshold sits one tick past ``timeout_s`` so
    the richer leader diagnosis wins the race when both are alive.
    """

    def __init__(self, group: SupervisedGroup):
        self._group = group
        self._interval = max(0.25, min(1.0, group.timeout_s / 4.0))
        super().__init__(
            daemon=True, name=f"coll-watchdog-{group.group_name}")
        self._stop_evt = threading.Event()
        self._members: Dict[int, Dict[str, Any]] = {}
        self._members_refreshed = 0.0
        self._last_membership_check = 0.0
        self._last_published: Any = None

    def stop(self) -> None:
        self._stop_evt.set()

    def run(self) -> None:
        g = self._group
        while not self._stop_evt.wait(self._interval):
            if g._state is not GroupState.READY:
                self._heartbeat()
                return
            try:
                entry = g._inflight
                if entry is not None and entry["t_end"] is None:
                    age = time.time() - entry["t_start"]
                    if age > g.timeout_s + 2 * self._interval:
                        g.abort(
                            f"op {entry['op']} seq={entry['seq']} exceeded "
                            f"timeout ({age:.1f}s > {g.timeout_s:.1f}s) "
                            f"with no leader diagnosis — leader "
                            f"unreachable or group desynced",
                            diagnosis=self._peer_diagnosis())
                        continue
                # GCS queries every tick only once the in-flight op is
                # actually SLOW (past half the timeout — attribution is
                # only needed then); healthy back-to-back collectives and
                # idle groups check for member death on a slow cadence so
                # N groups don't stream node/actor-table RPCs at the
                # control plane for the whole run
                now = time.time()
                inflight_slow = (
                    entry is not None and entry["t_end"] is None
                    and now - entry["t_start"] > g.timeout_s / 2.0)
                if (inflight_slow
                        or now - self._last_membership_check >= 5.0):
                    self._last_membership_check = now
                    self._check_membership()
                self._heartbeat()
            except Exception:  # noqa: BLE001 — supervisor must not die
                logger.debug("collective watchdog tick failed",
                             exc_info=True)

    # -- KV heartbeat -------------------------------------------------------
    def _heartbeat(self) -> None:
        g = self._group
        rec = g._status_record()
        fingerprint = (rec["state"], rec["last_done_seq"],
                       bool(rec["inflight"]))
        # publish on change, and periodically while an op is in flight so
        # peers can diagnose who is behind from a fresh record
        if fingerprint != self._last_published or rec["inflight"]:
            self._last_published = fingerprint
            g._publish_status()

    # -- GCS event watching -------------------------------------------------
    def _refresh_members(self) -> None:
        now = time.time()
        if self._members and now - self._members_refreshed < 5.0:
            return
        g = self._group
        try:
            from ray_tpu.experimental import internal_kv

            prefix = f"collective/{g.group_name}/status/"
            for key in internal_kv._internal_kv_list(
                    prefix, namespace="collective"):
                raw = internal_kv._internal_kv_get(
                    key.encode() if isinstance(key, str) else key,
                    namespace="collective")
                if not raw:
                    continue
                rec = json.loads(raw)
                # a record from ANOTHER incarnation (a rank that died
                # without cleanup, or a straggler) must not enter this
                # group's membership view — its dead actor/node would
                # abort a healthy re-formed group
                if rec.get("epoch", 0) != getattr(g._inner, "epoch", 0):
                    continue
                self._members[int(rec["rank"])] = rec
            self._members_refreshed = now
        except Exception:  # noqa: BLE001 — no cluster / KV hiccup
            pass

    def _check_membership(self) -> None:
        """Abort when a GCS node/actor death covers a member, or when a
        member node's drain deadline expires with an op in flight."""
        g = self._group
        self._refresh_members()
        if not self._members:
            return
        try:
            from ray_tpu._private.worker import get_global_worker

            w = get_global_worker()
            nodes = {n["node_id"]: n
                     for n in w.run_coro(w.gcs.call("get_all_nodes"),
                                         timeout=10)}
        except Exception:  # noqa: BLE001 — control-plane hiccup
            return
        now = time.time()
        inflight = g._inflight is not None
        for rank, rec in sorted(self._members.items()):
            if rank == g.rank:
                continue
            nid = rec.get("node_id") or ""
            node = nodes.get(nid)
            if node is None:
                continue
            state = node.get("state",
                             "ALIVE" if node.get("alive") else "DEAD")
            if state == "DEAD":
                why = (node.get("death_reason")
                       or node.get("drain_reason") or "node death")
                g.abort(
                    f"rank {rank} lost: node {nid[:8]} is DEAD ({why})",
                    diagnosis=self._peer_diagnosis())
                return
            if state == "DRAINING" and inflight:
                deadline = node.get("drain_deadline") or 0.0
                if deadline and now >= deadline:
                    g.abort(
                        f"rank {rank} lost to node drain: node {nid[:8]} "
                        f"drain deadline expired "
                        f"({node.get('drain_reason') or 'drain'})",
                        diagnosis=self._peer_diagnosis())
                    return
        # actor death on a still-alive node (SIGKILLed worker): checked
        # less precisely — the TCP leader's conn-loss abort usually wins
        try:
            from ray_tpu._private.worker import get_global_worker

            w = get_global_worker()
            dead = set()
            for a in w.run_coro(w.gcs.call("list_actors"), timeout=10):
                if a.get("state") == "DEAD" and a.get("actor_id"):
                    aid = a["actor_id"]
                    dead.add(aid.hex() if isinstance(aid, bytes) else
                             str(aid))
            for rank, rec in sorted(self._members.items()):
                if rank == g.rank:
                    continue
                if rec.get("actor_id") and rec["actor_id"] in dead:
                    g.abort(f"rank {rank} lost: its actor died",
                            diagnosis=self._peer_diagnosis())
                    return
        except Exception:  # noqa: BLE001
            pass

    def _peer_diagnosis(self) -> str:
        """Who is behind, from the peers' last KV heartbeats + the local
        flight recorder."""
        g = self._group
        lines = [f"flight recorder (rank {g.rank}):",
                 format_flight_tail(g.group_name)]
        self._refresh_members()
        if self._members:
            lines.append("peer progress (last heartbeat):")
            for rank, rec in sorted(self._members.items()):
                inflight = rec.get("inflight")
                where = (f"in flight op={inflight['op']} "
                         f"seq={inflight['seq']}" if inflight
                         else f"idle after seq={rec.get('last_done_seq')}")
                lines.append(
                    f"  rank {rank}: {rec.get('state')} {where} "
                    f"(heartbeat {time.time() - rec.get('t', 0):.1f}s ago)")
        return "\n".join(lines)
