"""LLM serving benchmark: real-chip tokens/s for the BASELINE serving row.

BASELINE.md row: "Serve + Compiled Graph Llama-2-7B TP inference —
tokens/s" (the reference's number comes from vLLM under ray Serve;
``/root/reference/python/ray/llm/_internal/serve/deployments/llm/vllm/``).

Modes:

* ``--mode engine`` (default): the paged-KV engine in-process on the real
  chip — Llama-2-7B shapes, bf16 params, continuous batching.  Reports
  end-to-end generated tokens/s and the decode-only steady-state rate.
* ``--mode serve``: the same engine inside a Serve replica
  (``llm/serving.py``), driven over HTTP with concurrent clients — the
  full serve-path number.  The driver process never imports jax, so the
  replica worker owns the TPU.
* ``--mode openloop``: the disaggregation gate.  Seeded-Poisson
  open-loop traffic (latencies measured from the INTENDED arrival — the
  PR 11 coordinated-omission-aware clock, ``ray_tpu.util.slo``) under a
  long-prompt + many-streams mix, A/B'd across topologies: a colocated
  single replica vs a disaggregated 1-prefill + 1-decode pair shipping
  KV blocks over the tiered channel plane.  Emits
  ``llm_serve_tokens_per_s`` + ``llm_serve_p99_ms`` and gates the record
  on: disaggregated p99 < colocated p99 AND disaggregated tokens/s
  within 10% of colocated.

Usage:  python benchmarks/serving_bench.py [--mode engine|serve|openloop]
        [--model llama2_7b|llama3_8b|tiny] [--slots 8] [--max-len 256]
        [--prompt-len 64] [--max-tokens 64] [--requests 32]
        [--rate 6.0] [--duration 20] [--long-every 8]
"""

from __future__ import annotations

import argparse
import json
import time

import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu._private.bench_emit import emit_final_record


def engine_bench(args) -> dict:
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.llm.engine import LLMEngine
    from ray_tpu.models.generation import SamplingParams
    from ray_tpu.models.llama import LlamaConfig, llama_init

    cfg = getattr(LlamaConfig, args.model)()
    if args.model != "tiny":
        # inference: bf16 weights (f32 7B = 27 GB would not fit one v5e)
        cfg = dataclasses.replace(cfg, param_dtype=jnp.bfloat16,
                                  max_seq_len=args.max_len)
    t0 = time.perf_counter()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    jax.block_until_ready(params)
    init_s = time.perf_counter() - t0
    eng = LLMEngine(cfg, params, batch_slots=args.slots,
                    max_len=args.max_len, block_size=16,
                    kv_cache_dtype=args.kv_dtype or None,
                    spec_tokens=args.spec)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(3, min(cfg.vocab_size, 30000),
                            size=args.prompt_len).tolist()
               for _ in range(args.requests)]
    sp = SamplingParams(temperature=0.0, max_tokens=args.max_tokens)

    # warmup compiles prefill buckets + decode program with DISTINCT
    # prompts, so the timed run's prefix-cache stats reflect the workload,
    # not warmup leftovers
    warm = [rng.integers(3, min(cfg.vocab_size, 30000),
                         size=args.prompt_len).tolist()
            for _ in range(args.slots)]
    eng.generate(warm, sp)
    if args.spec:
        # warm the verify program too (repetitive prompt makes the
        # drafter fire): its compile must not land in a timed phase
        motif_w = rng.integers(3, 1000, size=12).tolist()
        eng.generate([(motif_w * (args.prompt_len // 12 + 1))
                      [:args.prompt_len] for _ in range(2)],
                     SamplingParams(temperature=0.0, max_tokens=24))
    eng.blocks.stats.update(prefix_hits=0, prefix_blocks_reused=0)

    t0 = time.perf_counter()
    outs = eng.generate(prompts, sp)
    wall = time.perf_counter() - t0
    gen = sum(len(o.token_ids) for o in outs)

    # shared-prefix workload (the system-prompt pattern): same system
    # prefix, distinct continuations — measured separately so the hit
    # rate is real, not an artifact
    system = prompts[0][:args.prompt_len - 8]
    shared = [system + rng.integers(3, 1000, size=8).tolist()
              for _ in range(args.slots)]
    t0 = time.perf_counter()
    outs3 = eng.generate(shared, sp)
    shared_wall = time.perf_counter() - t0
    shared_gen = sum(len(o.token_ids) for o in outs3)

    # decode-dominated steady state: all slots decode to the length cap
    long_sp = SamplingParams(
        temperature=0.0,
        max_tokens=args.max_len - args.prompt_len - 2)
    t0 = time.perf_counter()
    outs2 = eng.generate(prompts[:args.slots], long_sp)
    decode_wall = time.perf_counter() - t0
    long_toks = sum(len(o.token_ids) for o in outs2)
    decode_tps = long_toks / decode_wall

    # snapshot BEFORE the spec phase: its repetitive prompts would
    # pollute the main workload's prefix-cache hit stats
    prefix_stats = dict(eng.blocks.stats)

    # speculative phase: REPETITIVE prompts (the extractive/templated
    # pattern prompt-lookup targets) decoded with the drafter off then
    # on, same engine + params — isolates the verify-pass speedup
    spec_block = None
    if args.spec:
        motif = rng.integers(3, 1000, size=12).tolist()
        rep = [(motif * (args.prompt_len // 12 + 1))[:args.prompt_len]
               for _ in range(args.slots)]

        # prefill rep prompts once UNTIMED so both runs start equally
        # warm in the prefix cache — the comparison isolates decode
        eng.generate(rep, SamplingParams(temperature=0.0, max_tokens=1))
        G = eng.G
        eng.G = 0  # drafter off: plain decode window baseline
        t0 = time.perf_counter()
        off_toks = sum(len(o.token_ids)
                       for o in eng.generate(rep, long_sp))
        off_wall = time.perf_counter() - t0
        eng.G = G
        eng.reset_spec_state()
        t0 = time.perf_counter()
        on_toks = sum(len(o.token_ids) for o in eng.generate(rep, long_sp))
        on_wall = time.perf_counter() - t0
        spec_block = {
            "repetitive_decode_tokens_per_s_spec_off":
                round(off_toks / off_wall, 1),
            "repetitive_decode_tokens_per_s_spec_on":
                round(on_toks / on_wall, 1),
            "spec_stats": dict(eng.spec_stats),
        }
    return {
        "mode": "engine", "model": args.model,
        "params_b": round(cfg.num_params() / 1e9, 2),
        "init_s": round(init_s, 1),
        "requests": args.requests, "slots": args.slots,
        "prompt_len": args.prompt_len, "max_tokens": args.max_tokens,
        "generated_tokens": gen,
        "tokens_per_s": round(gen / wall, 1),
        "shared_prefix_tokens_per_s": round(shared_gen / shared_wall, 1),
        "decode_only_tokens_per_s": round(decode_tps, 1),
        "kv_cache_dtype": args.kv_dtype or "bf16",
        "decode_window": eng.K,
        "spec_tokens": args.spec,
        "speculative": spec_block,
        "prefix_cache": prefix_stats,
    }


def serve_bench(args) -> dict:
    """Full serve path: HTTP -> proxy -> replica actor (owns the chip)."""
    import concurrent.futures
    import urllib.request

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm.serving import build_llm_deployment

    # num_tpus given explicitly: the driver must never import jax, or it
    # would claim the chip the replica needs
    ray_tpu.init(num_cpus=4, num_tpus=1)
    try:
        app = build_llm_deployment(
            {"model": args.model, "batch_slots": args.slots,
             "max_len": args.max_len,
             "kv_cache_dtype": args.kv_dtype or None},
            num_tpus_per_replica=1)
        port = 18499
        serve.start(http_options={"host": "127.0.0.1", "port": port,
                                  "request_timeout_s": 900.0})
        serve.run(app, name="llm-bench", route_prefix="/llm")
        url = f"http://127.0.0.1:{port}/llm"
        body = {"prompt": "benchmark " * (args.prompt_len // 2),
                "max_tokens": args.max_tokens, "temperature": 0.0}

        def one():
            req = urllib.request.Request(
                url, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=600) as r:
                return json.loads(r.read())

        one()  # warmup: compile on the replica
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(args.slots * 2) as pool:
            results = list(pool.map(lambda _: one(), range(args.requests)))
        wall = time.perf_counter() - t0
        gen = sum(r["num_generated_tokens"] for r in results)
        return {"mode": "serve", "model": args.model,
                "requests": args.requests,
                "generated_tokens": gen,
                "tokens_per_s": round(gen / wall, 1)}
    finally:
        ray_tpu.shutdown()


def serve_breakdown(args) -> dict:
    """Per-stage serve-path cost isolation (VERDICT r3 weak #3): the same
    workload through each successive layer —

      replica-direct : actor.handle_request (engine loop + actor call;
                       no serve framework at all)
      handle         : serve.run + DeploymentHandle.remote (adds router)
      http           : + HTTP proxy (the full 28.4 tok/s path)

    The deltas attribute the engine->serve collapse to specific layers.
    """
    import concurrent.futures
    import urllib.request

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm.serving import LLMServer

    ray_tpu.init(num_cpus=4, num_tpus=1)
    out: dict = {"mode": "serve-breakdown", "model": args.model,
                 "requests": args.requests}
    body = {"prompt": "benchmark " * (args.prompt_len // 2),
            "max_tokens": args.max_tokens, "temperature": 0.0}
    engine_kwargs = {"model": args.model, "batch_slots": args.slots,
                     "max_len": args.max_len,
                     "kv_cache_dtype": args.kv_dtype or None}
    try:
        # ---- stage 1: replica actor direct (no serve) ----
        from ray_tpu._private import serialization
        from ray_tpu.serve.replica import ReplicaActor

        # max_concurrency mirrors what the serve controller sets
        # (max_ongoing_requests): without it the actor serializes
        # requests and continuous batching never forms
        replica = ReplicaActor.options(
            num_tpus=1, max_concurrency=args.slots * 8).remote(
            serialization.dumps(LLMServer._target),
            (engine_kwargs, 1), {}, None, "bench", "r0")

        def direct_one():
            return ray_tpu.get(replica.handle_request.remote(
                "__call__", (body,), {}), timeout=600)

        def timed(fn):
            """Run the full request set twice; report the SECOND pass —
            the first pass triggers jit compiles for every admission/
            batch arity (compiles are cached cross-process by the
            compile service, so whichever stage runs first would
            otherwise eat them all and skew the layer deltas)."""
            for _ in range(2):
                t0 = time.perf_counter()
                with concurrent.futures.ThreadPoolExecutor(
                        args.slots * 2) as pool:
                    rs = list(pool.map(lambda _: fn(),
                                       range(args.requests)))
                dt = time.perf_counter() - t0
            return sum(r["num_generated_tokens"] for r in rs) / dt

        direct_one()  # compile
        out["replica_direct_tokens_per_s"] = round(timed(direct_one), 1)
        # the ONE chip must be fully released before the serve replica
        # starts: wait for the actor's process to actually exit
        rpid = ray_tpu.get(replica.stats.remote(), timeout=60)["pid"]
        ray_tpu.kill(replica)
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                os.kill(rpid, 0)
                time.sleep(0.5)
            except ProcessLookupError:
                break

        # ---- stage 2: serve handle path (router, no proxy) ----
        from ray_tpu.llm.serving import build_llm_deployment

        app = build_llm_deployment(engine_kwargs, num_tpus_per_replica=1)
        handle = serve.run(app, name="llm-bench", route_prefix="/llm")

        def handle_one():
            return handle.remote(body).result(timeout=600)

        handle_one()  # compile on the serve replica
        out["handle_tokens_per_s"] = round(timed(handle_one), 1)

        # ---- stage 3: full HTTP path ----
        port = 18499
        serve.start(http_options={"host": "127.0.0.1", "port": port,
                                  "request_timeout_s": 900.0})
        url = f"http://127.0.0.1:{port}/llm"

        def http_one():
            req = urllib.request.Request(
                url, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=600) as r:
                return json.loads(r.read())

        http_one()
        out["http_tokens_per_s"] = round(timed(http_one), 1)
        return out
    finally:
        ray_tpu.shutdown()


def _openloop_workload(args, seed: int = 7):
    """Fixed seeded workload shared by both topologies: Poisson intended
    arrivals at ``--rate`` for ``--duration`` seconds; every
    ``--long-every``-th request carries a LONG prompt (the head-of-line
    antagonist), the rest are short streaming requests."""
    import random

    rng = random.Random(seed)
    arrivals = []
    t = 0.0
    while t < args.duration:
        t += rng.expovariate(args.rate)
        if t < args.duration:
            arrivals.append(t)
    long_len = max(args.max_len - args.max_tokens - 8, args.prompt_len)
    reqs = []
    for i, at in enumerate(arrivals):
        if args.long_every and i % args.long_every == args.long_every - 1:
            prompt = [3 + rng.randrange(200) for _ in range(long_len)]
            body = {"prompt": prompt, "max_tokens": 4, "temperature": 0.0}
            kind = "long"
        else:
            prompt = [3 + rng.randrange(200) for _ in range(16)]
            # short streams decode a modest budget: the mix must sit
            # BELOW saturation so the A/B measures head-of-line
            # interference, not backlog dynamics
            body = {"prompt": prompt,
                    "max_tokens": min(args.max_tokens, 16),
                    "temperature": 0.0}
            kind = "short"
        reqs.append((at, kind, body))
    return reqs


def _drive_openloop(call_fn, stream_fn, reqs):
    """Open-loop client: the arrival schedule is fixed up front; a slow
    response never delays later arrivals (pool threads), and latency
    counts from the INTENDED arrival instant (coordinated omission).
    Short requests stream (the many-streams mix); longs are unary.
    Per-request timeouts live inside ``call_fn``/``stream_fn``."""
    import concurrent.futures
    import threading

    samples = []
    lock = threading.Lock()

    def one(intended_wall, kind, body):
        outcome, tokens = "ok", 0
        try:
            if kind == "short" and stream_fn is not None:
                for chunk in stream_fn(body):
                    if chunk.get("done"):
                        tokens = chunk["num_generated_tokens"]
            else:
                tokens = call_fn(body)["num_generated_tokens"]
        except Exception:  # noqa: BLE001 — outcome IS the datum
            outcome = "error"
        now = time.time()
        with lock:
            samples.append({"t": intended_wall, "kind": kind,
                            "latency_s": now - intended_wall,
                            "tokens": tokens, "outcome": outcome})

    width = max(32, int(len(reqs) / 2))
    t0 = time.time()
    with concurrent.futures.ThreadPoolExecutor(width) as pool:
        for at, kind, body in reqs:
            delay = at - (time.time() - t0)
            if delay > 0:
                time.sleep(delay)
            pool.submit(one, t0 + at, kind, body)
    wall = time.time() - t0
    return samples, wall


def _openloop_summary(samples, wall):
    from ray_tpu.util.slo import quantile

    ok = [s for s in samples if s["outcome"] == "ok"]
    lat = [s["latency_s"] for s in ok]
    short = [s["latency_s"] for s in ok if s["kind"] == "short"]
    toks = sum(s["tokens"] for s in ok)
    return {
        "offered": len(samples), "served": len(ok),
        "errors": len(samples) - len(ok),
        "tokens": toks,
        "tokens_per_s": round(toks / wall, 1),
        "p50_ms": round(quantile(lat, 0.50) * 1e3, 1) if lat else None,
        "p99_ms": round(quantile(lat, 0.99) * 1e3, 1) if lat else None,
        "short_p99_ms": round(quantile(short, 0.99) * 1e3, 1)
        if short else None,
        "wall_s": round(wall, 2),
    }


def openloop_bench(args) -> dict:
    """A/B: colocated single replica vs disaggregated 1-prefill +
    1-decode under the same seeded open-loop schedule."""
    os.environ.setdefault("RAY_TPU_ICI_EMULATE", "1")
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.router import DeploymentHandle
    from ray_tpu.llm.serving import (build_disaggregated_llm_deployment,
                                     build_llm_deployment,
                                     disaggregated_handle)

    ray_tpu.init(num_cpus=8, num_tpus=args.num_tpus)
    engine_kwargs = {"model": args.model, "batch_slots": args.slots,
                     "max_len": args.max_len,
                     "kv_cache_dtype": args.kv_dtype or None,
                     "prefill_chunk": 64}
    reqs = _openloop_workload(args)
    warm_long = {"prompt": list(range(3, 3 + args.max_len - 32)),
                 "max_tokens": 4, "temperature": 0.0}
    warm_short = {"prompt": list(range(3, 19)), "max_tokens": 8,
                  "temperature": 0.0}
    out: dict = {"benchmark": "llm_serving_openloop", "model": args.model,
                 "rate_hz": args.rate, "duration_s": args.duration,
                 "long_every": args.long_every,
                 "requests": len(reqs)}
    try:
        # ---- A: colocated single replica --------------------------------
        serve.run(build_llm_deployment(
            engine_kwargs,
            num_tpus_per_replica=args.num_tpus and 1),
            name="colo", route_prefix="/colo")
        handle = DeploymentHandle("LLMServer")
        for body in (warm_short, warm_long):  # compile both bucket sets
            handle.remote(body).result(timeout=300)
        list(handle.stream.remote_streaming(warm_short))

        def colo_call(body):
            return handle.remote(body).result(timeout=args.timeout_s)

        def colo_stream(body):
            yield from handle.stream.remote_streaming(body)

        samples, wall = _drive_openloop(colo_call, colo_stream, reqs)
        out["colocated"] = _openloop_summary(samples, wall)
        serve.delete("LLMServer")

        # ---- B: disaggregated 1 prefill + 1 decode ----------------------
        serve.run(build_disaggregated_llm_deployment(
            engine_kwargs, prefill_replicas=1, decode_replicas=1,
            num_tpus_per_replica=args.num_tpus and 1),
            name="disagg", route_prefix="/llm")
        two = disaggregated_handle()
        for body in (warm_short, warm_long):
            two.call(body, timeout=300)
        list(two.stream(warm_short))

        samples, wall = _drive_openloop(
            lambda b: two.call(b, timeout=args.timeout_s), two.stream,
            reqs)
        out["disaggregated"] = _openloop_summary(samples, wall)
        # shipping-plane evidence: tier + handoff counters from the pools
        try:
            pre = DeploymentHandle("LLMPrefill").stats.remote().result(
                timeout=30)
            out["shipper"] = pre.get("shipper")
            out["handoff"] = pre.get("handoff")
        except Exception:  # noqa: BLE001 — evidence is best-effort
            pass
    finally:
        ray_tpu.shutdown()

    colo, dis = out["colocated"], out["disaggregated"]
    gates = {
        "p99_improves": bool(
            colo["p99_ms"] is not None and dis["p99_ms"] is not None
            and dis["p99_ms"] < colo["p99_ms"]),
        "tokens_within_10pct": bool(
            dis["tokens_per_s"] >= 0.9 * colo["tokens_per_s"]),
        "all_served": dis["errors"] == 0,
    }
    out["gates"] = gates
    out["ok"] = all(gates.values())
    # headline metrics (the parsed record fields)
    out["llm_serve_tokens_per_s"] = dis["tokens_per_s"]
    out["llm_serve_p99_ms"] = dis["p99_ms"]
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="engine",
                    choices=["engine", "serve", "serve-breakdown",
                             "openloop"])
    ap.add_argument("--model", default="llama2_7b")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--prompt-len", type=int, default=64)
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--kv-dtype", default="", choices=["", "int8"],
                    help="int8: half-size KV pool, ~2x slots per chip")
    ap.add_argument("--spec", type=int, default=0,
                    help="prompt-lookup speculative decoding draft length")
    ap.add_argument("--rate", type=float, default=6.0,
                    help="openloop: Poisson arrival rate (req/s)")
    ap.add_argument("--duration", type=float, default=20.0,
                    help="openloop: offered-traffic window (s)")
    ap.add_argument("--long-every", type=int, default=8,
                    help="openloop: every Nth request is a long prompt")
    ap.add_argument("--timeout-s", type=float, default=120.0,
                    help="openloop: per-request client timeout")
    ap.add_argument("--num-tpus", type=int, default=0,
                    help="openloop: TPU chips to give the cluster "
                         "(0 = CPU tiny-model proxy)")
    args = ap.parse_args()
    if args.mode == "openloop" and args.model == "llama2_7b" \
            and not args.num_tpus:
        args.model = "tiny"  # CPU A/B runs the tiny proxy by default
    out = {"engine": engine_bench, "serve": serve_bench,
           "serve-breakdown": serve_breakdown,
           "openloop": openloop_bench}[args.mode](args)
    emit_final_record(out)


if __name__ == "__main__":
    main()
