"""Top-K checkpoint bookkeeping for a train run.

Parity: ``python/ray/train/_internal/checkpoint_manager.py`` (keep top-K by
score) and ``storage.py`` (persist to run storage dir).

Persistence is CRASH-ATOMIC (the discipline of Orbax emergency
checkpointing, and of the GCS WAL's torn-tail truncation): a checkpoint
is staged into ``checkpoint_NNNNNN.tmp``, fsynced, and committed with a
single ``os.rename`` — a process SIGKILLed mid-write (a preempted TPU
host, the chief failure mode this exists for) can only ever leave a
``*.tmp`` staging dir behind, never a half-written directory that
restore would load.  ``latest_committed_checkpoint`` and the stale-tmp
sweep ignore/remove such torn leftovers.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import shutil
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.train.checkpoint import Checkpoint

logger = logging.getLogger(__name__)

_CKPT_RE = re.compile(r"^checkpoint_(\d{6,})$")


@dataclasses.dataclass
class _Tracked:
    checkpoint: Checkpoint
    metrics: Dict[str, Any]
    index: int


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without dir fds: rename atomicity still holds
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _fsync_tree(root: str) -> None:
    """fsync every file then every directory under ``root`` so the
    rename-commit publishes fully-durable content (rename alone orders
    the NAME, not the bytes, across a power cut)."""
    for dirpath, _dirnames, filenames in os.walk(root, topdown=False):
        for name in filenames:
            try:
                fd = os.open(os.path.join(dirpath, name), os.O_RDONLY)
            except OSError:
                continue
            try:
                os.fsync(fd)
            except OSError:
                pass
            finally:
                os.close(fd)
        _fsync_dir(dirpath)


def committed_checkpoint_dirs(storage_dir: str) -> List[Tuple[int, str]]:
    """(index, abspath) of every COMMITTED checkpoint under
    ``storage_dir``, sorted by index.  Skips ``*.tmp`` staging dirs (a
    crash mid-copy) and anything not matching the committed name pattern
    — the restore-side half of the atomic-commit contract."""
    out: List[Tuple[int, str]] = []
    try:
        entries = os.listdir(storage_dir)
    except OSError:
        return out
    for name in entries:
        m = _CKPT_RE.match(name)
        if not m:
            continue
        path = os.path.join(storage_dir, name)
        if os.path.isdir(path):
            out.append((int(m.group(1)), os.path.abspath(path)))
    out.sort()
    return out


def latest_committed_checkpoint(storage_dir: str) -> Optional[Checkpoint]:
    """The newest checkpoint a crashed/preempted run durably committed
    (None if there is none).  The resume entry point: pass it as
    ``resume_from_checkpoint`` to continue from where the dead run left
    off with zero risk of loading a torn directory."""
    dirs = committed_checkpoint_dirs(storage_dir)
    return Checkpoint(dirs[-1][1]) if dirs else None


class CheckpointManager:
    def __init__(self, storage_dir: Optional[str], num_to_keep: Optional[int],
                 score_attribute: Optional[str], score_order: str = "max"):
        self.storage_dir = storage_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._tracked: List[_Tracked] = []
        self._index = 0
        if storage_dir:
            os.makedirs(storage_dir, exist_ok=True)
            # sweep staging dirs a killed writer left behind, and resume
            # indexing ABOVE existing commits so a restarted run can
            # never overwrite a checkpoint the dead run durably owns
            for name in os.listdir(storage_dir):
                if name.endswith(".tmp") and _CKPT_RE.match(name[:-4]):
                    logger.warning(
                        "removing torn checkpoint staging dir %s "
                        "(writer died mid-commit)", name)
                    shutil.rmtree(os.path.join(storage_dir, name),
                                  ignore_errors=True)
            committed = committed_checkpoint_dirs(storage_dir)
            if committed:
                self._index = committed[-1][0]

    @property
    def latest(self) -> Optional[Checkpoint]:
        if not self._tracked:
            return None
        return max(self._tracked, key=lambda t: t.index).checkpoint

    @property
    def best(self) -> Optional[Checkpoint]:
        t = self._best_tracked()
        return t.checkpoint if t else None

    def _best_tracked(self) -> Optional[_Tracked]:
        if not self._tracked:
            return None
        if not self.score_attribute:
            return max(self._tracked, key=lambda t: t.index)
        scored = [t for t in self._tracked if self.score_attribute in t.metrics]
        if not scored:
            return max(self._tracked, key=lambda t: t.index)
        key = lambda t: t.metrics[self.score_attribute]  # noqa: E731
        return (max if self.score_order == "max" else min)(scored, key=key)

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any]) -> Checkpoint:
        """Persist (if storage configured) and track; evicts beyond top-K.

        The persist is a two-phase commit: stage into ``<dest>.tmp``,
        fsync, rename to ``<dest>``.  Dying anywhere before the rename
        (the ``train.checkpoint.commit`` fault site sits exactly there)
        leaves only a ``.tmp`` dir that restore ignores and the next
        manager sweeps.
        """
        from ray_tpu.util.fault_injection import fault_point

        # adopt-in-place: a checkpoint ALREADY committed inside this
        # manager's storage dir (the tiered sharded writer renames
        # checkpoint_NNNNNN directly into storage) keeps its index and
        # is tracked without a copy — re-copying a multi-gigabyte
        # sharded checkpoint to a second slot would defeat the plane
        if self.storage_dir:
            abspath = os.path.abspath(checkpoint.path)
            m = _CKPT_RE.match(os.path.basename(abspath))
            if m and os.path.dirname(abspath) == \
                    os.path.abspath(self.storage_dir):
                idx = int(m.group(1))
                self._index = max(self._index, idx)
                for t in self._tracked:
                    if t.index == idx:  # already adopted (re-report)
                        return t.checkpoint
                self._tracked.append(
                    _Tracked(checkpoint, dict(metrics), idx))
                self._evict()
                return checkpoint
        self._index += 1
        if self.storage_dir:
            dest = os.path.join(self.storage_dir,
                                f"checkpoint_{self._index:06d}")
            if os.path.abspath(checkpoint.path) != dest:
                # index collision (another writer / a restart race):
                # NEVER delete a committed checkpoint to make room —
                # a crash between its removal and our rename would
                # destroy durable state.  Skip to the next free slot.
                while os.path.exists(dest):
                    self._index += 1
                    dest = os.path.join(
                        self.storage_dir,
                        f"checkpoint_{self._index:06d}")
                tmp = dest + ".tmp"
                shutil.rmtree(tmp, ignore_errors=True)
                shutil.copytree(checkpoint.path, tmp)
                _fsync_tree(tmp)
                # the commit point: everything staged + durable, one
                # rename publishes it.  A kill here (chaos tests arm
                # this site, incl. with a real SIGKILL) must never
                # yield a dir restore would load.
                fault_point("train.checkpoint.commit")
                os.rename(tmp, dest)
                _fsync_dir(self.storage_dir)
            checkpoint = Checkpoint(dest)
        self._tracked.append(_Tracked(checkpoint, dict(metrics), self._index))
        self._evict()
        return checkpoint

    def _evict(self) -> None:
        if not self.num_to_keep or len(self._tracked) <= self.num_to_keep:
            return
        # never evict the best or the latest
        keep_ids = set()
        best = self._best_tracked()
        if best:
            keep_ids.add(id(best))
        latest = max(self._tracked, key=lambda t: t.index)
        keep_ids.add(id(latest))
        candidates = sorted(
            (t for t in self._tracked if id(t) not in keep_ids),
            key=lambda t: t.index)
        while len(self._tracked) > self.num_to_keep and candidates:
            victim = candidates.pop(0)
            self._tracked.remove(victim)
            if self.storage_dir and victim.checkpoint.path.startswith(
                    os.path.abspath(self.storage_dir)):
                shutil.rmtree(victim.checkpoint.path, ignore_errors=True)
