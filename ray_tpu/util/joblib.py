"""joblib backend running batches as ray_tpu tasks.

Reference parity: ``python/ray/util/joblib/`` (``register_ray()`` +
``RayBackend``).  The reference monkey-patches joblib's ``PicklingPool``
bases onto its multiprocessing Pool shim (``ray_backend.py:58``); here the
backend subclasses ``ParallelBackendBase`` directly and submits each joblib
batch as one task — no pool-class surgery, and scikit-learn's
``Parallel(n_jobs=...)`` fans out across the cluster unchanged:

    from ray_tpu.util.joblib import register_ray_tpu
    register_ray_tpu()
    with joblib.parallel_config(backend="ray_tpu"):
        Parallel(n_jobs=4)(delayed(f)(i) for i in range(100))
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from joblib._parallel_backends import ParallelBackendBase

import ray_tpu


class _TaskResult:
    """joblib-facing handle: blocking ``get`` plus completion callback."""

    def __init__(self, ref, callback: Optional[Callable[[Any], None]]):
        self._ref = ref
        if callback is not None:
            # joblib's BatchCompletionCallBack assumes the callback fires on
            # failure as well as success (it schedules the next batches);
            # the actual exception re-raises from get() on the main thread.
            def waiter():
                try:
                    out = ray_tpu.get(ref)
                except Exception as e:  # noqa: BLE001
                    out = e
                callback(out)

            threading.Thread(target=waiter, daemon=True).start()

    def get(self, timeout: Optional[float] = None):
        return ray_tpu.get(self._ref, timeout=timeout)


@ray_tpu.remote
def _run_batch(func):
    # ``func`` is joblib's BatchedCalls: a zero-arg callable returning a
    # list of results for the whole batch.
    return func()


class RayTpuBackend(ParallelBackendBase):
    """joblib ParallelBackendBase over ray_tpu tasks."""

    supports_timeout = True
    supports_retrieve_callback = False
    uses_threads = False
    supports_sharedmem = False

    def configure(self, n_jobs=1, parallel=None, prefer=None, require=None,
                  **backend_kwargs):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.parallel = parallel
        return self.effective_n_jobs(n_jobs)

    def effective_n_jobs(self, n_jobs):
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        if n_jobs is None:
            return 1
        if n_jobs < 0:
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            n_cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
            # joblib convention: -1 = all CPUs, -2 = all but one, ...
            return max(1, n_cpus + 1 + n_jobs)
        return n_jobs

    def submit(self, func, callback=None):
        return _TaskResult(_run_batch.remote(func), callback)

    # Older joblib entry point; newer versions call submit().
    def apply_async(self, func, callback=None):
        return self.submit(func, callback)


def register_ray_tpu() -> None:
    """Register so ``joblib.parallel_config(backend="ray_tpu")`` works."""
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", RayTpuBackend)


__all__ = ["register_ray_tpu", "RayTpuBackend"]
