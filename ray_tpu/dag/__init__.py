"""ray_tpu.dag: lazy DAGs over actors/tasks + compiled execution.

Parity: ``python/ray/dag/`` — ``DAGNode.experimental_compile``
(``dag_node.py:265``) → ``CompiledDAG`` (``compiled_dag_node.py:805``).
"""

from ray_tpu.dag.collective_node import (
    CollectiveNode,
    allgather,
    allreduce,
    reducescatter,
)
from ray_tpu.dag.compiled_dag import CompiledDAG, CompiledDAGRef
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "DAGNode", "InputNode", "InputAttributeNode", "ClassMethodNode",
    "FunctionNode", "MultiOutputNode", "CompiledDAG", "CompiledDAGRef",
    "CollectiveNode", "allreduce", "allgather", "reducescatter",
]
