"""Collective types. Parity: ``python/ray/util/collective/types.py:29-46``
(Backend enum NCCL/GLOO there; here the accelerator plane is XLA)."""

from __future__ import annotations

import enum


class Backend(str, enum.Enum):
    """Collective backends.

    - TCP: host-memory collectives between actor processes over TCP, with
      GCS-KV rendezvous — the GLOO-role backend (works anywhere, used for
      CPU smoke tests and control-plane reductions).
    - XLA: in-mesh collectives — arrays live on TPU devices of one process
      mesh; ops lower to psum/all_gather/ppermute over ICI inside jit.
      (The multi-host variant forms the mesh via jax.distributed.)
    """

    TCP = "tcp"
    XLA = "xla"
    # single-controller fast path: ONE process owns the whole device mesh
    # ("ranks" are its devices); ops are jitted shard_map collectives over
    # ICI — values never host-stage
    XLA_MESH = "xla_mesh"

    @staticmethod
    def parse(v) -> "Backend":
        if isinstance(v, Backend):
            return v
        v = str(v).lower()
        if v in ("tcp", "gloo", "cpu"):
            return Backend.TCP
        if v in ("xla", "ici", "tpu", "nccl"):
            return Backend.XLA
        if v in ("xla_mesh", "mesh"):
            return Backend.XLA_MESH
        raise ValueError(f"unknown collective backend {v!r}")


class ReduceOp(str, enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


class GroupState(str, enum.Enum):
    """Supervised lifecycle of a collective group membership.

    READY -> ABORTED (watchdog/leader/GCS-event abort: current and future
    ops raise ``CollectiveAbortError``) -> DESTROYED (``destroy_group``;
    the name may then be re-initialized under a new epoch).
    """

    READY = "READY"
    ABORTED = "ABORTED"
    DESTROYED = "DESTROYED"


unset_timeout_ms = 30_000
