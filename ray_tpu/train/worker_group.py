"""Train worker group: N actors gang-scheduled on a placement group.

Parity: Train-v2 worker group
(``python/ray/train/v2/_internal/execution/worker_group/worker_group.py``)
and v1 ``WorkerGroup`` (``python/ray/train/_internal/worker_group.py:102``).
The controller polls workers for status instead of blocking on futures —
that is what makes failure handling and elastic resize possible between
control-loop steps.
"""

from __future__ import annotations

import dataclasses
import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint


@dataclasses.dataclass
class WorkerStatus:
    """One worker's poll snapshot."""

    rank: int
    running: bool
    finished: bool
    error: Optional[str]
    results: List[Dict[str, Any]]  # drained (metrics, checkpoint) rows
    dead: bool = False  # actor unreachable
    # tiered-checkpoint status of this rank's AsyncCheckpointer (None in
    # sync mode): {"index", "tier", "ram_acked", "committed_path"} — the
    # background persist lands AFTER the report row drained, so tier
    # progress travels on every poll, not on the one-shot row
    ckpt: Optional[Dict[str, Any]] = None


class TrainWorker:
    """Actor hosting one training process; runs the user loop in a thread.

    TPU-first: each worker owns the chips its raylet isolated for it; the
    jax process inside forms (or joins) the mesh.  On multi-host slices the
    controller passes coordinator address/process ids so workers can call
    ``jax.distributed.initialize`` (GSPMD mesh over the pod slice).
    """

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._session = None

    def get_metadata(self) -> Dict[str, Any]:
        import os
        import socket

        from ray_tpu._private.net import local_ip

        ctx = ray_tpu.get_runtime_context()
        return {
            "node_id": ctx.get_node_id(),
            "pid": os.getpid(),
            "hostname": socket.gethostname(),
            "ip": local_ip(),
        }

    def find_free_port(self) -> int:
        """A free port on THIS worker's host (for the rank-0 jax
        coordinator — the bind happens in this process later, so this is
        best-effort but races only with unrelated local processes)."""
        from ray_tpu._private.net import free_port

        return free_port()

    def setup_distributed(self, env: Dict[str, str]) -> None:
        """Install coordination env vars (before any jax import in the loop)."""
        import os

        os.environ.update(env)

    def start_loop(
        self,
        fn_payload: bytes,
        config: Dict[str, Any],
        rank: int,
        world_size: int,
        group_name: str,
        checkpoint_path: Optional[str],
        dataset_shard: Any = None,
        mesh_config: Any = None,
        axis_rules: Any = None,
        ckpt_plane: Optional[Dict[str, Any]] = None,
    ) -> None:
        from ray_tpu._private import serialization
        from ray_tpu.train import session as session_mod

        fn = serialization.loads(fn_payload)
        ckpt = Checkpoint(checkpoint_path) if checkpoint_path else None
        sess = session_mod._start_session(
            rank=rank,
            world_size=world_size,
            group_name=group_name,
            config=config,
            checkpoint=ckpt,
            mesh_config=mesh_config,
            axis_rules=axis_rules,
            ckpt_plane=ckpt_plane,
        )
        sess.dataset_shard = dataset_shard
        self._session = sess

        def _run():
            try:
                if _takes_config(fn):
                    fn(config)
                else:
                    fn()
            except BaseException as e:  # noqa: BLE001 — reported to controller
                sess.error = e
                sess.error_tb = traceback.format_exc()
            finally:
                sess.finished.set()

        self._thread = threading.Thread(target=_run, daemon=True, name="train-loop")
        self._thread.start()

    def request_checkpoint(self, tier: str = "any",
                           avoid_nodes: Optional[List[str]] = None) -> bool:
        """Drain-notice leg: ask the loop to checkpoint at its next step
        boundary (``get_context().drain_requested()`` flips true).
        ``tier="memory"`` marks the deadline too short for the disk
        tier: the loop should ``commit_ram()`` and report as soon as
        the peer-RAM replica acks.  ``avoid_nodes`` are the draining
        node ids — the emergency push must not land its replica on a
        node the drain protocol is about to shut down."""
        sess = self._session
        if sess is None:
            return False
        sess.checkpoint_request_avoid = set(avoid_nodes or ())
        sess.checkpoint_request_tier = tier
        sess.checkpoint_requested.set()
        return True

    def poll(self) -> Dict[str, Any]:
        sess = self._session
        if sess is None:
            return {"running": False, "finished": False, "error": None, "results": []}
        rows = []
        while True:
            try:
                rows.append(sess.results.get_nowait())
            except Exception:
                break
        # Checkpoints travel as paths (directories are node-local; the
        # controller re-wraps them).  Tiered handles travel as their
        # generation index — durability progress rides the poll-level
        # ``ckpt`` status below, since the background persist usually
        # finishes after the row drains.
        out_rows = []
        for r in rows:
            ck = r.get("checkpoint")
            row = {"metrics": r["metrics"], "checkpoint_path": None}
            if ck is not None:
                if hasattr(ck, "ram_acked"):  # TieredCheckpoint handle
                    row["checkpoint_index"] = ck.index
                    row["checkpoint_path"] = ck.committed_path
                else:
                    row["checkpoint_path"] = ck.path
            out_rows.append(row)
        err = None
        if sess.error is not None:
            err = getattr(sess, "error_tb", None) or repr(sess.error)
        ckpt_status = None
        cp = sess._checkpointer
        last = cp.last if cp is not None else None
        if last is not None:
            ckpt_status = {
                "index": last.index,
                "tier": last.tier,
                "ram_acked": last.ram_acked,
                "committed_path": last.committed_path,
                "world": last.world,
            }
        return {
            "running": self._thread is not None and self._thread.is_alive(),
            "finished": sess.finished.is_set(),
            "error": err,
            "results": out_rows,
            "ckpt": ckpt_status,
        }

    def shutdown(self) -> bool:
        return True


def _takes_config(fn: Callable) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    params = [p for p in sig.parameters.values()
              if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    return len(params) >= 1


class WorkerGroup:
    """Lifecycle of the N train-worker actors + their placement group."""

    def __init__(self, scaling_config, group_name: str):
        self.scaling_config = scaling_config
        self.group_name = group_name
        self.workers: List[Any] = []
        self.worker_metadata: List[Dict[str, Any]] = []
        self.pg = None
        self._started = False

    def start(self) -> None:
        from ray_tpu.util.placement_group import placement_group
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        sc = self.scaling_config
        res = sc.worker_resources()
        bundles = [dict(res) for _ in range(sc.num_workers)]
        # Gang-reserve: one bundle per worker.  A requested topology
        # gang-schedules a contiguous pod slice (all bundles on nodes
        # sharing one slice label, ICI-adjacency order) when the cluster
        # advertises slice labels; PACK otherwise (reference:
        # BackendExecutor._create_placement_group,
        # python/ray/train/_internal/backend_executor.py:230).
        # restartable=True is the train controller's mode: a node death
        # inside the gang fate-shares it and the GCS re-runs atomic
        # reservation while the controller checkpoint-restarts.
        strategy = "STRICT_PACK_SLICE" if sc.topology else "PACK"
        self.pg = placement_group(bundles, strategy=strategy,
                                  name=f"train-{self.group_name}",
                                  priority=getattr(sc, "priority", 0),
                                  restartable=True)
        if not self.pg.wait(timeout_seconds=60):
            raise RuntimeError(
                f"placement group for {self.group_name} not placed in 60s "
                f"(bundles={bundles})")

        worker_cls = ray_tpu.remote(TrainWorker)
        # Predefined resources go through their dedicated options; only
        # custom keys ride the resources= dict (api_utils rejects CPU/TPU
        # there, mirroring the reference's option validation).
        opts: Dict[str, Any] = {
            "num_cpus": res.get("CPU", 0.0),
        }
        if res.get("GPU"):
            opts["num_gpus"] = res["GPU"]
        if res.get("TPU"):
            opts["num_tpus"] = res["TPU"]
        if res.get("memory"):
            opts["memory"] = res["memory"]
        custom = {k: v for k, v in res.items()
                  if k not in ("CPU", "GPU", "TPU", "memory")}
        if custom:
            opts["resources"] = custom
        self.workers = [
            worker_cls.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self.pg, placement_group_bundle_index=i),
                **opts,
            ).remote()
            for i in range(sc.num_workers)
        ]
        # barrier: all actors alive
        self.worker_metadata = ray_tpu.get(
            [w.get_metadata.remote() for w in self.workers], timeout=60)
        self._started = True

    def worker_node_ids(self) -> List[str]:
        """Node hosting each rank (the drain watcher intersects this
        with the cluster's DRAINING set)."""
        return [m.get("node_id", "") for m in self.worker_metadata]

    def request_checkpoint(self, tier: str = "any",
                           avoid_nodes: Optional[List[str]] = None) -> None:
        """Best-effort fan-out of the drain notice to every rank
        (``tier="memory"`` when the deadline can't fit the disk tier;
        ``avoid_nodes`` = the draining nodes, so emergency replicas
        steer clear of hardware about to disappear)."""
        refs = []
        for w in self.workers:
            try:
                refs.append(w.request_checkpoint.remote(tier, avoid_nodes))
            except Exception:  # noqa: BLE001 — dying worker
                pass
        for r in refs:
            try:
                ray_tpu.get(r, timeout=5)
            except Exception:  # noqa: BLE001
                pass

    def run_train_fn(
        self,
        fn_payload: bytes,
        config: Dict[str, Any],
        checkpoint: Optional[Checkpoint],
        dataset_shards: Optional[List[Any]] = None,
        dist_env: Optional[List[Dict[str, str]]] = None,
        mesh_config: Any = None,
        axis_rules: Any = None,
        ckpt_planes: Optional[List[Dict[str, Any]]] = None,
    ) -> None:
        n = len(self.workers)
        if dist_env is not None:
            ray_tpu.get([
                w.setup_distributed.remote(dist_env[i])
                for i, w in enumerate(self.workers)
            ], timeout=30)
        refs = []
        for rank, w in enumerate(self.workers):
            shard = dataset_shards[rank] if dataset_shards else None
            refs.append(w.start_loop.remote(
                fn_payload, config, rank, n, self.group_name,
                checkpoint.path if checkpoint else None, shard,
                mesh_config, axis_rules,
                ckpt_planes[rank] if ckpt_planes else None,
            ))
        ray_tpu.get(refs, timeout=60)

    def poll(self, timeout: float = 30.0) -> List[WorkerStatus]:
        """Poll every worker; a dead actor yields ``dead=True`` status."""
        statuses: List[WorkerStatus] = []
        refs = [w.poll.remote() for w in self.workers]
        for rank, ref in enumerate(refs):
            try:
                st = ray_tpu.get(ref, timeout=timeout)
                statuses.append(WorkerStatus(
                    rank=rank, running=st["running"], finished=st["finished"],
                    error=st["error"], results=st["results"],
                    ckpt=st.get("ckpt")))
            except Exception as e:  # actor died / unreachable
                statuses.append(WorkerStatus(
                    rank=rank, running=False, finished=False,
                    error=f"worker {rank} unreachable: {e!r}", results=[],
                    dead=True))
        return statuses

    def shutdown(self) -> None:
        from ray_tpu.util.placement_group import remove_placement_group

        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None
        self._started = False
