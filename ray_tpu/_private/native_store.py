"""ctypes binding for the C++ arena object store (ray_tpu/_native/store.cc).

Same interface as ``object_store.SharedObjectStore`` (one shm segment per
object) but backed by ONE mmap'd arena per node with a boundary-tag
allocator, an open-addressing object table, and LRU eviction — the
plasma-store design (``src/ray/object_manager/plasma/store.h:55``) as a
daemon-less library.  Payload I/O is zero-copy: Python mmaps the same
segment and slices memoryviews at offsets the C side allocates.
"""

from __future__ import annotations

import ctypes
import logging
import mmap
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID

logger = logging.getLogger(__name__)

_lib = None
_lib_lock = threading.Lock()


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from ray_tpu._native.build import lib_path

        path = lib_path()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        lib.rtpu_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                          ctypes.c_uint64]
        lib.rtpu_store_create.restype = ctypes.c_int
        lib.rtpu_store_attach.argtypes = [ctypes.c_char_p]
        lib.rtpu_store_attach.restype = ctypes.c_int
        lib.rtpu_store_detach.argtypes = [ctypes.c_int]
        lib.rtpu_store_unlink.argtypes = [ctypes.c_char_p]
        lib.rtpu_store_unlink.restype = ctypes.c_int
        lib.rtpu_store_alloc.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                         ctypes.c_uint64, ctypes.c_uint32]
        lib.rtpu_store_alloc.restype = ctypes.c_int64
        lib.rtpu_store_seal.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.rtpu_store_seal.restype = ctypes.c_int
        lib.rtpu_store_reclaim_dead.argtypes = [ctypes.c_int]
        lib.rtpu_store_reclaim_dead.restype = ctypes.c_int64
        lib.rtpu_store_get.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                       ctypes.POINTER(ctypes.c_uint64)]
        lib.rtpu_store_get.restype = ctypes.c_int64
        lib.rtpu_store_peek.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_uint64)]
        lib.rtpu_store_peek.restype = ctypes.c_int64
        lib.rtpu_store_release.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.rtpu_store_release.restype = ctypes.c_int
        lib.rtpu_store_contains.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.rtpu_store_contains.restype = ctypes.c_int
        lib.rtpu_store_delete.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.rtpu_store_delete.restype = ctypes.c_int
        lib.rtpu_store_stats.argtypes = [ctypes.c_int,
                                         ctypes.POINTER(ctypes.c_uint64 * 4)]
        lib.rtpu_store_stats.restype = ctypes.c_int
        lib.rtpu_store_evictable.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                             ctypes.c_uint64]
        lib.rtpu_store_evictable.restype = ctypes.c_int64
        _lib = lib
        return lib


def available() -> bool:
    return _load_lib() is not None


class NativeArenaStore:
    """Per-process handle onto the node's shared arena."""

    def __init__(self, name: str, arena_bytes: int = 256 * 1024 * 1024,
                 table_capacity: int = 65536, create: bool = False):
        lib = _load_lib()
        if lib is None:
            from ray_tpu._native.build import build_error

            raise RuntimeError(f"native store unavailable: {build_error()}")
        self._lib = lib
        self.name = name
        self._cname = name.encode()
        if create:
            h = lib.rtpu_store_create(self._cname, arena_bytes, table_capacity)
            if h == -17:  # EEXIST: another process won the create race
                h = lib.rtpu_store_attach(self._cname)
        else:
            h = lib.rtpu_store_attach(self._cname)
            if h == -2 and create is False:  # ENOENT
                raise FileNotFoundError(f"no arena {name!r}")
        if h < 0:
            raise OSError(-h, os.strerror(-h), name)
        self._h = h
        # python-side zero-copy view of the same segment
        fd = os.open(f"/dev/shm{name if name.startswith('/') else '/' + name}",
                     os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, 0)
        finally:
            os.close(fd)
        self._view = memoryview(self._mm)
        self._closed = False

    # -- SharedObjectStore-compatible interface ----------------------------

    def put_serialized(self, object_id: ObjectID, payload: bytes) -> str:
        return self.put_into(object_id, len(payload),
                             lambda view: view.__setitem__(
                                 slice(0, len(payload)), payload))

    def put_into(self, object_id: ObjectID, nbytes: int, write_fn,
                 no_evict: bool = False) -> str:
        """Alloc → ``write_fn(view)`` writes the payload in place → seal.
        Serialization packs straight into the arena (no staging copy).
        ``no_evict`` returns MemoryError instead of destructively evicting
        refcount-0 objects (the spill manager persists them first)."""
        oid = object_id.binary()
        off = self._lib.rtpu_store_alloc(self._h, oid, nbytes,
                                         1 if no_evict else 0)
        if off == -12:  # ENOMEM: pins leaked by SIGKILLed processes may
            # be the pressure — reclaim and retry once (the daemon-less
            # equivalent of plasma's client-disconnect cleanup)
            if self.reclaim_dead() > 0:
                off = self._lib.rtpu_store_alloc(self._h, oid, nbytes,
                                                 1 if no_evict else 0)
        if off == -17:  # EEXIST
            # idempotent only if the existing entry is actually readable
            # (a pending-delete entry is invisible — let the caller fall
            # back to the segment store)
            if self.contains(object_id):
                return self.name
            raise MemoryError(f"object {object_id.hex()} exists but is "
                              f"not readable (pending delete)")
        if off < 0:
            raise MemoryError(
                f"arena store alloc failed for {nbytes}B: "
                f"{os.strerror(-off)}")
        write_fn(self._view[off:off + nbytes])
        rc = self._lib.rtpu_store_seal(self._h, oid)
        if rc != 0:
            raise OSError(-rc, os.strerror(-rc))
        return self.name

    def put(self, object_id: ObjectID, value: Any) -> Tuple[str, int, List]:
        payload, refs = serialization.serialize(value)
        name = self.put_serialized(object_id, payload)
        return name, len(payload), refs

    def contains(self, object_id: ObjectID) -> bool:
        return self._lib.rtpu_store_contains(
            self._h, object_id.binary()) == 1

    def get_buffer(self, object_id: ObjectID) -> Optional[memoryview]:
        """Unpinned zero-copy view (peek): lifetime is guaranteed by the
        creator pin, which only an explicit delete() drops."""
        size = ctypes.c_uint64()
        off = self._lib.rtpu_store_peek(self._h, object_id.binary(),
                                        ctypes.byref(size))
        if off < 0:
            return None
        return self._view[off:off + size.value]

    def pin(self, object_id: ObjectID) -> bool:
        """Bump the refcount (protects from eviction AND from delete
        freeing the block under live readers)."""
        size = ctypes.c_uint64()
        return self._lib.rtpu_store_get(self._h, object_id.binary(),
                                        ctypes.byref(size)) >= 0

    def get(self, object_id: ObjectID) -> Tuple[Any, List]:
        buf = self.get_buffer(object_id)
        if buf is None:
            raise KeyError(object_id)
        return serialization.deserialize(buf)

    def get_bytes(self, object_id: ObjectID) -> Optional[bytes]:
        buf = self.get_buffer(object_id)
        return None if buf is None else bytes(buf)

    def create_writable(self, object_id: ObjectID, nbytes: int):
        """(view, seal) split of put_into for incremental chunk writes."""
        oid = object_id.binary()
        off = self._lib.rtpu_store_alloc(self._h, oid, nbytes, 0)
        if off < 0:
            raise MemoryError(
                f"arena alloc failed for {nbytes}B: {os.strerror(-off)}")

        def seal():
            rc = self._lib.rtpu_store_seal(self._h, oid)
            if rc != 0:
                raise OSError(-rc, os.strerror(-rc))

        return self._view[off:off + nbytes], seal

    def evictable(self, max_n: int = 256) -> List[ObjectID]:
        """Sealed refcount-0 objects in LRU order (spill candidates —
        reference LocalObjectManager::SpillObjects)."""
        buf = ctypes.create_string_buffer(16 * max_n)
        n = self._lib.rtpu_store_evictable(self._h, buf, max_n)
        if n <= 0:
            return []
        raw = buf.raw
        return [ObjectID(raw[16 * i:16 * (i + 1)]) for i in range(n)]

    def release(self, object_id: ObjectID):
        self._lib.rtpu_store_release(self._h, object_id.binary())

    def reclaim_dead(self) -> int:
        """Drop pins leaked by dead processes; returns pins reclaimed."""
        return max(0, int(self._lib.rtpu_store_reclaim_dead(self._h)))

    def delete(self, object_id: ObjectID):
        self._lib.rtpu_store_delete(self._h, object_id.binary())

    def stats(self) -> Dict[str, int]:
        out = (ctypes.c_uint64 * 4)()
        self._lib.rtpu_store_stats(self._h, ctypes.byref(out))
        return {"capacity": out[0], "used": out[1], "objects": out[2],
                "evictions": out[3]}

    def close(self, unlink_created: bool = False):
        if self._closed:
            return
        self._closed = True
        try:
            self._view.release()
            self._mm.close()
        except (BufferError, Exception):
            pass  # exported buffers: OS reclaims at exit (plasma model)
        self._lib.rtpu_store_detach(self._h)
        if unlink_created:
            self._lib.rtpu_store_unlink(self._cname)

    def unlink(self):
        self._lib.rtpu_store_unlink(self._cname)
