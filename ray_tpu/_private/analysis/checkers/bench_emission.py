"""bench-emission: benchmark entrypoints must end stdout with ONE record.

The bench harness parses the LAST line of a run's captured output
(stdout and stderr merged) as the round's record.  Hand-rolled
``print(json.dumps(...))`` endings broke that contract twice over —
unflushed-stream interleave let stderr warning chatter land after the
record, and any failure before the final print exited with a traceback
instead of a record.  ``MULTICHIP_*.json`` shipped without a top-level
parsed metric for five rounds because of exactly this class.

``ray_tpu._private.bench_emit`` centralizes the fix
(``emit_final_record`` flushes stderr first and writes the record
flushed; ``final_record_guard`` emits a structured error record when the
body dies; ``emit_record_line`` for intermediate per-scenario records).
This rule keeps every benchmark entrypoint on those helpers:

- a file with an ``if __name__ == "__main__"`` guard must call
  ``emit_final_record`` (or run under ``final_record_guard``) somewhere;
- bare-JSON prints — ``print(json.dumps(...))`` /
  ``sys.stdout.write(json.dumps(...))`` — are flagged wherever they
  appear in a benchmark file: they compete with the contract line and
  skip the stream-flush ordering.

Prefixed prints (``print("TAG " + json.dumps(...))``) are NOT bare-JSON
lines and stay legal.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ray_tpu._private.analysis.core import (
    Checker,
    Finding,
    ParsedFile,
    dotted_name,
    register,
)

_FINAL_EMITTERS = ("emit_final_record", "final_record_guard")
_LINE_EMITTER = "emit_record_line"


def _is_main_guard(node: ast.AST) -> bool:
    if not isinstance(node, ast.If) or \
            not isinstance(node.test, ast.Compare):
        return False
    t = node.test
    sides = [t.left] + list(t.comparators)
    names = {dotted_name(s) for s in sides}
    consts = {s.value for s in sides if isinstance(s, ast.Constant)}
    return "__name__" in names and "__main__" in consts


def _bare_json_arg(call: ast.Call) -> Optional[ast.Call]:
    """The ``json.dumps(...)`` call passed DIRECTLY as an argument (a
    bare-JSON output line), if any.  String-prefixed concatenations are
    not bare lines."""
    for a in call.args:
        if isinstance(a, ast.Call) and \
                dotted_name(a.func).endswith("json.dumps"):
            return a
    return None


@register
class BenchEmissionChecker(Checker):
    rule = "bench-emission"
    description = ("benchmark entrypoints must emit their final record "
                   "via bench_emit.emit_final_record (stderr-flushed "
                   "final bare-JSON line) and never hand-print bare "
                   "JSON records")
    hint = ("route records through ray_tpu._private.bench_emit: "
            "emit_final_record(rec) for the headline (or wrap the body "
            "in final_record_guard), emit_record_line(rec) for "
            "intermediate records")

    def applies_to(self, relpath: str) -> bool:
        return relpath in ("bench.py", "__graft_entry__.py") or (
            relpath.startswith("benchmarks/")
            and relpath.endswith(".py"))

    def check(self, pf: ParsedFile) -> Iterable[Finding]:
        out: List[Finding] = []
        guard = next((n for n in pf.tree.body if _is_main_guard(n)), None)
        if guard is None:
            return out  # importable helper module, not an entrypoint
        emits_final = False
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name.split(".")[-1] in _FINAL_EMITTERS:
                emits_final = True
                continue
            if name == "print" or name.endswith("stdout.write"):
                dumped = _bare_json_arg(node)
                if dumped is not None:
                    out.append(self.finding(
                        pf, node,
                        "hand-printed bare-JSON record — competes with "
                        "the harness's last-line parse and skips the "
                        "stderr flush ordering"))
        if not emits_final:
            out.append(self.finding(
                pf, guard,
                "benchmark entrypoint never calls emit_final_record / "
                "final_record_guard — on any failure (or stderr "
                "interleave) the harness's last-line parse finds no "
                "record"))
        return out
