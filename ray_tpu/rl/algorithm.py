"""Algorithm facade: config -> build() -> train() iterations.

Reference: ``rllib/algorithms/algorithm.py:207`` (Algorithm orchestrating
EnvRunnerGroup + LearnerGroup) and ``algorithm_config.py`` (builder-style
AlgorithmConfig).  Two execution modes:

- env_runners(num_env_runners=0) + a jax env: everything — rollout, GAE,
  minibatch epochs — runs in jitted device code in this process (TPU-first
  fast path; the mesh shards the batch over ``dp``).
- num_env_runners>0 (or a gym env): EnvRunner actors collect on CPU hosts,
  the learner updates on device — the reference's architecture.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rl.env import JaxVectorEnv, make_env
from ray_tpu.rl.models import ActorCriticModule
from ray_tpu.rl.ppo import PPOConfig, PPOLearner, compute_gae, make_rollout_fn


class AlgorithmConfig:
    def __init__(self, algo_class=None):
        self.algo_class = algo_class or PPO
        self.env_name: Optional[str] = None
        self.num_env_runners = 0
        self.num_envs_per_runner = 8
        self.rollout_fragment_length = 128
        self.hidden_sizes = (64, 64)
        self.ppo = PPOConfig()
        self.seed = 0

    def environment(self, env: str) -> "AlgorithmConfig":
        self.env_name = env
        return self

    def env_runners(self, num_env_runners: int = 0,
                    num_envs_per_env_runner: int = 8,
                    rollout_fragment_length: int = 128) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 clip_eps: Optional[float] = None,
                 entropy_coef: Optional[float] = None,
                 num_epochs: Optional[int] = None,
                 num_minibatches: Optional[int] = None,
                 hidden_sizes=None) -> "AlgorithmConfig":
        import dataclasses

        kw = {k: v for k, v in dict(
            lr=lr, gamma=gamma, clip_eps=clip_eps, entropy_coef=entropy_coef,
            num_epochs=num_epochs, num_minibatches=num_minibatches,
        ).items() if v is not None}
        self.ppo = dataclasses.replace(self.ppo, **kw)
        if hidden_sizes is not None:
            self.hidden_sizes = tuple(hidden_sizes)
        return self

    def seed_(self, seed: int) -> "AlgorithmConfig":
        self.seed = seed
        return self

    def build(self) -> "Algorithm":
        return self.algo_class(self)


class Algorithm:
    def __init__(self, config: AlgorithmConfig):
        self.config = config

    def train(self) -> Dict[str, Any]:
        raise NotImplementedError

    def stop(self):
        pass


class PPO(Algorithm):
    def __init__(self, config: AlgorithmConfig):
        super().__init__(config)
        import jax

        env = make_env(config.env_name)
        self.is_jax_env = isinstance(env, JaxVectorEnv)
        self.env = env
        spec = env.spec
        self.module = ActorCriticModule(spec.obs_dim, spec.num_actions,
                                        config.hidden_sizes)
        self.learner = PPOLearner(self.module, config.ppo, seed=config.seed)
        self.key = jax.random.PRNGKey(config.seed + 1)
        self.iteration = 0
        self._ep_returns: List[float] = []
        self._last_ep_reward = float("nan")
        if self.is_jax_env and config.num_env_runners == 0:
            self.key, k = jax.random.split(self.key)
            self.env_state, self.obs = env.reset(
                k, config.num_envs_per_runner)
            self._rollout = make_rollout_fn(
                self.module, env, config.rollout_fragment_length, config.ppo)
            self.runner_group = None
        else:
            from ray_tpu.rl.env_runner import EnvRunnerGroup

            self.runner_group = EnvRunnerGroup(
                config.env_name, max(1, config.num_env_runners),
                config.num_envs_per_runner,
                {"obs_dim": spec.obs_dim, "num_actions": spec.num_actions,
                 "hidden": config.hidden_sizes, "gamma": config.ppo.gamma},
                seed=config.seed)
            self.runner_group.sync_weights(self.learner.get_weights())

    # -- one training iteration -------------------------------------------
    def train(self) -> Dict[str, Any]:
        import jax

        t0 = time.perf_counter()
        cfg = self.config
        if self.runner_group is None:
            self.key, kr, ku = jax.random.split(self.key, 3)
            self.env_state, self.obs, batch, stats = self._rollout(
                self.learner.params, self.env_state, self.obs, kr)
            metrics = self.learner.update(batch, ku)
            n_steps = int(batch["obs"].shape[0])
            eps = float(stats["episodes_done"])
            rps = float(stats["reward_per_step"])
            if eps > 0:
                ep_reward = rps * n_steps / eps
                self._last_ep_reward = ep_reward
            else:
                # no episode finished this fragment: carry the previous
                # estimate rather than reporting the whole batch's reward
                ep_reward = self._last_ep_reward
        else:
            trajs = self.runner_group.sample(cfg.rollout_fragment_length)
            batch = self._assemble(trajs)
            self.key, ku = jax.random.split(self.key)
            metrics = self.learner.update(batch, ku)
            self.runner_group.sync_weights(self.learner.get_weights())
            n_steps = int(batch["obs"].shape[0])
            done_eps = self.runner_group.episode_stats()
            self._ep_returns.extend(done_eps)
            recent = self._ep_returns[-50:]
            ep_reward = float(np.mean(recent)) if recent else float("nan")
        self.iteration += 1
        metrics.update({
            "training_iteration": self.iteration,
            "env_steps_this_iter": n_steps,
            "env_steps_per_sec": n_steps / (time.perf_counter() - t0),
            "episode_reward_mean": ep_reward,
        })
        return metrics

    def _assemble(self, trajs: List[Dict[str, np.ndarray]]):
        import jax.numpy as jnp

        from ray_tpu.rl.ppo import compute_gae

        parts = []
        for t in trajs:
            advs, rets = compute_gae(
                jnp.asarray(t["rewards"]), jnp.asarray(t["values"]),
                jnp.asarray(t["dones"]), jnp.asarray(t["last_value"]),
                self.config.ppo.gamma, self.config.ppo.gae_lambda)
            parts.append({
                "obs": t["obs"].reshape(-1, t["obs"].shape[-1]),
                "actions": t["actions"].reshape(-1),
                "logp_old": t["logp_old"].reshape(-1),
                "advantages": np.asarray(advs).reshape(-1),
                "returns": np.asarray(rets).reshape(-1),
            })
        return {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}

    # -- checkpointing ------------------------------------------------------
    def save_checkpoint(self) -> Dict[str, Any]:
        return {"learner": self.learner.get_state(),
                "iteration": self.iteration}

    def load_checkpoint(self, state: Dict[str, Any]):
        if "learner" in state:
            self.learner.set_state(state["learner"])
        else:  # params-only checkpoint (older format)
            self.learner.set_weights(state["params"])
        self.iteration = state["iteration"]
        if self.runner_group is not None:
            self.runner_group.sync_weights(self.learner.get_weights())

    def stop(self):
        if self.runner_group is not None:
            self.runner_group.stop()
