"""DeploymentHandle + power-of-two-choices replica routing.

Reference: ``python/ray/serve/handle.py`` (``DeploymentHandle.remote :709``)
and ``serve/_private/replica_scheduler/pow_2_scheduler.py``
(``PowerOfTwoChoicesReplicaScheduler :52``, ``choose_replica_for_request
:816``): sample two replicas, probe queue lengths (with a short-lived
cache), send to the shorter queue.

Overload protection (reference: ``serve/_private/router.py``
queue-length-capped scheduling): the router is the serving path's
admission valve.  It tracks its own dispatched-but-unfinished count per
replica and never sends a replica more than ``max_ongoing_requests``;
excess requests wait in a bounded router-side queue
(``max_queued_requests``), and once THAT is full new arrivals fail fast
with ``BackPressureError`` instead of piling up without limit behind a
stalled replica.  Requests carry a deadline (``serve.context``): one
whose budget is already spent is rejected before dispatch rather than
executed for a client that stopped waiting.
"""

from __future__ import annotations

import collections
import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private import resilience
from ray_tpu.exceptions import BackPressureError, DeadlineExceededError
from ray_tpu.serve.context import OverloadStats, current_context
from ray_tpu.util.fault_injection import fault_point


def _assign_retryable(err: BaseException) -> bool:
    """Dispatch-time failures worth a refresh+retry: transport loss to a
    replica (it died; the controller will repopulate the set) and the
    empty-replica window during a rolling update.  Application errors
    raised by the replica's own code surface through the returned ref,
    not here, so anything else at dispatch time is fatal.  Overload
    verdicts are explicitly NON-retryable: a shed (``BackPressureError``)
    means the queue is full — re-entering it from inside the router would
    defeat the bound (the PROXY owns the retry decision, via
    ``Retry-After``) — and a spent deadline (``DeadlineExceededError``)
    can only get more spent."""
    if isinstance(err, (BackPressureError, DeadlineExceededError)):
        return False
    return resilience.is_retryable(err) or "has no replicas" in str(err)


class DeploymentResponse:
    """Future-like result of handle.remote() (reference DeploymentResponse)."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout: Optional[float] = None):
        return ray_tpu.get(self._ref, timeout=timeout)

    @property
    def ref(self):
        return self._ref


class Router:
    """Pow-2 replica chooser with a queue-length cache and a bounded
    admission queue."""

    QUEUE_LEN_CACHE_S = 2.0
    # dispatch-time affinity entries are provisional for this long: the
    # replica only reports a model as loaded AFTER the load finishes, so
    # a probe racing a cold load must not strip the entry (that flap sent
    # concurrent same-model requests to different replicas, each paying a
    # duplicate load — exactly what model-aware routing exists to avoid)
    MODEL_LOAD_GRACE_S = 30.0
    # deployment-version polls ride the request path; uncapped they cost
    # one controller RPC PER REQUEST (measured: the largest serve-path
    # overhead after the replica call itself on a 1-vCPU box)
    VERSION_CHECK_INTERVAL_S = 0.5
    # how long a queued request sleeps between capacity re-checks (a
    # completion notifies the condition immediately; this only bounds the
    # staleness of the replica-set view while waiting)
    QUEUE_POLL_S = 0.05
    # an unchanged overload snapshot is still re-pushed this often so the
    # controller can tell idle-but-alive reporters from exited ones
    # (must stay well under Controller.OVERLOAD_RETIRE_S)
    REPORT_HEARTBEAT_S = 5.0

    def __init__(self, deployment_name: str, controller):
        self._deployment = deployment_name
        self._controller = controller
        self._replicas: List[Any] = []
        # concurrency knobs are SEEDED FROM THE DEPLOYMENT CONFIG by the
        # refresh() below, never from a magic default: early traffic
        # against a low-concurrency deployment must not over-dispatch
        # during the pre-refresh window
        self._max_ongoing: Optional[int] = None
        self._max_queued: int = -1
        self._version = -1
        self._qlen_cache: Dict[str, tuple] = {}  # actor id -> (len, expiry)
        # model-aware routing (reference multiplex.py): model id ->
        # replica cache keys that recently served / reported that model
        self._mux_affinity: Dict[str, List[str]] = {}
        # (model id, replica key) -> monotonic time of last dispatch;
        # consulted by _sync_models to keep provisional entries alive
        self._mux_dispatch_t: Dict[tuple, float] = {}
        self._lock = threading.Lock()
        # admission state: replica key -> dispatched-but-unfinished count,
        # resolved by the completion watcher; waiters block on the
        # condition until a slot frees (or their deadline expires)
        self._cond = threading.Condition(self._lock)
        self._inflight: Dict[str, int] = {}
        self._outstanding: Dict[Any, str] = {}  # ref -> replica key
        self._queued = 0
        # slot releases from _SlotReleasingStream.__del__: a GC finalizer
        # must not take the router lock (it could fire while THIS thread
        # holds it), so it appends here (deque.append is atomic) and the
        # next assign / watcher pass drains it
        self._orphan_releases: collections.deque = collections.deque()
        self._stopped = threading.Event()
        self._overload = OverloadStats(deployment_name)
        self._reporter_id = uuid.uuid4().hex[:12]
        self._last_reported: Optional[Dict[str, int]] = None
        self._last_report_t = 0.0
        self._rng = random.Random()
        self._last_version_check = 0.0
        self.refresh()
        # the completion watcher doubles as the overload-report
        # heartbeat, so it starts eagerly: a router whose traffic was
        # ALL shed (nothing ever dispatched) must still get its final
        # counters to the controller after the burst ends
        self._watcher = threading.Thread(
            target=self._watch_loop, daemon=True,
            name=f"serve-router-watch-{deployment_name}")
        self._watcher.start()

    @property
    def overload_stats(self) -> OverloadStats:
        return self._overload

    def refresh(self):
        # bounded: refresh runs on dispatch/watcher control threads — a
        # dead controller must surface as an error, not a permanent hang
        info = ray_tpu.get(
            self._controller.get_deployment_info.remote(self._deployment),
            timeout=30)
        if info is None:
            raise KeyError(f"no deployment {self._deployment!r}")
        with self._lock:
            self._replicas = info["replicas"]
            self._max_ongoing = info["max_ongoing_requests"]
            self._max_queued = info.get("max_queued_requests", -1)
            self._version = info["version"]
            self._qlen_cache.clear()  # cache keys are replica ids; drop stale
            self._cond.notify_all()  # new replicas may mean new capacity

    def _maybe_refresh(self):
        # long-poll analog: cheap version check piggybacked on the probe
        # path — throttled so the hot path isn't one controller RPC per
        # request (a replica-set change waits at most the interval)
        now = time.monotonic()
        with self._lock:
            if now - self._last_version_check < self.VERSION_CHECK_INTERVAL_S:
                return
            self._last_version_check = now
        try:
            v = ray_tpu.get(
                self._controller.get_version.remote(self._deployment),
                timeout=5)
        except Exception:
            return
        if v != self._version:
            try:
                self.refresh()
            except TimeoutError:
                return  # opportunistic refresh: the next interval retries

        self._report_overload()

    def _report_overload(self):
        """Snapshot-deduped fire-and-forget push of this router's
        shed/expired/cancelled/queued counters to the controller, which
        aggregates across reporter processes into the published serve
        status.  Called from the request path (rides _maybe_refresh) AND
        from the completion watcher — the watcher's calls are what land
        the final drained-to-zero ``queued`` gauge after traffic stops
        (a request-path-only report would leave the last mid-burst
        snapshot, with its phantom queued count, published forever)."""
        snap = self._overload.snapshot()
        now = time.monotonic()
        # dedup unchanged snapshots, but never go silent longer than the
        # heartbeat: the controller retires reporters it hasn't heard
        # from (folding their counters into a base) — a live-but-idle
        # router must keep proving it's alive or its eventual next
        # report would double-count against the folded base
        if (snap == self._last_reported
                and now - self._last_report_t < self.REPORT_HEARTBEAT_S):
            return
        self._last_reported = snap
        self._last_report_t = now
        try:
            self._controller.report_overload.remote(
                self._deployment, self._reporter_id, snap)
        except Exception:  # noqa: BLE001 — visibility never fails a request
            pass

    def _cache_key(self, replica) -> str:
        return replica._actor_id.hex()

    def _probe(self, replica) -> int:
        key = self._cache_key(replica)
        now = time.monotonic()
        with self._lock:
            hit = self._qlen_cache.get(key)
            if hit and hit[1] > now:
                return hit[0]
        try:
            # short: the probe rides the DISPATCH path, so an unreachable
            # replica (dying mid-drain, wedged in a long GIL hold) must
            # cost one bounded stall per cache window, not 5s per probe —
            # under open-loop load the old timeout alone inflated p99 by
            # seconds whenever a replica was killed (production-day
            # crucible).  The failure result is negative-cached below for
            # QUEUE_LEN_CACHE_S like any other probe answer.
            info = ray_tpu.get(replica.probe.remote(), timeout=1.5)
            qlen = info["qlen"]
            self._sync_models(key, info.get("models") or [])
        except Exception:
            qlen = 1 << 30  # unreachable replica: never prefer it
        with self._lock:
            self._qlen_cache[key] = (qlen, now + self.QUEUE_LEN_CACHE_S)
        return qlen

    def _sync_models(self, key: str, models: List[str]) -> None:
        """Reconcile the affinity map with a replica's AUTHORITATIVE
        loaded-model report: models it evicted stop routing to it, and
        the map is bounded (stale ids age out).  Entries dispatched
        within MODEL_LOAD_GRACE_S survive an "absent" report — the load
        the dispatch triggered may simply not have finished yet."""
        now = time.monotonic()
        with self._lock:
            loaded = set(models)
            for mid, lst in list(self._mux_affinity.items()):
                if mid in loaded:
                    if key not in lst:
                        lst.append(key)
                    self._mux_dispatch_t.pop((mid, key), None)
                elif key in lst:
                    t = self._mux_dispatch_t.get((mid, key))
                    if t is not None and now - t < self.MODEL_LOAD_GRACE_S:
                        continue  # provisional: cold load in progress
                    lst.remove(key)
                    self._mux_dispatch_t.pop((mid, key), None)
                    if not lst:
                        del self._mux_affinity[mid]
            while len(self._mux_affinity) > 1024:
                mid = next(iter(self._mux_affinity))
                for k in self._mux_affinity.pop(mid):
                    self._mux_dispatch_t.pop((mid, k), None)
            if len(self._mux_dispatch_t) > 8192:
                self._mux_dispatch_t = {
                    k: t for k, t in self._mux_dispatch_t.items()
                    if now - t < self.MODEL_LOAD_GRACE_S}

    # ------------------------------------------------------------- admission

    def _replicas_snapshot(self) -> List[Any]:
        with self._lock:
            reps = list(self._replicas)
        if not reps:
            self._maybe_refresh()
            with self._lock:
                reps = list(self._replicas)
            if not reps:
                raise RuntimeError(
                    f"deployment {self._deployment!r} has no replicas")
        return reps

    def _capacity_candidates(self, reps: List[Any]) -> List[Any]:
        """Replicas this router may still dispatch to (its own
        dispatched-but-unfinished count is under max_ongoing)."""
        with self._lock:
            limit = self._max_ongoing or 1
            return [r for r in reps
                    if self._inflight.get(self._cache_key(r), 0) < limit]

    def _acquire_replica(self, model_id: str, ctx):
        """Admission valve: pick a replica with spare capacity and reserve
        one slot on it.  When every replica is saturated the caller waits
        in the bounded router queue; a full queue sheds the request with
        ``BackPressureError`` and a spent deadline drops it with
        ``DeadlineExceededError`` — both BEFORE any replica sees it."""
        queued = False
        try:
            while True:
                self._drain_orphans()
                reps = self._replicas_snapshot()
                candidates = self._capacity_candidates(reps)
                if candidates:
                    pick = self.choose_replica(model_id, candidates)
                    with self._lock:
                        key = self._cache_key(pick)
                        if self._inflight.get(key, 0) < (self._max_ongoing
                                                         or 1):
                            self._inflight[key] = \
                                self._inflight.get(key, 0) + 1
                            return pick
                    continue  # lost the reservation race: re-pick
                # saturated: join (or keep) a bounded wait-queue slot
                with self._cond:
                    if not queued:
                        if 0 <= self._max_queued <= self._queued:
                            self._overload.note_shed()
                            raise BackPressureError(
                                deployment=self._deployment,
                                queued=self._queued,
                                limit=self._max_queued,
                                retry_after_s=self._retry_after_hint())
                        self._queued += 1
                        self._overload.note_queued(+1)
                        queued = True
                    if ctx is not None and ctx.expired():
                        self._overload.note_expired()
                        raise DeadlineExceededError(
                            request_id=ctx.request_id,
                            deployment=self._deployment,
                            stage="router-queue",
                            overrun_s=ctx.overrun_s())
                    wait_s = self.QUEUE_POLL_S
                    if ctx is not None:
                        remaining = ctx.remaining_s()
                        if remaining is not None:
                            wait_s = max(0.0, min(wait_s, remaining))
                    self._cond.wait(timeout=wait_s)
                self._maybe_refresh()  # autoscale may have added capacity
        finally:
            if queued:
                with self._cond:
                    self._queued -= 1
                    self._overload.note_queued(-1)

    def _retry_after_hint(self) -> float:
        """Rough time for one queue position to free: assume the oldest
        in-flight request completes within a second — intentionally a
        HINT (HTTP Retry-After), not a promise."""
        return 1.0

    def _release(self, key: str):
        with self._cond:
            n = self._inflight.get(key, 0)
            if n <= 1:
                self._inflight.pop(key, None)
            else:
                self._inflight[key] = n - 1
            self._cond.notify_all()

    def _drain_orphans(self):
        while True:
            try:
                key = self._orphan_releases.popleft()
            except IndexError:
                return
            self._release(key)

    def stop(self):
        """Settle the watcher thread (serve.shutdown); the router object
        is being dropped and must not pin a daemon thread forever."""
        self._stopped.set()
        with self._cond:
            self._cond.notify_all()

    def _track_completion(self, ref, key: str):
        """Register a dispatched ref with the completion watcher, which
        releases the replica slot when the task finishes (success, error,
        cancellation, or replica death — ``wait`` resolves them all)."""
        with self._cond:
            self._outstanding[ref] = key
            self._cond.notify_all()

    def _watch_loop(self):
        while not self._stopped.is_set():
            self._drain_orphans()
            self._report_overload()  # outside the lock: settles counters
            with self._cond:
                if not self._outstanding:
                    self._cond.wait(timeout=5.0)
                refs = list(self._outstanding)
            if not refs:
                continue  # idle tick: loop back (report) and wait again
            try:
                # num_returns=1: wake the moment the FIRST watched ref
                # resolves (a batch drains through instant follow-up
                # waits) instead of spinning at QUEUE_POLL_S granularity;
                # the timeout only bounds how long a ref dispatched AFTER
                # this wait started goes unwatched
                ready, _ = ray_tpu.wait(
                    refs, num_returns=1, timeout=0.1, fetch_local=False)
            except Exception:  # noqa: BLE001 — worker tearing down
                time.sleep(0.5)
                continue
            if not ready:
                continue
            with self._cond:
                keys = [self._outstanding.pop(r) for r in ready
                        if r in self._outstanding]
            for key in keys:
                self._release(key)

    def inflight_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._inflight)

    # -------------------------------------------------------------- choosing

    def choose_replica(self, model_id: str = "",
                       reps: Optional[List[Any]] = None):
        # operate on a snapshot: a concurrent refresh() must not shift
        # indices under us
        if reps is None:
            reps = self._replicas_snapshot()
        if model_id:
            pick, has_holders = self._choose_for_model(model_id, reps)
            if pick is not None:
                return pick
            if not has_holders:
                # cold model: pick a candidate, then atomically
                # claim-or-adopt so CONCURRENT cold requests for the same
                # model coalesce onto one replica instead of each paying
                # a duplicate load (the race affinity-at-dispatch left
                # open)
                cand = self._pow2(reps)
                with self._lock:
                    keys = list(self._mux_affinity.get(model_id, ()))
                    by_key = {self._cache_key(r): r for r in reps}
                    for k in keys:
                        if k in by_key:  # someone claimed first: adopt
                            return by_key[k]
                    key = self._cache_key(cand)
                    lst = self._mux_affinity.setdefault(model_id, [])
                    lst.insert(0, key)
                    self._mux_dispatch_t[(model_id, key)] = time.monotonic()
                return cand
        return self._pow2(reps)

    def _pow2(self, reps: List[Any]):
        if len(reps) == 1:
            return reps[0]
        i, j = self._rng.sample(range(len(reps)), 2)
        return reps[i] if self._probe(reps[i]) <= self._probe(reps[j]) \
            else reps[j]

    def _choose_for_model(self, model_id: str, reps: List[Any]):
        """Prefer a replica that already holds ``model_id`` (avoids a
        load + possible LRU eviction elsewhere); fall back to pow-2 when
        none does or the holder is saturated.  Returns ``(pick,
        has_holders)`` — ``has_holders`` distinguishes "saturated holder,
        deliberately spill elsewhere" from "no holder at all" (only the
        latter may claim-coalesce).  Reference: ``multiplex.py``
        model-aware routing in the pow-2 scheduler."""
        with self._lock:
            keys = list(self._mux_affinity.get(model_id, ()))
            limit = self._max_ongoing or 1
        if keys:
            by_key = {self._cache_key(r): r for r in reps}
            holders = [by_key[k] for k in keys if k in by_key]
            if holders:
                best = min(holders, key=self._probe)
                if self._probe(best) < limit:
                    return best, True
                return None, True
        return None, False

    def note_model(self, model_id: str, replica) -> None:
        """Record that ``replica`` now holds ``model_id`` (front of the
        affinity list); trimmed to a handful — stale entries age out as
        other replicas take over."""
        if not model_id:
            return
        key = self._cache_key(replica)
        with self._lock:
            lst = self._mux_affinity.setdefault(model_id, [])
            if key in lst:
                lst.remove(key)
            lst.insert(0, key)
            for dropped in lst[4:]:
                self._mux_dispatch_t.pop((model_id, dropped), None)
            del lst[4:]
            # provisional until the replica's loaded-model report
            # confirms it (cleared there)
            self._mux_dispatch_t[(model_id, key)] = time.monotonic()

    def note_dispatch(self, replica):
        """Bump the cached queue length so back-to-back requests spread."""
        key = self._cache_key(replica)
        with self._lock:
            hit = self._qlen_cache.get(key)
            if hit:
                self._qlen_cache[key] = (hit[0] + 1, hit[1])

    def note_cancelled(self):
        """Proxy-observed client abandon: count it against this
        deployment (the proxy already issued ``ray_tpu.cancel``)."""
        self._overload.note_cancelled()

    def note_shed(self):
        """Proxy-level shed (its dispatch pool was fully pinned — the
        request never reached this router's queue)."""
        self._overload.note_shed()

    def note_expired(self, bump_metric: bool = True):
        """Proxy/handle-observed deadline expiry past dispatch (e.g. the
        replica reported the drop, or the result wait timed out).
        ``bump_metric=False`` when the originating process (a replica
        dropping a spent request) already bumped the registry counter."""
        self._overload.note_expired(bump_metric=bump_metric)

    # ------------------------------------------------------------- dispatch
    #
    # a dead replica refreshes the set and re-picks, with a short backoff
    # so a controller mid-update has time to land the new replica list
    # (the old bare 3x loop retried EVERY exception instantly, hammering
    # a deployment that was failing for real)
    ASSIGN_RETRY_POLICY = resilience.RetryPolicy(
        max_attempts=3, base_delay_s=0.05, max_delay_s=0.5)

    def _assign_with_retry(self, model_id: str, dispatch):
        """Shared retry harness for unary/streaming dispatch: classified
        errors refresh the replica set and retry with backoff; fatal
        errors (including overload verdicts) surface immediately.
        Returns ``(ref_or_gen, replica_key)``."""

        def _attempt():
            ctx = current_context()
            if ctx is not None and ctx.expired():
                # budget spent before we even touched a replica: reject
                # at the cheapest point instead of executing a discarded
                # answer
                self._overload.note_expired()
                raise DeadlineExceededError(
                    request_id=ctx.request_id, deployment=self._deployment,
                    stage="router", overrun_s=ctx.overrun_s())
            fault_point("serve.router.assign")
            self._maybe_refresh()
            replica = self._acquire_replica(model_id, ctx)
            key = self._cache_key(replica)
            try:
                ref = dispatch(replica,
                               None if ctx is None else ctx.to_dict())
            except BaseException:
                self._release(key)
                raise
            self.note_dispatch(replica)
            self.note_model(model_id, replica)
            return ref, key

        def _on_retry(attempt, err, delay):
            self.refresh()

        return resilience.retry_call(
            _attempt, policy=self.ASSIGN_RETRY_POLICY,
            classify=_assign_retryable, site="serve.router.assign",
            on_retry=_on_retry)

    def assign(self, method: str, args: tuple, kwargs: dict,
               model_id: str = ""):
        ref, key = self._assign_with_retry(
            model_id,
            lambda replica, ctx_d: replica.handle_request.remote(
                method, args, kwargs, multiplexed_model_id=model_id,
                request_context=ctx_d))
        self._track_completion(ref, key)
        return ref

    def assign_streaming(self, method: str, args: tuple, kwargs: dict,
                         model_id: str = ""):
        """Route one streaming request; returns an ObjectRefGenerator
        (wrapped so the replica slot is released when the stream ends,
        errors, or is dropped)."""
        gen, key = self._assign_with_retry(
            model_id,
            lambda replica, ctx_d: replica.handle_request_streaming.options(
                num_returns="streaming").remote(
                    method, args, kwargs,
                    multiplexed_model_id=model_id, request_context=ctx_d))
        return _SlotReleasingStream(gen, self, key)

    # ------------------------------------------------- targeted dispatch
    #
    # Two-stage (disaggregated) serving needs the replica CHOICE and the
    # dispatch to decouple: the decode replica must be reserved before
    # prefill starts, because the prefill stage ships KV blocks to that
    # specific replica's channel.  These helpers expose the admission
    # valve (reserve) and the dispatch separately, with the same
    # slot-accounting/queueing/shed semantics as assign().

    def acquire_replica(self, ctx=None):
        """Reserve one admission slot on a chosen replica; returns
        ``(replica, key)``.  Blocks in the bounded router queue when the
        pool is saturated; sheds with ``BackPressureError`` / expires
        with ``DeadlineExceededError`` exactly like ``assign``.  The
        caller MUST end the reservation via ``dispatch_to`` (slot
        released on completion) or ``release_replica``."""
        self._maybe_refresh()
        replica = self._acquire_replica("", ctx)
        return replica, self._cache_key(replica)

    def release_replica(self, key: str) -> None:
        """Give back a reservation acquired via ``acquire_replica``
        without dispatching (stage-1 failure)."""
        self._release(key)

    def dispatch_to(self, replica, key: str, method: str, args: tuple,
                    kwargs: dict, *, streaming: bool = False):
        """Dispatch to an already-reserved replica.  Unary returns the
        ref (completion watcher releases the slot); streaming returns a
        ``_SlotReleasingStream``.  On dispatch failure the reservation is
        released before the error surfaces."""
        ctx = current_context()
        ctx_d = None if ctx is None else ctx.to_dict()
        try:
            if streaming:
                out = replica.handle_request_streaming.options(
                    num_returns="streaming").remote(
                        method, args, kwargs, request_context=ctx_d)
            else:
                out = replica.handle_request.remote(
                    method, args, kwargs, request_context=ctx_d)
        except BaseException:
            self._release(key)
            raise
        self.note_dispatch(replica)
        if streaming:
            return _SlotReleasingStream(out, self, key)
        self._track_completion(out, key)
        return out


class _SlotReleasingStream:
    """Iterator proxy over a streaming dispatch that gives the replica's
    admission slot back exactly once — on exhaustion, error, explicit
    close, or garbage collection (a client that dropped the stream
    without draining it must not leak capacity forever)."""

    def __init__(self, gen, router: Router, key: str):
        self._gen = gen
        self._router = router
        self._key = key
        self._released = False

    def _release(self):
        if not self._released:
            self._released = True
            self._router._release(self._key)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._gen)
        except BaseException:
            self._release()
            raise

    def close(self):
        try:
            close = getattr(self._gen, "close", None)
            if close is not None:
                close()
        finally:
            self._release()

    def __del__(self):
        # GC context: must not take the router lock (the collector can
        # fire while the owning thread holds it) — hand the release to
        # the router's orphan queue instead
        if not self._released:
            self._released = True
            self._router._orphan_releases.append(self._key)

    def __getattr__(self, name):
        return getattr(self._gen, name)


class DeploymentHandle:
    """Client-side handle; composition-safe (picklable into replicas)."""

    # routers are shared per (deployment) across handle copies in one
    # process so model-affinity state survives handle.options() chains
    _routers: Dict[str, Router] = {}
    _routers_lock = threading.Lock()

    def __init__(self, deployment_name: str, method_name: str = "__call__",
                 multiplexed_model_id: str = ""):
        self._deployment = deployment_name
        self._method = method_name
        self._mux_id = multiplexed_model_id

    def __reduce__(self):
        return (DeploymentHandle,
                (self._deployment, self._method, self._mux_id))

    def options(self, method_name: Optional[str] = None, *,
                multiplexed_model_id: Optional[str] = None
                ) -> "DeploymentHandle":
        """Reference: ``handle.options(multiplexed_model_id="m1")``
        routes to a replica that already has model "m1" loaded."""
        return DeploymentHandle(
            self._deployment,
            method_name if method_name is not None else self._method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._mux_id)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._deployment, name, self._mux_id)

    def _get_router(self) -> Router:
        with DeploymentHandle._routers_lock:
            router = DeploymentHandle._routers.get(self._deployment)
            if router is None:
                from ray_tpu.serve.controller import get_controller

                router = Router(self._deployment, get_controller())
                DeploymentHandle._routers[self._deployment] = router
            return router

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        ref = self._get_router().assign(self._method, args, kwargs,
                                        model_id=self._mux_id)
        return DeploymentResponse(ref)

    def remote_streaming(self, *args, **kwargs) -> "DeploymentStreamingResponse":
        """Call a generator method of the deployment; iterate the result
        to receive items as the replica yields them (reference:
        handle.options(stream=True))."""
        gen = self._get_router().assign_streaming(
            self._method, args, kwargs, model_id=self._mux_id)
        return DeploymentStreamingResponse(gen)


class DeploymentStreamingResponse:
    """Iterator over a streaming deployment call's yielded values."""

    def __init__(self, ref_gen):
        self._gen = ref_gen

    def __iter__(self):
        import ray_tpu

        for ref in self._gen:
            # consumer-facing streaming iterator: blocking for the next
            # yielded value on the caller's own thread IS the API
            yield ray_tpu.get(ref)  # raylint: disable=bounded-blocking -- caller-thread streaming consumption, not a control thread; replica death resolves the ref with an error

    @property
    def ref_generator(self):
        return self._gen


class TwoStageHandle:
    """Disaggregated two-stage dispatch: prefill → handoff token → decode.

    Stage 1 goes through the prefill deployment's ordinary router
    (queueing on the prefill pool is the autoscaler's queue-depth
    signal).  The decode replica is RESERVED first — the prefill stage
    ships KV blocks into that specific replica's landing channel — then
    stage 2 dispatches the handoff token to the reserved replica, unary
    or streaming, so the token fan-out the client sees is byte-identical
    to the colocated path.

    A decode replica that dies mid-request (or mid-stream) triggers a
    bounded **re-prefill**: the whole two-stage flow re-runs on a
    healthy pair within the request's remaining deadline, counted in
    ``reprefills``; already-delivered stream chunks are deduplicated by
    index.  Overload verdicts (``BackPressureError``,
    ``DeadlineExceededError``) from either stage surface unchanged —
    they are never retried here (the proxy owns that decision).
    """

    # generous stage-1 bound for deadline-less direct use: a wedged
    # prefill replica must surface as an error, not a permanent hang
    DEFAULT_STAGE_TIMEOUT_S = 300.0

    def __init__(self, prefill: "DeploymentHandle",
                 decode: "DeploymentHandle", *,
                 prefill_method: str = "prefill",
                 decode_method: str = "decode",
                 decode_stream_method: str = "decode_stream",
                 max_reprefills: int = 1):
        self._prefill = prefill
        self._decode = decode
        self._m1 = prefill_method
        self._m2 = decode_method
        self._m2s = decode_stream_method
        self._max_reprefills = max_reprefills
        self.stats = {"requests": 0, "reprefills": 0}

    def _remaining(self, ctx, deadline: Optional[float] = None) -> float:
        """Remaining budget: the tighter of the request context's
        deadline and the caller's explicit bound (monotonic)."""
        rem = self.DEFAULT_STAGE_TIMEOUT_S
        if ctx is not None:
            ctx_rem = ctx.remaining_s()
            if ctx_rem is not None:
                rem = max(0.0, ctx_rem)
        if deadline is not None:
            rem = min(rem, max(0.0, deadline - time.monotonic()))
        return rem

    def _dispatch(self, body, *, streaming: bool,
                  deadline: Optional[float] = None):
        """One full two-stage attempt; returns the stage-2 ref/stream."""
        ctx = current_context()
        r2 = self._decode._get_router()
        replica, key = r2.acquire_replica(ctx)
        try:
            token = self._prefill.options(method_name=self._m1).remote(
                body, replica).result(
                    timeout=self._remaining(ctx, deadline))
        except BaseException:
            r2.release_replica(key)
            raise
        return r2.dispatch_to(
            replica, key, self._m2s if streaming else self._m2,
            (token, body), {}, streaming=streaming)

    _reprefill_counter = None

    def _note_reprefill(self):
        self.stats["reprefills"] += 1
        try:
            from ray_tpu.util import metrics

            cls = TwoStageHandle
            if cls._reprefill_counter is None:
                # cached: Metric.__init__ re-registers (and would reset)
                cls._reprefill_counter = metrics.Counter(
                    "llm_reprefills",
                    "two-stage requests re-prefilled after a "
                    "decode-replica failure")
            cls._reprefill_counter.inc()
        except Exception:  # noqa: BLE001 — visibility never fails a request
            pass

    def _retryable(self, err: BaseException, ctx,
                   deadline: Optional[float] = None) -> bool:
        """A mid-flight replica/transport death is worth a re-prefill on
        a healthy pair; overload verdicts, spent budgets (request
        deadline OR the caller's explicit bound), and non-``Exception``
        BaseExceptions are not — a client disconnect surfaces as
        ``GeneratorExit`` at the yield, and re-dispatching a whole
        prefill+ship+decode nobody will read (then yielding into the
        closed generator) is exactly wrong."""
        if not isinstance(err, Exception):
            return False  # GeneratorExit / KeyboardInterrupt / SystemExit
        if isinstance(err, (BackPressureError, DeadlineExceededError)):
            return False
        if ctx is not None and ctx.expired():
            return False
        if deadline is not None and time.monotonic() >= deadline:
            return False
        return True

    def _pre_retry(self):
        """Refresh the decode replica set (the controller prunes a
        killed replica within a tick) and back off briefly so the next
        attempt doesn't land straight back on the corpse."""
        try:
            self._decode._get_router().refresh()
        except Exception:  # noqa: BLE001 — next attempt retries anyway
            pass
        time.sleep(0.25)

    def call(self, body, timeout: Optional[float] = None):
        """Blocking unary request through both stages.  ``timeout``
        bounds the WHOLE call including any re-prefill attempts — with
        no surrounding request scope, a deadline-carrying context is
        minted from it so the router-queue waits of BOTH pools honor
        the bound too (they block on the context, not the caller's
        clock)."""
        import contextlib

        import ray_tpu
        from ray_tpu.serve.context import RequestContext, scope

        self.stats["requests"] += 1
        ctx = current_context()
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        minted = contextlib.nullcontext()
        if ctx is None and timeout is not None:
            ctx = RequestContext(uuid.uuid4().hex,
                                 deadline_s=time.time() + timeout)
            minted = scope(ctx)
        attempts = self._max_reprefills + 1
        with minted:
            for attempt in range(attempts):
                try:
                    ref = self._dispatch(body, streaming=False,
                                         deadline=deadline)
                    return ray_tpu.get(
                        ref, timeout=self._remaining(ctx, deadline))
                except BaseException as e:  # noqa: BLE001 — classified
                    if attempt + 1 >= attempts \
                            or not self._retryable(e, ctx, deadline):
                        raise
                    self._note_reprefill()
                    self._pre_retry()

    @staticmethod
    def _stream_resumable(body) -> bool:
        """Resume-at-index after a mid-stream death splices chunks from
        TWO generations — only coherent when decoding is deterministic.
        Greedy (``temperature == 0``) requests resume; sampled ones
        surface the error once chunks were delivered (the engine's
        default temperature is 0.7, so an absent field counts as
        sampled)."""
        if not isinstance(body, dict):
            return False
        try:
            return float(body.get("temperature", 0.7) or 0.0) == 0.0
        except (TypeError, ValueError):
            return False

    def stream(self, body):
        """Streaming request: yields the decode replica's chunks (each
        carries ``index``; the final chunk carries ``done``).  A decode
        death mid-stream re-prefills and resumes from the first
        undelivered index — for greedy streams; a sampled stream that
        already delivered chunks cannot be coherently resumed and
        surfaces the error instead (an untouched stream always
        retries)."""
        self.stats["requests"] += 1
        ctx = current_context()
        attempts = self._max_reprefills + 1
        delivered = 0
        for attempt in range(attempts):
            try:
                stream = self._dispatch(body, streaming=True)
                for chunk in DeploymentStreamingResponse(stream):
                    if chunk.get("done"):
                        yield chunk
                        return
                    idx = chunk.get("index", delivered)
                    if idx < delivered:
                        continue  # replayed after a re-prefill: dedup
                    delivered = idx + 1
                    yield chunk
                return  # stream ended without a done marker: complete
            except BaseException as e:  # noqa: BLE001 — classified below
                if attempt + 1 >= attempts or not self._retryable(e, ctx) \
                        or (delivered > 0
                            and not self._stream_resumable(body)):
                    raise
                self._note_reprefill()
                self._pre_retry()
