"""Communicator ABC for compiled-graph device transport.

Parity: ``python/ray/experimental/channel/communicator.py:19`` (initialize /
send / recv / allreduce / allgather / reducescatter).  The reference's
production impl is NCCL (``nccl_group.py:22``); here the production path is
XLA over ICI — device arrays move either inside one jitted program (in-mesh
fusion, the fast path) or host-staged over the shm channel (the portable
path).  ``CpuCommunicator`` is the test/emulation backend, the same trick as
the reference's ``cpu_communicator.py``.
"""

from __future__ import annotations

import abc
from typing import Any, List, Optional


class Communicator(abc.ABC):
    @abc.abstractmethod
    def initialize(self, rank: int) -> None: ...

    @abc.abstractmethod
    def get_rank(self, actor) -> int: ...

    @abc.abstractmethod
    def get_world_size(self) -> int: ...

    @abc.abstractmethod
    def send(self, tensor: Any, peer_rank: int) -> None: ...

    @abc.abstractmethod
    def recv(self, shape, dtype, peer_rank: int) -> Any: ...

    @abc.abstractmethod
    def allreduce(self, tensor: Any, op: str = "sum") -> Any: ...

    def allgather(self, tensor: Any) -> List[Any]:
        raise NotImplementedError

    def reducescatter(self, tensor: Any, op: str = "sum") -> Any:
        raise NotImplementedError

    @abc.abstractmethod
    def destroy(self) -> None: ...


class CpuCommunicator(Communicator):
    """Host-memory communicator over the framework's collective groups."""

    def __init__(self, world_size: int, group_name: str,
                 actor_ranks: Optional[dict] = None):
        self.world_size = world_size
        self.group_name = group_name
        self._rank: Optional[int] = None
        self._actor_ranks = actor_ranks or {}

    def initialize(self, rank: int) -> None:
        from ray_tpu.util import collective as col

        self._rank = rank
        if not col.is_group_initialized(self.group_name):
            col.init_collective_group(
                self.world_size, rank, backend="tcp",
                group_name=self.group_name)

    def get_rank(self, actor) -> int:
        key = getattr(actor, "_actor_id", None) or actor
        rank = self._actor_ranks.get(key)
        if rank is None:
            # a silent -1 here becomes a wrong-peer send downstream —
            # name the actor instead
            raise ValueError(
                f"actor {actor!r} is not a member of communicator group "
                f"{self.group_name!r} (known ranks: "
                f"{sorted(map(repr, self._actor_ranks))})")
        return rank

    def get_world_size(self) -> int:
        return self.world_size

    def send(self, tensor, peer_rank: int) -> None:
        from ray_tpu.util import collective as col

        col.send(tensor, peer_rank, group_name=self.group_name)

    def recv(self, shape, dtype, peer_rank: int):
        from ray_tpu.util import collective as col

        return col.recv(shape, dtype, peer_rank, group_name=self.group_name)

    def allreduce(self, tensor, op: str = "sum"):
        from ray_tpu.util import collective as col
        from ray_tpu.util.collective.types import ReduceOp

        ops = {"sum": ReduceOp.SUM, "product": ReduceOp.PRODUCT,
               "min": ReduceOp.MIN, "max": ReduceOp.MAX}
        return col.allreduce(tensor, group_name=self.group_name, op=ops[op])

    def allgather(self, tensor):
        from ray_tpu.util import collective as col

        return col.allgather(tensor, group_name=self.group_name)

    def destroy(self) -> None:
        from ray_tpu.util import collective as col

        try:
            if col.is_group_initialized(self.group_name):
                col.destroy_collective_group(self.group_name)
        except Exception:
            pass


class TpuCommunicator(CpuCommunicator):
    """Device-array communicator: host-staged today, in-mesh when fused.

    Out-of-graph eager send/recv between separate TPU processes has no
    public ICI API (SURVEY.md §7 hard-part 1), so device arrays are staged
    through host shm (device_get → channel → device_put) — correct on any
    topology, DCN-bandwidth-bound.  The fast path is *in-mesh fusion*: when
    every node of a DAG edge lives in one process holding a mesh, keep the
    whole step under one jit so values stay as jax.Arrays and XLA moves
    them over ICI inside the compiled program (no channel hop at all).

    Compiled-graph edges no longer go through this class for bulk data:
    the tier-negotiated ``transport.EdgeTransport`` (device frames +
    alias-guarded ``device_put`` from the shm view) is the channel plane
    — see ``experimental/channel/transport.py`` and
    docs/compiled_graphs.md.
    """

    def send(self, tensor, peer_rank: int) -> None:
        import jax

        super().send(jax.device_get(tensor), peer_rank)

    def recv(self, shape, dtype, peer_rank: int):
        import jax

        host = super().recv(shape, dtype, peer_rank)
        return jax.device_put(host)
