"""Serve request context: request id + absolute deadline, minted at the
ingress and carried through every hop of the serving data plane.

Reference: Ray Serve's ``_serve_request_context`` contextvar
(``python/ray/serve/context.py``) plus the HTTP ``request_timeout_s`` /
gRPC-deadline plumbing in ``serve/_private/proxy.py``.  The proxies mint
one :class:`RequestContext` per route invocation (the tooling test
``test_every_proxy_route_mints_request_context`` enforces this); the
router checks the budget before dispatch, the replica checks it again
before invoking the user callable, and nested ``DeploymentHandle`` calls
made inside a replica inherit the REMAINING budget automatically through
the contextvar — a composition chain shares one deadline instead of each
hop resetting the clock.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import threading
import time
import uuid
from typing import Any, Dict, Iterator, Optional


@dataclasses.dataclass(frozen=True)
class ReplicaContext:
    """Identity of the replica hosting the current callable (reference:
    ``serve.get_replica_context``) — deployment name + replica id, so a
    callable can label what it publishes (e.g. the LLM engine-stats
    records the pool autoscaler reads) without threading its own name
    through init args.  Lives here (not in ``replica.py``) because the
    replica ACTOR class ships by value; this module is always imported
    by reference, so its global is the one every reader sees."""

    deployment: str
    replica_id: str


_replica_context: Optional[ReplicaContext] = None


def _set_replica_context(ctx: Optional[ReplicaContext]) -> None:
    global _replica_context
    _replica_context = ctx


def get_replica_context() -> Optional[ReplicaContext]:
    """The hosting replica's context, or None outside a replica."""
    return _replica_context


@dataclasses.dataclass(frozen=True)
class RequestContext:
    """One serving request's identity and end-to-end budget.

    ``deadline_s`` is an ABSOLUTE ``time.time()`` instant (``None`` means
    no budget — e.g. a driver calling a handle directly without opting
    in).  Wall-clock is the right base despite NTP wobble: the deadline
    must survive pickling across processes on (potentially) different
    hosts, where a monotonic reading is meaningless.
    """

    request_id: str
    deadline_s: Optional[float] = None
    # causal trace context (tracing.SpanContext.to_dict()): one fresh
    # trace per request, minted at the ingress with the RequestContext
    # itself; every hop that installs the request scope also installs
    # this, so replica-side task submissions parent to the request span.
    trace_ctx: Optional[Dict[str, Any]] = None

    def remaining_s(self) -> Optional[float]:
        if self.deadline_s is None:
            return None
        return self.deadline_s - time.time()

    def expired(self) -> bool:
        return self.deadline_s is not None and time.time() > self.deadline_s

    def overrun_s(self) -> float:
        if self.deadline_s is None:
            return 0.0
        return max(0.0, time.time() - self.deadline_s)

    def to_dict(self) -> Dict[str, Any]:
        return {"request_id": self.request_id, "deadline_s": self.deadline_s,
                "trace_ctx": self.trace_ctx}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]
                  ) -> Optional["RequestContext"]:
        if not d:
            return None
        return cls(request_id=d.get("request_id", ""),
                   deadline_s=d.get("deadline_s"),
                   trace_ctx=d.get("trace_ctx"))


_request_ctx: contextvars.ContextVar[Optional[RequestContext]] = \
    contextvars.ContextVar("ray_tpu_serve_request_context", default=None)


def current_context() -> Optional[RequestContext]:
    """The in-flight request's context, or None outside a request scope."""
    return _request_ctx.get()


def new_request_context(*, timeout_s: Optional[float],
                        request_id: Optional[str] = None) -> RequestContext:
    """Mint an ingress context: ``timeout_s`` from now becomes the
    request's ABSOLUTE deadline, and a FRESH trace is rooted here — one
    causal tree per request.  Every proxy route must call this (with a
    real timeout) before touching a deployment handle."""
    from ray_tpu._private import tracing

    rid = request_id or uuid.uuid4().hex[:16]
    trace_ctx = None
    if tracing.is_enabled():
        ctx = tracing.SpanContext(tracing.new_trace_id(),
                                  tracing.new_span_id(), None)
        # record the request root at mint time (near-zero duration): the
        # tree's spans parent to it, and an ingress can't know when the
        # last hop retires — the per-hop spans carry the durations
        now = time.time()
        tracing.record_span("serve.request", now, now, ctx, kind="request",
                            attrs={"request_id": rid})
        trace_ctx = ctx.to_dict()
    return RequestContext(
        request_id=rid,
        deadline_s=None if timeout_s is None else time.time() + timeout_s,
        trace_ctx=trace_ctx)


@contextlib.contextmanager
def scope(ctx: Optional[RequestContext]) -> Iterator[None]:
    """Install ``ctx`` as the current request context for the duration.

    Used by the proxies around dispatch (``run_in_executor`` does NOT
    propagate contextvars, so the executor callable re-enters the scope
    explicitly) and by the replica around the user callable so nested
    handle calls inherit the remaining budget.
    """
    from ray_tpu._private import tracing

    token = _request_ctx.set(ctx)
    # carry the trace context alongside the deadline: handle calls made
    # inside the scope parent to the request's trace root
    trace_token = None
    span_ctx = tracing.SpanContext.from_dict(
        ctx.trace_ctx if ctx is not None else None)
    if span_ctx is not None:
        trace_token = tracing.set_current(span_ctx)
    try:
        yield
    finally:
        if trace_token is not None:
            tracing.reset_current(trace_token)
        _request_ctx.reset(token)


@contextlib.contextmanager
def request_scope(*, timeout_s: Optional[float],
                  request_id: Optional[str] = None) -> Iterator[RequestContext]:
    """Mint-and-install in one step — the driver-side opt-in for handle
    calls that want a budget without going through a proxy::

        with serve.context.request_scope(timeout_s=2.0):
            handle.remote(body).result()   # whole chain shares the 2s
    """
    ctx = new_request_context(timeout_s=timeout_s, request_id=request_id)
    with scope(ctx):
        yield ctx


# ---------------------------------------------------------------------------
# overload visibility: per-deployment shed/expired/cancelled counters
# ---------------------------------------------------------------------------


class OverloadStats:
    """Per-deployment degradation counters, double-published: into the
    process-local ``util.metrics`` registry (→ GCS KV → dashboard
    ``/metrics``) and — via the router's throttled report — to the serve
    controller, which aggregates across reporter processes for
    ``serve.status()`` / ``util.state.list_serve_deployments()`` /
    ``raytpu status`` / the dashboard serve panel."""

    _metrics_lock = threading.Lock()
    _metrics: Dict[str, Any] = {}

    def __init__(self, deployment: str):
        self._deployment = deployment
        self._lock = threading.Lock()
        self.shed = 0        # rejected at admission (BackPressureError)
        self.expired = 0     # dropped with the deadline already spent
        self.cancelled = 0   # abandoned by the client and cancelled
        self.queued = 0      # currently waiting for replica capacity
        self.peak_queued = 0

    @classmethod
    def _counter(cls, name: str, description: str):
        # lazy so importing serve never spawns the metrics publisher; the
        # first real overload event registers the counters
        with cls._metrics_lock:
            m = cls._metrics.get(name)
            if m is None:
                from ray_tpu.util.metrics import Counter

                m = Counter(name, description, tag_keys=("deployment",))
                cls._metrics[name] = m
            return m

    def _bump_metric(self, name: str, description: str):
        try:
            self._counter(name, description).inc(
                tags={"deployment": self._deployment})
        except Exception:  # noqa: BLE001 — visibility must never fail a request
            pass

    def note_shed(self):
        with self._lock:
            self.shed += 1
        self._bump_metric("serve_requests_shed",
                          "requests rejected at admission (backpressure)")

    def note_expired(self, bump_metric: bool = True):
        with self._lock:
            self.expired += 1
        if bump_metric:
            self._bump_metric("serve_requests_expired",
                              "requests dropped with their deadline spent")

    def note_cancelled(self):
        with self._lock:
            self.cancelled += 1
        self._bump_metric("serve_requests_cancelled",
                          "in-flight requests cancelled after client abandon")

    def note_queued(self, delta: int):
        with self._lock:
            self.queued += delta
            self.peak_queued = max(self.peak_queued, self.queued)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"shed": self.shed, "expired": self.expired,
                    "cancelled": self.cancelled, "queued": self.queued,
                    "peak_queued": self.peak_queued}
