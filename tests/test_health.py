"""Hardware health plane: straggler & silent-degradation detection.

Covers the pure outlier math (median/MAD robust z, hysteresis), the
passive signal extractors (step-record scoring with collective-wait
asymmetry attribution, pending ages, edge latencies), the SDC canary,
verdict aggregation + stale sweep, the HealthMonitor's confirm/acquit/
quarantine legs (via the ``probe_fn`` hook — no cluster), and the GCS
health ladder (SUSPECT -> QUARANTINED -> drain, sticky, exclusions)
against in-process servers.
"""

import asyncio
import json
import os
import tempfile
import time

import pytest

from ray_tpu.util import health as H


# ---------------------------------------------------------------------------
# robust statistics
# ---------------------------------------------------------------------------


def test_median_and_mad_basics():
    assert H.median([3.0, 1.0, 2.0]) == 2.0
    assert H.median([1.0, 2.0, 3.0, 4.0]) == 2.5
    assert H.mad([1.0, 1.0, 1.0]) == 0.0
    assert H.mad([1.0, 2.0, 3.0, 100.0]) == 1.0  # outlier cannot inflate
    with pytest.raises(ValueError):
        H.median([])


def test_robust_z_identical_samples_score_zero():
    zs = H.robust_z([0.1] * 8)
    assert zs == [0.0] * 8  # noise floor, not division by zero


def test_robust_z_flags_the_slow_sample():
    values = [0.10, 0.11, 0.10, 0.09, 0.10, 0.30]
    zs = H.robust_z(values)
    assert zs[-1] > 3.5
    assert all(abs(z) < 3.5 for z in zs[:-1])


def test_mad_outliers_one_sided_ignores_fast_ranks():
    # one unusually FAST sample is not a health problem
    values = [0.10, 0.10, 0.11, 0.10, 0.01]
    assert H.mad_outliers(values, 3.5) == []
    assert 4 in H.mad_outliers(values, 3.5, one_sided=False)


def test_hysteresis_requires_consecutive_windows():
    t = H.HysteresisTracker(3)
    assert t.observe(["a"], ["a", "b"]) == []
    assert t.observe(["a"], ["a", "b"]) == []
    assert t.observe(["a"], ["a", "b"]) == ["a"]      # exactly at N
    assert t.observe(["a"], ["a", "b"]) == []         # promoted once
    # a clean window resets the streak
    t2 = H.HysteresisTracker(2)
    t2.observe(["a"], ["a"])
    t2.observe([], ["a"])                              # clean: reset
    assert t2.streak("a") == 0
    assert t2.observe(["a"], ["a"]) == []
    assert t2.observe(["a"], ["a"]) == ["a"]


def test_hysteresis_absent_from_population_keeps_streak():
    # a rank that published no record this window is unknown, not clean
    t = H.HysteresisTracker(2)
    t.observe(["a"], ["a", "b"])
    t.observe([], ["b"])                               # "a" absent
    assert t.streak("a") == 1
    assert t.observe(["a"], ["a", "b"]) == ["a"]


def test_hysteresis_rejects_zero_windows():
    with pytest.raises(ValueError):
        H.HysteresisTracker(0)


# ---------------------------------------------------------------------------
# step-record scoring: the collective-wait asymmetry attribution
# ---------------------------------------------------------------------------


def _rec(rank, wall, coll, node="", steps=8):
    return {"group": "g", "rank": rank, "node_id": node,
            "recent": {"steps": steps, "wall_s_per_step": wall,
                       "buckets_s": {"compute": max(0.0, wall - coll),
                                     "collective_wait": coll}}}


def test_score_step_records_attributes_the_straggler():
    # synchronous mesh: every rank's WALL is identical (they all wait
    # for the slowest); the straggler is the one with high OWN time and
    # near-zero collective wait
    records = [_rec(0, 0.30, 0.20), _rec(1, 0.30, 0.21),
               _rec(2, 0.30, 0.00), _rec(3, 0.30, 0.19)]
    score = H.score_step_records(records, mad_threshold=3.5)
    assert score["suspects"] == [2]
    assert score["ranks"][2]["own_s"] == pytest.approx(0.30)
    assert score["ranks"][0]["own_s"] == pytest.approx(0.10)
    assert score["ranks"][2]["z"] > 3.5


def test_score_step_records_high_wait_outlier_is_not_a_straggler():
    # an own-time outlier that ALSO waits above the median is blocked on
    # someone else (e.g. its input pipeline stalls mid-collective) — not
    # the rank everyone waits for
    records = [_rec(0, 0.12, 0.02), _rec(1, 0.12, 0.02),
               _rec(2, 0.42, 0.10), _rec(3, 0.12, 0.02)]
    score = H.score_step_records(records, mad_threshold=3.5)
    assert score["suspects"] == []


def test_score_step_records_needs_three_ranks():
    records = [_rec(0, 0.1, 0.05), _rec(1, 0.4, 0.0)]
    assert H.score_step_records(records)["suspects"] == []


def test_score_step_records_prefers_recent_window():
    # lifetime means say healthy; the recent window says degraded — the
    # fresh signal must win (a long healthy history would otherwise
    # dilute a newly sick rank below threshold)
    records = [_rec(0, 0.30, 0.20), _rec(1, 0.30, 0.20),
               _rec(3, 0.30, 0.20)]
    degraded = {"group": "g", "rank": 2, "node_id": "",
                "steps": 500, "step_wall_s": 0.11,
                "buckets_s": {"compute": 0.10, "collective_wait": 0.01},
                "recent": {"steps": 8, "wall_s_per_step": 0.30,
                           "buckets_s": {"compute": 0.30,
                                         "collective_wait": 0.0}}}
    score = H.score_step_records(records + [degraded])
    assert score["suspects"] == [2]


def test_score_step_records_falls_back_to_lifetime_breakdown():
    # a record with no recent window (publisher predates it, or empty
    # history) scores on the lifetime breakdown block's step_wall_s
    records = [_rec(0, 0.30, 0.20), _rec(1, 0.30, 0.20),
               {"group": "g", "rank": 2, "steps": 40, "step_wall_s": 0.30,
                "buckets_s": {"compute": 0.30, "collective_wait": 0.0}},
               _rec(3, 0.30, 0.20)]
    score = H.score_step_records(records)
    assert score["suspects"] == [2]


def test_noisy_healthy_cluster_never_promotes():
    """Acceptance gate: realistic jitter over many windows must never
    reach a verdict — the hysteresis + robust-z stack absorbs it."""
    import random

    rng = random.Random(7)
    tracker = H.HysteresisTracker(3)
    promoted = []
    for _window in range(60):
        records = []
        for rank in range(8):
            wall = 0.30 * rng.uniform(0.9, 1.1)
            coll = 0.18 * rng.uniform(0.7, 1.3)
            records.append(_rec(rank, wall, min(coll, wall)))
        score = H.score_step_records(records, mad_threshold=3.5)
        promoted += tracker.observe(score["suspects"],
                                    list(score["ranks"]))
    assert promoted == []


def test_3x_straggler_promotes_within_k_windows():
    """The flip side: a genuine 3x-slow rank must be promoted after
    exactly the hysteresis window count, jitter and all."""
    import random

    rng = random.Random(11)
    windows = 3
    tracker = H.HysteresisTracker(windows)
    for w in range(1, 10):
        records = []
        for rank in range(8):
            if rank == 5:
                own = 0.30 * rng.uniform(0.95, 1.05) * 3.0
                coll = 0.002
            else:
                own = 0.10 * rng.uniform(0.95, 1.05)
                coll = 0.0
            wall = own + coll + 0.0  # healthy ranks' wait added below
            records.append(_rec(rank, wall, coll))
        # healthy ranks park in the collective waiting for rank 5
        for rec in records:
            if rec["rank"] != 5:
                gap = 0.92 - rec["recent"]["wall_s_per_step"]
                rec["recent"]["buckets_s"]["collective_wait"] = gap
                rec["recent"]["wall_s_per_step"] = 0.92
        score = H.score_step_records(records, mad_threshold=3.5)
        promoted = tracker.observe(score["suspects"],
                                   list(score["ranks"]))
        if promoted:
            assert promoted == [5]
            assert w == windows, f"promoted at window {w}, want {windows}"
            return
    pytest.fail("straggler never promoted")


# ---------------------------------------------------------------------------
# pending ages, edge latency, SDC canary, HBM stats
# ---------------------------------------------------------------------------


def test_pending_age_lags():
    now = 1000.0
    members = [{"rank": 0, "inflight": {"op": "allreduce",
                                        "t_start": 998.0}},
               {"rank": 1, "inflight": None},
               {"rank": 2, "inflight": {"op": "allreduce",
                                        "t_start": 999.5}}]
    ages = H.pending_age_lags(members, now=now)
    assert ages == {0: 2.0, 2: 0.5}


def test_edge_latency_tracker_ewma_and_reset():
    H.reset_edge_latency()
    try:
        H.note_edge_latency("a->b", 0.1)
        H.note_edge_latency("a->b", 0.2)
        snap = H.edge_latency_snapshot()
        assert snap["a->b"]["count"] == 2
        assert snap["a->b"]["last_s"] == pytest.approx(0.2)
        assert 0.1 < snap["a->b"]["ewma_s"] < 0.2
        # snapshot is a copy: mutating it must not leak back
        snap["a->b"]["count"] = 999
        assert H.edge_latency_snapshot()["a->b"]["count"] == 2
    finally:
        H.reset_edge_latency()
    assert H.edge_latency_snapshot() == {}


def test_sdc_digest_is_deterministic_and_seed_sensitive():
    a = H.sdc_digest(seed=7)
    assert a == H.sdc_digest(seed=7)          # bit-exact, always
    assert a != H.sdc_digest(seed=8)          # actually depends on input
    assert len(a) == 64                        # sha256 hex


def test_device_memory_stats_shape():
    # conftest imports jax (cpu backend), so rows must come back with at
    # least the device identity; occupancy only where the backend
    # exposes memory_stats()
    rows = H.device_memory_stats()
    assert isinstance(rows, list)
    for row in rows:
        assert row["device"]
        assert "kind" in row
        if "occupancy" in row:
            assert 0.0 <= row["occupancy"] <= 1.0


# ---------------------------------------------------------------------------
# verdict records: aggregation + stale sweep
# ---------------------------------------------------------------------------


def test_aggregate_health_records_orders_and_sweeps():
    now = time.time()
    records = [
        {"kind": "rank", "subject": "g/1", "health": "SUSPECT",
         "ts": now - 5},
        {"kind": "node", "subject": "nodeZ", "health": "HEALTHY",
         "ts": now - 5},
        {"kind": "node", "subject": "nodeA", "health": "QUARANTINED",
         "ts": now - 5},
        # stale: a monitor that died must not pin its verdict forever
        {"kind": "node", "subject": "ghost", "health": "QUARANTINED",
         "ts": now - H.STALE_S - 1},
    ]
    out = H.aggregate_health_records(records, now=now)
    assert [r["subject"] for r in out] == ["nodeA", "g/1", "nodeZ"]


def test_health_verdict_roundtrip():
    v = H.HealthVerdict(kind="node", subject="n1", health=H.QUARANTINED,
                        reason="probe 3.1x slower than reference",
                        node_id="n1", signals={"probe_ratio": 3.1},
                        hw_confirmed=False, suspect_ts=1.0,
                        quarantine_ts=2.0)
    d = json.loads(json.dumps(v.to_dict()))
    assert d["health"] == "QUARANTINED"
    assert d["signals"]["probe_ratio"] == 3.1
    assert d["quarantine_ts"] == 2.0


# ---------------------------------------------------------------------------
# HealthMonitor: confirm / acquit / SDC legs (probe_fn hook, no cluster)
# ---------------------------------------------------------------------------


def _make_monitor(step_records, nodes, probe_fn, **kw):
    from ray_tpu._private.health_plane import HealthMonitor

    kw.setdefault("interval_s", 0.05)
    kw.setdefault("suspect_windows", 2)
    kw.setdefault("probe_factor", 2.0)
    mon = HealthMonitor(probe_fn=probe_fn, **kw)
    table = {f"step_breakdown/g/{r['rank']}": json.dumps(r).encode()
             for r in step_records}
    mon._kv_prefix = (
        lambda prefix, ns: dict(table) if ns == "train" else {})
    mon._alive_nodes = lambda: [
        {"node_id": n, "alive": True, "health": "HEALTHY"} for n in nodes]
    ladder = []
    mon._set_node_health = (
        lambda node_id, health, reason, hw_confirmed=False:
        ladder.append((node_id, health, hw_confirmed)))
    return mon, ladder


_STRAGGLER_RECORDS = [_rec(0, 0.30, 0.20, node="nodeA"),
                      _rec(1, 0.30, 0.21, node="nodeB"),
                      _rec(2, 0.30, 0.00, node="nodeC"),
                      _rec(3, 0.30, 0.19, node="nodeD")]


def test_monitor_probe_ratio_confirms_and_quarantines():
    good = H.sdc_digest(seed=7)

    def probe(node_id):
        slow = node_id == "nodeC"
        return {"node_id": node_id, "digest": good,
                "elapsed_s": 0.35 if slow else 0.10}

    mon, ladder = _make_monitor(_STRAGGLER_RECORDS,
                                ["nodeA", "nodeB", "nodeC", "nodeD"],
                                probe)
    mon.tick()                     # window 1: streak building
    assert mon.summary()["quarantined"] == []
    mon.tick()                     # window 2: promoted -> probe -> confirm
    s = mon.summary()
    assert s["quarantined"] == ["nodeC"]
    assert "detection_to_quarantine_s" in s
    kinds = [e["event"] for e in s["events"]]
    assert kinds.count("suspect") >= 1 and kinds.count("quarantine") == 1
    assert ("nodeC", "QUARANTINED", False) in ladder
    # verdict mentions the probe ratio evidence
    q = [e for e in s["events"] if e["event"] == "quarantine"][0]
    assert "slower than reference" in q["reason"]


def test_monitor_probe_acquittal_resets_the_streak():
    good = H.sdc_digest(seed=7)

    def probe(node_id):
        return {"node_id": node_id, "digest": good, "elapsed_s": 0.10}

    mon, ladder = _make_monitor(_STRAGGLER_RECORDS,
                                ["nodeA", "nodeB", "nodeC", "nodeD"],
                                probe)
    for _ in range(4):
        mon.tick()
    s = mon.summary()
    assert s["quarantined"] == []                 # probe cleared it
    assert all(h != "QUARANTINED" for _, h, _hw in ladder)
    # acquittal reset the streak: the passive signal alone keeps it
    # SUSPECT-bound, never quarantined
    assert mon._rank_hyst.streak(("g", 2)) < 2


def test_monitor_sdc_mismatch_is_hw_confirmed_final():
    """A canary digest mismatch means the chip corrupts data: quarantine
    rides ``hw_confirmed`` so the GCS makes the eventual death final
    (report_node_failure semantics)."""
    good = H.sdc_digest(seed=7)

    def probe(node_id):
        bad = node_id == "nodeC"
        return {"node_id": node_id,
                "digest": "deadbeef" * 8 if bad else good,
                "elapsed_s": 0.10}

    mon, ladder = _make_monitor(_STRAGGLER_RECORDS,
                                ["nodeA", "nodeB", "nodeC", "nodeD"],
                                probe)
    mon.tick()
    mon.tick()
    s = mon.summary()
    assert s["quarantined"] == ["nodeC"]
    q = [e for e in s["events"] if e["event"] == "quarantine"][0]
    assert q["hw_confirmed"] is True
    assert "SDC" in q["reason"]
    assert ("nodeC", "QUARANTINED", True) in ladder


def test_monitor_probe_timeout_while_reference_answers_confirms():
    good = H.sdc_digest(seed=7)

    def probe(node_id):
        if node_id == "nodeC":
            return None                    # suspect never answers
        return {"node_id": node_id, "digest": good, "elapsed_s": 0.10}

    mon, _ladder = _make_monitor(_STRAGGLER_RECORDS,
                                 ["nodeA", "nodeB", "nodeC", "nodeD"],
                                 probe)
    mon.tick()
    mon.tick()
    s = mon.summary()
    assert s["quarantined"] == ["nodeC"]
    q = [e for e in s["events"] if e["event"] == "quarantine"][0]
    assert "timed out" in q["reason"]


def test_monitor_no_reference_leaves_suspect_unconfirmed():
    # every other node quarantined/unreachable: no healthy yardstick —
    # must NOT quarantine on passive evidence alone
    def probe(node_id):
        return None

    mon, ladder = _make_monitor(_STRAGGLER_RECORDS, [], probe)
    for _ in range(4):
        mon.tick()
    assert mon.summary()["quarantined"] == []
    assert all(h != "QUARANTINED" for _, h, _hw in ladder)


def test_monitor_probe_sweep_catches_degraded_node_without_groups():
    """The node-sweep leg: detection with no train group at all (the
    production-day crucible's shape — single-rank learners)."""
    good = H.sdc_digest(seed=7)

    def probe(node_id):
        slow = node_id == "n3"
        return {"node_id": node_id, "digest": good,
                "elapsed_s": 0.50 if slow else 0.10}

    mon, ladder = _make_monitor(
        [], ["n1", "n2", "n3", "n4"], probe,
        probe_sweep=True, probe_sweep_every=1, suspect_windows=2)
    mon.tick()
    assert mon.summary()["quarantined"] == []     # hysteresis holding
    mon.tick()
    s = mon.summary()
    assert s["quarantined"] == ["n3"]
    assert ("n3", "QUARANTINED", False) in ladder
    assert "detection_to_quarantine_s" in s


def test_monitor_probe_sweep_needs_three_nodes():
    def probe(node_id):
        return {"node_id": node_id, "digest": H.sdc_digest(seed=7),
                "elapsed_s": 0.50 if node_id == "n2" else 0.10}

    mon, _ladder = _make_monitor([], ["n1", "n2"], probe,
                                 probe_sweep=True, probe_sweep_every=1)
    for _ in range(4):
        mon.tick()
    assert mon.summary()["quarantined"] == []


# ---------------------------------------------------------------------------
# GCS ladder: SUSPECT -> QUARANTINED -> drain, sticky, exclusions
# ---------------------------------------------------------------------------


def _gcs_raylet_env(test_body, flags=None):
    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.raylet import Raylet

    config.reload(dict({"health_check_period_s": 1.0}, **(flags or {})))

    async def main():
        sd = tempfile.mkdtemp()
        os.makedirs(os.path.join(sd, "logs"), exist_ok=True)
        g = GcsServer(sd)
        await g.start()
        r1 = Raylet(sd, g.addr, {"CPU": 2})
        await r1.start()
        r2 = Raylet(sd, g.addr, {"CPU": 2})
        await r2.start()
        try:
            await test_body(g, r1, r2)
        finally:
            for r in (r1, r2):
                try:
                    await r.stop()
                except Exception:  # noqa: BLE001
                    pass
            await g.stop()

    try:
        asyncio.run(main())
    finally:
        config.reload()


def test_gcs_health_ladder_quarantine_drains_and_excludes():
    async def body(g, r1, r2):
        nid = r1.node_id
        assert g.nodes[nid]["health"] == "HEALTHY"
        ack = await g.handle_set_node_health(node_id=nid,
                                             health="SUSPECT",
                                             reason="own-time outlier")
        assert ack["accepted"] and ack["previous"] == "HEALTHY"
        assert g.nodes[nid]["health"] == "SUSPECT"
        # SUSPECT is advisory: still schedulable
        assert nid not in g._unschedulable_node_ids()

        ack = await g.handle_set_node_health(node_id=nid,
                                             health="QUARANTINED",
                                             reason="probe 3x slower")
        assert ack["accepted"]
        assert ack["drain"] and ack["drain"]["accepted"]
        node = g.nodes[nid]
        assert node["health"] == "QUARANTINED"
        assert node["state"] == "DRAINING"          # actuation: drain opened
        assert "quarantine" in node["drain_reason"]
        # excluded from scheduling and from available capacity
        assert nid in g._unschedulable_node_ids()
        avail = await g.handle_available_resources()
        total_with = await g.handle_cluster_resources()
        assert avail.get("CPU", 0) <= total_with.get("CPU", 0) - 2
        # cluster view carries the ladder for every surface
        healths = {n["node_id"]: n["health"] for n in g._cluster_view()}
        assert healths[nid] == "QUARANTINED"
        assert healths[r2.node_id] == "HEALTHY"
        # the broadcast fired
        ev = await g.handle_subscribe(cursor=0, channel="nodes",
                                      timeout=0.1)
        assert any(e["event"] == "node_health" and
                   e["health"] == "QUARANTINED" for e in ev["events"])

        # sticky: no self-acquittal back down the ladder
        ack = await g.handle_set_node_health(node_id=nid,
                                             health="HEALTHY",
                                             reason="oops")
        assert not ack["accepted"]
        assert "sticky" in ack["rejection_reason"]
        assert g.nodes[nid]["health"] == "QUARANTINED"

        # unknown node / unknown state rejected
        assert not (await g.handle_set_node_health(
            node_id="nope", health="SUSPECT"))["accepted"]
        assert not (await g.handle_set_node_health(
            node_id=nid, health="WEIRD"))["accepted"]

    _gcs_raylet_env(body)


def test_gcs_hw_confirmed_quarantine_death_is_final():
    """An SDC-confirmed quarantine must make the drain-expiry death
    FINAL: the corpse's late heartbeats cannot resurrect it."""
    async def body(g, r1, r2):
        nid = r1.node_id
        await g.handle_set_node_health(
            node_id=nid, health="QUARANTINED",
            reason="SDC canary digest mismatch", hw_confirmed=True)
        assert g.nodes[nid]["health_hw_confirmed"] is True
        # let the quarantine drain expire
        deadline = time.time() + 15
        while time.time() < deadline:
            if g.nodes[nid]["state"] == "DEAD":
                break
            await asyncio.sleep(0.1)
        node = g.nodes[nid]
        assert node["state"] == "DEAD"
        assert node.get("death_final"), \
            "hw-confirmed quarantine death must be final"

    _gcs_raylet_env(body, flags={
        "health_quarantine_drain_deadline_s": 0.4})


def test_gcs_arm_node_fault_reaches_the_raylet_registry():
    """The chaos fan-out path: GCS ``arm_node_fault`` relays to the
    node's raylet, which arms its own in-process registry (and would
    fan to pooled workers + re-arm late-spawning ones)."""
    from ray_tpu.util import fault_injection as fi

    async def body(g, r1, r2):
        site = "health.test_arm"
        try:
            ack = await g.handle_arm_node_fault(
                node_id=r1.node_id, site=site, start_s=0.0,
                duration_s=30.0, exc="slow:3")
            assert ack["armed"] >= 1, ack
            # the raylet process (this process) armed the window
            assert site in fi._armed
            assert fi._armed[site].factor == 3.0
            # the raylet remembers the window for late worker spawns
            assert any(a["site"] == site for a in r1._armed_faults)
            assert not (await g.handle_arm_node_fault(
                node_id="nope", site=site))["armed"]
        finally:
            fi.disarm(site)

    _gcs_raylet_env(body)


# ---------------------------------------------------------------------------
# state API surface
# ---------------------------------------------------------------------------


def test_list_node_health_reports_ladder_and_verdicts(ray_start):
    import ray_tpu
    from ray_tpu.util.state import list_node_health

    v = H.HealthVerdict(kind="rank", subject="tg/3", health=H.SUSPECT,
                        reason="own-time outlier", group="tg", rank=3,
                        signals={"own_time_z": 5.2})
    assert H.publish_health_verdict(v)
    try:
        report = list_node_health()
        assert report["nodes"], "no nodes listed"
        for n in report["nodes"]:
            assert n["health"] in ("HEALTHY", "SUSPECT", "QUARANTINED")
        subjects = {r["subject"]: r for r in report["verdicts"]}
        assert "tg/3" in subjects
        assert subjects["tg/3"]["signals"]["own_time_z"] == 5.2
    finally:
        from ray_tpu.experimental import internal_kv

        internal_kv._internal_kv_del(b"verdict/rank/tg/3",
                                     namespace="health")


# ---------------------------------------------------------------------------
# end to end: straggler -> detect -> quarantine -> drain -> re-mesh
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.slow
def test_straggler_detected_quarantined_and_remeshed(no_cluster, tmp_path,
                                                     monkeypatch):
    """The full health-plane loop on a live multi-process CPU cluster:
    degrade one trainer node 3x (``slow`` fault on its compute path AND
    on ``health.probe`` so the confirm probe sees the sick hardware),
    let the HealthMonitor attribute the straggler from step-ledger
    evidence, confirm with the probe, quarantine through the GCS ladder
    (which opens a drain), and assert the elastic run re-meshes off the
    quarantined node and completes with a ZERO failure budget —
    quarantine is a planned migration, never a charged failure."""
    import threading

    import ray_tpu  # noqa: F401
    from ray_tpu import train
    from ray_tpu._private.health_plane import HealthMonitor
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train.policies import ElasticScalingPolicy

    monkeypatch.setenv("RAY_TPU_HEALTH_CHECK_PERIOD_S", "1.0")
    monkeypatch.setenv("RAY_TPU_HEALTH_QUARANTINE_DRAIN_DEADLINE_S", "8.0")
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    mon = None
    try:
        cluster.connect()
        for _ in range(3):
            cluster.add_node(num_cpus=2, resources={"trainer_slot": 1})
        cluster.wait_for_nodes()
        side = str(tmp_path / "side")
        os.makedirs(side, exist_ok=True)

        def loop(config):
            import json as _json
            import os as _os
            import tempfile as _tempfile
            import time as _t

            from ray_tpu import train as _train
            from ray_tpu.util.fault_injection import fault_point as _fp

            ctx = _train.get_context()
            rank = ctx.get_world_rank()
            world = ctx.get_world_size()
            ledger = ctx.step_ledger()
            ledger._PUBLISH_EVERY_S = 0.0   # publish every step boundary
            start = 0
            ck = ctx.get_checkpoint()
            if ck is not None:
                with open(_os.path.join(ck.path, "state.json")) as f:
                    start = _json.load(f)["step"] + 1
            for step in range(start, config["steps"]):
                with ledger.step():
                    with ledger.bucket("compute"):
                        _fp("train.work")   # the degradable compute path
                        _t.sleep(config["step_s"])
                    # file barrier standing in for the collective: the
                    # wait is charged to collective_wait, so healthy
                    # ranks show high wait and the straggler shows high
                    # own-time — the attribution the scorer keys on
                    me = _os.path.join(config["side_dir"],
                                       f"s{step}-w{world}-r{rank}")
                    with open(me + ".tmp", "w") as f:
                        _json.dump(
                            {"step": step, "rank": rank, "world": world,
                             "node": _os.environ.get(
                                 "RAY_TPU_NODE_ID", "")}, f)
                    _os.replace(me + ".tmp", me)
                    t0 = _t.monotonic()
                    want = {f"s{step}-w{world}-r{r}" for r in range(world)}
                    while _t.monotonic() - t0 < 60:
                        if want <= set(_os.listdir(config["side_dir"])):
                            break
                        _t.sleep(0.01)
                    ledger.note("collective_wait", _t.monotonic() - t0)
                d = _tempfile.mkdtemp()
                with open(_os.path.join(d, "state.json"), "w") as f:
                    _json.dump({"step": step}, f)
                _train.report({"step": step, "world": world},
                              checkpoint=_train.Checkpoint(d))

        armed = {}

        def saboteur():
            # wait for rank-1 step-2 evidence at full size, then arm a
            # 3x slowdown on that whole node: the compute site AND the
            # probe site (degraded hardware is slow for the probe too)
            from ray_tpu._private.worker import get_global_worker

            deadline = time.time() + 120
            while time.time() < deadline:
                marker = os.path.join(side, "s2-w3-r1")
                if os.path.exists(marker):
                    with open(marker) as f:
                        info = json.load(f)
                    if info["node"]:
                        w = get_global_worker()
                        for site in ("train.work", "health.probe"):
                            ack = w.run_coro(
                                w.gcs.call("arm_node_fault",
                                           node_id=info["node"],
                                           site=site, start_s=0.0,
                                           duration_s=120.0,
                                           exc="slow:3", timeout=10),
                                timeout=15)
                            assert ack["armed"] >= 1, ack
                        armed["node"] = info["node"]
                        armed["t"] = time.time()
                        return
                time.sleep(0.2)

        mon = HealthMonitor(interval_s=0.5, suspect_windows=2,
                            probe_factor=1.5, probe_timeout_s=30.0)
        mon.start()
        t = threading.Thread(target=saboteur, daemon=True)
        t.start()

        trainer = train.DataParallelTrainer(
            loop,
            train_loop_config={"side_dir": side, "steps": 25,
                               "step_s": 0.2},
            scaling_config=train.ScalingConfig(
                num_workers=3,
                resources_per_worker={"CPU": 1, "trainer_slot": 1}),
            run_config=train.RunConfig(
                name="health-run", storage_path=str(tmp_path),
                failure_config=train.FailureConfig(max_failures=0)),
            scaling_policy=ElasticScalingPolicy(
                min_workers=2, max_workers=3,
                resources_per_worker={"CPU": 1, "trainer_slot": 1}),
        )
        result = trainer.fit()
        t.join(timeout=5)

        assert "node" in armed, "saboteur never armed the degradation"
        # the run completed despite the sick node — with max_failures=0:
        # quarantine-drain is a planned migration, not a charged failure
        assert result.error is None, result.error
        steps = [m["step"] for m in result.metrics_history]
        assert steps[-1] == 24, f"did not finish: {steps}"

        # the monitor detected, confirmed and quarantined the victim
        s = mon.summary()
        assert armed["node"] in s["quarantined"], s
        assert "detection_to_quarantine_s" in s, s
        assert s["detection_to_quarantine_s"] >= 0.0

        # the re-meshed group ran at the surviving size, off the victim
        post_nodes = set()
        t_recovered = None
        for name in os.listdir(side):
            if "-w2-" not in name or name.endswith(".tmp"):
                continue
            path = os.path.join(side, name)
            with open(path) as f:
                post_nodes.add(json.load(f)["node"])
            mtime = os.path.getmtime(path)
            t_recovered = mtime if t_recovered is None \
                else min(t_recovered, mtime)
        assert post_nodes, "group never re-meshed at the surviving size"
        assert armed["node"] not in post_nodes, post_nodes
        # detection-to-recovery: degradation armed -> first step of the
        # re-meshed group (generous bound; the point is it is bounded)
        assert t_recovered is not None
        assert t_recovered - armed["t"] < 90, (
            f"recovery took {t_recovered - armed['t']:.1f}s")

        # the GCS ladder shows the quarantine, and the node is DRAINING
        # or already dead -- never schedulable again
        victim = [n for n in ray_tpu.nodes()
                  if n["node_id"] == armed["node"]][0]
        assert victim.get("health") == "QUARANTINED", victim
        assert victim["state"] in ("DRAINING", "DEAD"), victim
    finally:
        if mon is not None:
            mon.stop()
        cluster.shutdown()
