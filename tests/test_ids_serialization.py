"""Unit tests for IDs and serialization (no cluster needed)."""

import numpy as np
import pytest

from ray_tpu._private import serialization
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID, UniqueID


def test_id_roundtrip():
    uid = UniqueID.from_random()
    assert UniqueID.from_hex(uid.hex()) == uid
    assert len(uid.binary()) == UniqueID.SIZE
    assert not uid.is_nil()
    assert UniqueID.nil().is_nil()


def test_id_derivation_deterministic():
    job = JobID.from_int(7)
    t = TaskID.for_driver_task(job)
    t2 = TaskID.for_driver_task(job)
    assert t == t2
    o1 = ObjectID.from_task_and_index(t, 0)
    o2 = ObjectID.from_task_and_index(t, 0)
    o3 = ObjectID.from_task_and_index(t, 1)
    assert o1 == o2 and o1 != o3
    a = ActorID.of(job, t, 1)
    assert a == ActorID.of(job, t, 1)
    assert a != ActorID.of(job, t, 2)


def test_id_type_distinction():
    raw = b"x" * 16
    assert UniqueID(raw) != ObjectID(raw)
    with pytest.raises(ValueError):
        TaskID(raw)  # wrong width


def test_serialize_roundtrip_basic():
    for value in [1, "abc", [1, 2, {"k": (3, 4)}], None, b"bytes", {"nested": [1.5]}]:
        payload, refs = serialization.serialize(value)
        out, refs2 = serialization.deserialize(payload)
        assert out == value
        assert refs == [] and refs2 == []


def test_serialize_numpy_zero_copy():
    arr = np.arange(100000, dtype=np.float32).reshape(100, 1000)
    payload, _ = serialization.serialize({"x": arr, "tag": 5})
    out, _ = serialization.deserialize(payload)
    np.testing.assert_array_equal(out["x"], arr)
    # zero-copy: deserialized array should view the payload buffer
    assert not out["x"].flags["OWNDATA"]


def test_serialize_closure():
    y = 42

    def fn(x):
        return x + y

    payload = serialization.dumps(fn)
    fn2 = serialization.loads(payload)
    assert fn2(1) == 43


def test_serialize_jax_array():
    import jax.numpy as jnp

    x = jnp.arange(16.0)
    payload, _ = serialization.serialize(x)
    out, _ = serialization.deserialize(payload)
    assert isinstance(out, type(x))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
