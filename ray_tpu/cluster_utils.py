"""Multi-node-on-one-host test clusters.

Equivalent of the reference's ``python/ray/cluster_utils.py:135 Cluster`` /
``add_node :202`` — start multiple raylets as separate processes on one
machine, each a full scheduling node with its own resources, against one GCS.
This is the workhorse for distributed scheduling / fault-tolerance tests.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu._private.node import NodeServices, default_resources


class ClusterNode:
    def __init__(self, node_id: str, addr: str, proc: Optional[subprocess.Popen]):
        self.node_id = node_id
        self.addr = addr
        self.proc = proc

    @property
    def unique_id(self) -> str:
        return self.node_id


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None, connect: bool = False):
        self._services = NodeServices()
        self.head_node: Optional[ClusterNode] = None
        self.worker_nodes: List[ClusterNode] = []
        self._extra_sessions: List[str] = []
        self.gcs_address = ""
        if initialize_head:
            args = dict(head_node_args or {})
            resources = default_resources(num_cpus=args.pop("num_cpus", 4),
                                          num_tpus=args.pop("num_tpus", 0))
            resources.update(args.pop("resources", {}))
            labels = args.pop("labels", {})
            self.gcs_address = self._services.start_head(resources, labels)
            self.head_node = ClusterNode("head", self.gcs_address, self._services.head_proc)
            if connect:
                ray_tpu.init(address=self.gcs_address)

    @property
    def address(self) -> str:
        return self.gcs_address

    def connect(self):
        ray_tpu.init(address=self.gcs_address)

    def add_node(self, num_cpus: float = 4, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 node_name: str = "",
                 separate_session: bool = False) -> ClusterNode:
        res = default_resources(num_cpus=num_cpus, num_tpus=num_tpus)
        if resources:
            res.update(resources)
        session_dir = self._services.session_dir
        if separate_session:
            # own session dir -> own object-store arena: cross-node gets
            # exercise the REAL transfer plane (chunked pull / same-host
            # handoff) instead of reading a shared test arena — what a
            # distinct physical host would look like
            session_dir = f"{session_dir}_n{time.time_ns() % 10**9}"
            os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
            os.makedirs(os.path.join(session_dir, "sockets"), exist_ok=True)
            self._extra_sessions.append(session_dir)
        log = open(os.path.join(session_dir, "logs",
                                f"raylet-{time.time_ns()}.log"), "ab")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "ray_tpu._private.raylet_proc",
                "--session-dir", session_dir,
                "--gcs-addr", self.gcs_address,
                "--resources", json.dumps(res),
                "--labels", json.dumps(labels or {}),
                "--node-name", node_name,
            ],
            stdout=subprocess.PIPE,
            stderr=log,
            start_new_session=True,
        )
        line = proc.stdout.readline().decode().strip()
        info = json.loads(line)
        node = ClusterNode(info["node_id"], info["addr"], proc)
        self.worker_nodes.append(node)
        return node

    def remove_node(self, node: ClusterNode, allow_graceful: bool = False):
        if node.proc is not None:
            node.proc.kill()
            node.proc.wait(timeout=5)
        if node in self.worker_nodes:
            self.worker_nodes.remove(node)

    def wait_for_nodes(self, timeout: float = 30.0):
        expected = 1 + len(self.worker_nodes)
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                alive = [n for n in ray_tpu.nodes() if n["alive"]]
                if len(alive) >= expected:
                    return
            except Exception:
                pass
            time.sleep(0.1)
        raise TimeoutError(f"cluster did not reach {expected} nodes")

    def shutdown(self):
        for node in list(self.worker_nodes):
            self.remove_node(node)
        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        else:
            self._services.stop()
        for sess in self._extra_sessions:
            try:
                from ray_tpu._private.object_store import arena_name_for

                os.unlink("/dev/shm" + arena_name_for(sess))
            except OSError:
                pass
            shutil.rmtree(sess, ignore_errors=True)
        self._extra_sessions.clear()
