"""ray_tpu.tune: hyperparameter optimization (reference: ``python/ray/tune/``)."""

from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    HyperbandImprovementSearcher,
    Searcher,
    TPESearcher,
    choice,
    generate_variants,
    grid_search,
    loguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.trainable import FunctionTrainable, Trainable, get_checkpoint, report
from ray_tpu.tune.tuner import (
    Result,
    ResultGrid,
    TuneConfig,
    TuneController,
    Tuner,
    run,
)

__all__ = [
    "ASHAScheduler", "AsyncHyperBandScheduler", "BasicVariantGenerator",
    "FIFOScheduler", "FunctionTrainable", "HyperbandImprovementSearcher",
    "MedianStoppingRule", "PopulationBasedTraining", "Result", "ResultGrid",
    "Searcher", "TPESearcher", "Trainable", "TrialScheduler", "TuneConfig",
    "TuneController",
    "Tuner", "choice", "generate_variants", "get_checkpoint", "grid_search",
    "loguniform", "quniform", "randint", "report", "run", "sample_from",
    "uniform",
]
