"""Standalone raylet process — an additional "node" joining an existing GCS.

Used by ``ray_tpu.cluster_utils.Cluster.add_node`` to build multi-node
topologies on one host (reference: ``python/ray/cluster_utils.py:135,202``).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--gcs-addr", required=True)
    parser.add_argument("--resources", required=True)
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--node-name", default="")
    args = parser.parse_args()

    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    from ray_tpu._private.raylet import Raylet

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    raylet = Raylet(
        args.session_dir,
        gcs_addr=args.gcs_addr,
        resources=json.loads(args.resources),
        labels=json.loads(args.labels),
        node_name=args.node_name,
    )
    # a cluster-wide shutdown_node must end this PROCESS, not just the
    # raylet object (the launcher's `down` relies on it)
    raylet.on_shutdown = lambda: loop.call_later(0.2, loop.stop)
    loop.run_until_complete(raylet.start())
    # readiness marker for the parent
    marker = os.path.join(args.session_dir, f"raylet_{raylet.node_id[:12]}.ready")
    with open(marker, "w") as f:
        f.write(raylet.addr)
    print(json.dumps({"node_id": raylet.node_id, "addr": raylet.addr}), flush=True)

    # SIGTERM maps to SELF-DRAIN (the autoscaler/slice-provider terminate
    # path and spot/maintenance preemption notices both deliver SIGTERM):
    # broadcast the drain so schedulers route around this node and
    # consumers (train/serve) checkpoint/migrate, wait for leases to
    # drain — bounded by the drain deadline — then stop gracefully so the
    # node flips to dead immediately instead of after heartbeat timeout.
    # An idle node (no lease holders) exits as fast as it used to.
    import signal
    import time as _time

    def _term(_sig, _frm):
        async def _drain_stop_and_exit():
            try:
                await raylet.self_drain("SIGTERM")
                while (_time.time() < raylet.drain_deadline
                       and any(h.lease is not None
                               for h in raylet.workers.values())):
                    await asyncio.sleep(0.2)
            except Exception:  # noqa: BLE001
                pass
            try:
                await asyncio.wait_for(raylet.stop(), timeout=8.0)
            except Exception:  # noqa: BLE001
                pass
            loop.stop()

        asyncio.ensure_future(_drain_stop_and_exit())

    loop.add_signal_handler(signal.SIGTERM, _term, signal.SIGTERM, None)
    try:
        loop.run_forever()
    except KeyboardInterrupt:
        pass
    sys.exit(0)


if __name__ == "__main__":
    main()
