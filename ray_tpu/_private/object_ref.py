"""ObjectRef — a typed future naming an object in the cluster.

Equivalent of the reference's ``ObjectRef`` (``python/ray/_raylet.pyx`` /
``src/ray/common/id.h`` ObjectID + ownership metadata from
``src/ray/core_worker/reference_count.h:72``).  Each ref carries its owner's
address so any holder can resolve the value directly from the owner (the
ownership model: the worker that created an object serves and refcounts it).

Lifetime: refs handed out by the framework (put / task submission /
deserialization) are *counted* — ``__del__`` reports the drop to the
CoreWorker's ``ReferenceCounter`` so the owner can free the object once no
holder remains anywhere (see ``reference_counting.py``).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner_addr", "_in_band", "_counted", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_addr: Optional[str] = None):
        self.id = object_id
        self.owner_addr = owner_addr
        self._in_band = None  # local-mode fast path: value carried inline
        self._counted = False  # set by the worker when this ref is tracked

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        # Track refs crossing serialization boundaries (borrower registration,
        # reference: reference_count.h borrow protocol).
        from ray_tpu._private import serialization

        serialization.note_serialized_ref(self)
        return (_rebuild_ref, (self.id, self.owner_addr))

    def __del__(self):
        if not self._counted:
            return
        try:
            from ray_tpu._private import worker as _w

            w = _w.global_worker
            if w is not None and not w._shutdown:
                # lock-free: deque.append is GIL-atomic; the worker's IO
                # loop drains the event queue in FIFO order
                w._ref_events.append(("del", self.id, self.owner_addr))
        except Exception:  # interpreter teardown
            pass

    def future(self):
        """Return a concurrent.futures.Future resolving to the value
        (non-blocking: resolution rides the worker's IO loop)."""
        from ray_tpu._private.worker import get_global_worker

        return get_global_worker().future_for(self)

    def __await__(self):
        # Awaitable inside async actors/drivers.
        from ray_tpu._private.worker import global_worker

        return global_worker.get_async(self).__await__()


def _rebuild_ref(object_id, owner_addr):
    from ray_tpu._private import serialization

    ref = ObjectRef(object_id, owner_addr)
    serialization.note_deserialized_ref(ref)
    # Borrow registration: deserializing a ref makes this process a holder
    # (suppressed for task-spec loads — see serialization.uncounted_refs).
    if serialization.counting_suppressed():
        return ref
    try:
        from ray_tpu._private import worker as _w

        w = _w.global_worker
        if w is not None and not w._shutdown:
            ref._counted = True
            w._ref_events.append(("add", object_id, owner_addr))
    except Exception:
        pass
    return ref
