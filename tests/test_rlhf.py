"""RLHF chaos crucible: versioned weight-sync units + the end-to-end
rollout → reward → update loop under fault injection.

Tier-1 (non-slow) covers the weight-sync layer's contracts (monotonic
versions, torn publishes unobservable, digest-validated atomic swap,
staleness backpressure, resume-above-committed) and the acceptance e2e:
≥3 loop iterations with a rollout-actor kill AND a weight-publish fault
injected, asserting loop completion, no double-counted trajectories,
monotonically non-decreasing consumed weight versions, and no consumer
ever observing a mixed-version param tree (digest re-verified on every
read).  The slow tier drives the standing chaos runner
(``benchmarks/rlhf_chaos.py``) through train-node drain mid-epoch and
the remaining registry scenarios.
"""

import os
import sys
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import fault_injection as fi

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _params(scale: float = 1.0):
    return {"w": np.full((4, 4), scale, np.float32),
            "b": np.zeros((4,), np.float32)}


# ---------------------------------------------------------------------------
# ledger units (no cluster)
# ---------------------------------------------------------------------------


class TestTrajectoryLedger:
    def test_admit_is_exactly_once(self):
        from ray_tpu.rl import TrajectoryLedger

        led = TrajectoryLedger()
        assert led.admit(7)
        assert not led.admit(7)
        assert led.consumed == 1
        assert led.duplicates_rejected == 1

    def test_roundtrip_preserves_consumed_ids(self):
        from ray_tpu.rl import TrajectoryLedger

        led = TrajectoryLedger()
        led.record_produced(3)
        led.admit(1)
        led.admit(2)
        led.record_dropped(1, "actor died")
        led2 = TrajectoryLedger.from_state(led.state_dict())
        # the exactly-once gate survives checkpoint/restore
        assert not led2.admit(2)
        assert led2.admit(3)
        assert led2.dropped == 1
        assert led2.drop_reasons == {"actor died": 1}

    def test_uid_bases_unique_across_mints(self):
        from ray_tpu.rl.rlhf import _mint_uid_base

        bases = {_mint_uid_base() for _ in range(512)}
        assert len(bases) == 512
        assert all(0 < b < 2 ** 63 for b in bases)


# ---------------------------------------------------------------------------
# weight-sync layer
# ---------------------------------------------------------------------------


@pytest.mark.usefixtures("ray_start")
class TestWeightSync:
    def test_publish_subscribe_atomic_snapshot(self):
        from ray_tpu.rl import WeightPublisher, WeightSubscriber

        pub = WeightPublisher("ws-basic", resume=False)
        v1 = pub.publish(_params(1.0))
        assert (v1.version, v1.epoch) == (1, 0)
        sub = WeightSubscriber("ws-basic", verify_on_read=True)
        params, ver = sub.current()
        assert ver == v1
        np.testing.assert_array_equal(params["w"], _params(1.0)["w"])
        v2 = pub.publish(_params(2.0))
        assert v2.version == 2
        assert sub.poll(timeout_s=2.0)
        params, ver = sub.current()
        assert ver.version == 2 and float(params["w"][0, 0]) == 2.0
        pub.close()

    def test_torn_publish_never_observed_and_retry_is_gapless(self):
        from ray_tpu.rl import WeightPublisher, WeightSubscriber

        pub = WeightPublisher("ws-torn", resume=False)
        pub.publish(_params(1.0))
        sub = WeightSubscriber("ws-torn")
        with fi.armed("rl.weight_sync.publish", nth=1):
            with pytest.raises(ConnectionError):
                pub.publish(_params(9.0))
        # the payload exists but the commit never happened: unobservable
        assert not sub.poll(timeout_s=0.2)
        _, ver = sub.current()
        assert ver.version == 1
        # the retry re-publishes the SAME version number — no gap, no
        # rewind, and consumers converge on it
        v2 = pub.publish(_params(2.0))
        assert v2.version == 2
        assert sub.poll(timeout_s=2.0)
        params, ver = sub.current()
        assert ver.version == 2 and float(params["w"][0, 0]) == 2.0
        assert pub.stats["publish_failures"] == 1
        pub.close()

    def test_corrupt_payload_rejected_not_served(self):
        import pickle

        from ray_tpu.experimental import internal_kv
        from ray_tpu.rl import WeightPublisher, WeightSubscriber
        from ray_tpu.rl.weight_sync import _NAMESPACE, _latest_key

        pub = WeightPublisher("ws-corrupt", resume=False)
        pub.publish(_params(1.0))
        sub = WeightSubscriber("ws-corrupt")
        # forge a commit record whose payload digest cannot validate:
        # point v2 at a payload whose tree bytes disagree with the digest
        bad = {"version": 2, "epoch": 0, "digest": "0" * 64,
               "params": _params(666.0)}
        ref = ray_tpu.put(bad)
        internal_kv._internal_kv_put(
            _latest_key("ws-corrupt"),
            pickle.dumps({"version": 2, "epoch": 0, "digest": "0" * 64,
                          "ref": pickle.dumps(ref),
                          "published_at": time.time()}),
            namespace=_NAMESPACE)
        assert not sub.poll(timeout_s=0.2)
        params, ver = sub.current()
        assert ver.version == 1 and float(params["w"][0, 0]) == 1.0
        assert sub.stats["rejected"] == 1
        pub.close()

    def test_staleness_gate_backpressures_then_releases(self):
        from ray_tpu.rl import (
            WeightPublisher, WeightSubscriber, WeightsStaleError)

        pub = WeightPublisher("ws-stale", resume=False)
        pub.publish(_params(1.0))
        sub = WeightSubscriber("ws-stale", staleness_bound=2)
        sub.gate(timeout_s=0.1)  # under the bound: no-op
        sub.note_sample()
        sub.note_sample()
        with pytest.raises(WeightsStaleError):
            sub.gate(timeout_s=0.3)
        pub.publish(_params(2.0))
        sub.gate(timeout_s=5.0)  # released by the fresh publish
        _, ver = sub.current()
        assert ver.version == 2
        pub.close()

    def test_resume_continues_above_committed_version(self):
        from ray_tpu.rl import WeightPublisher, WeightSubscriber

        pub = WeightPublisher("ws-resume", resume=False)
        for s in (1.0, 2.0, 3.0):
            pub.publish(_params(s))
        pub.close()
        # a restarted learner (drain, preemption) must continue ABOVE
        # the durable version with a bumped epoch — never rewind
        pub2 = WeightPublisher("ws-resume", resume=True)
        v = pub2.publish(_params(4.0))
        assert (v.version, v.epoch) == (4, 1)
        sub = WeightSubscriber("ws-resume")
        _, ver = sub.current()
        assert (ver.version, ver.epoch) == (4, 1)
        pub2.close()

    def test_channel_fast_path_and_dead_reader_fallback(self):
        from ray_tpu.rl import WeightPublisher, WeightSubscriber

        pub = WeightPublisher("ws-chan", resume=False,
                              channel_write_timeout_s=0.3)
        pub.publish(_params(1.0))
        info = pub.rotate_channel(1)
        sub = WeightSubscriber("ws-chan")
        sub.attach_channel(info, 0)
        pub.publish(_params(2.0))
        assert sub.poll(timeout_s=2.0)
        assert sub.stats["channel_updates"] >= 1
        # reader stops draining (dead consumer): the bounded channel
        # write times out, the channel is retired, and publication
        # continues on the durable path
        sub.detach_channel()
        pub.publish(_params(3.0))  # fills the channel slot, no ack ever
        pub.publish(_params(4.0))  # write times out -> retire
        assert pub.stats["channel_retired"] == 1
        assert pub.latest_version.version == 4
        assert sub.poll(timeout_s=2.0)
        _, ver = sub.current()
        assert ver.version == 4
        pub.close()


# ---------------------------------------------------------------------------
# the acceptance e2e (tier-1): ≥3 iterations under kill + publish fault
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.usefixtures("ray_start")
class TestRLHFLoopEndToEnd:
    def test_loop_survives_rollout_kill_and_publish_fault(self):
        from ray_tpu.rl import RLHFConfig, RLHFLoop

        cfg = RLHFConfig(
            iterations=4, num_rollout_actors=2, rollout_batch=32,
            learner_batch_size=32, name="rlhf-e2e", mesh="dp",
            sample_timeout_s=60.0,
            # every RolloutActor.current() re-hashes the served tree
            # against its committed digest: a mixed-version tree anywhere
            # would fail the sample, and so the loop
            verify_weights_on_read=True,
            chaos={"kill_rollout_at_iter": 2, "publish_fault_at": 2,
                   "reward_fault_at": 3},
        )
        result = RLHFLoop(cfg).run()
        assert result.error is None, result.error
        m = result.metrics
        # the loop completed every iteration through the chaos
        assert m["training_iteration"] == 4
        # all three armed faults actually fired
        assert m["publish_faults_fired"] >= 1
        assert m["reward_faults_fired"] >= 1
        assert m["respawns_used"] >= 1
        # the killed actor's in-flight batch was dropped WITH accounting
        assert m["trajectories_dropped"] >= 1
        # ...and nothing was double-counted (the retried reward round
        # re-scored cleanly, the respawned actor minted fresh uids)
        assert m["duplicates_rejected"] == 0
        assert m["trajectories_consumed"] <= m["trajectories_produced"]
        # every consumed batch's weight version is monotonically
        # non-decreasing
        cv = m["consumed_versions"]
        assert len(cv) >= 3
        assert all(a <= b for a, b in zip(cv, cv[1:])), cv
        # version stream is gapless-monotonic despite the publish fault:
        # 1 initial + one per iteration
        assert m["published_version"] == 5
        assert m["publisher_epoch"] == 0
        # the loop actually learned from consumed rows
        assert m["rows_consumed"] > 0
        assert np.isfinite(m["loss"])


# ---------------------------------------------------------------------------
# EnvRunnerGroup hardening (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.usefixtures("ray_start")
class TestEnvRunnerGroupHardening:
    def _group(self, n=2, respawn_budget=2):
        from ray_tpu.rl.env_runner import EnvRunnerGroup

        return EnvRunnerGroup(
            "CartPole-v1", n, 2,
            {"obs_dim": 4, "num_actions": 2, "hidden": (8,), "gamma": 0.99},
            seed=0, timeout_s=60.0, respawn_budget=respawn_budget)

    def test_dead_runner_respawned_and_iteration_survives(self):
        group = self._group()
        try:
            group.sync_weights(_module_params())
            ray_tpu.kill(group.runners[0])
            time.sleep(0.3)
            out = group.sample(4)  # dead runner dropped from THIS round
            assert 1 <= len(out) <= 2
            assert len(group.runners) == 2, "dead runner not respawned"
            assert group.respawns_left == 1
            # the respawned runner was re-synced to the last broadcast
            # weights: the next round has everyone contributing
            out = group.sample(4)
            assert len(out) == 2
            assert group.dropped_runners == 0
        finally:
            group.stop()

    def test_budget_exhausted_drops_runner_with_count(self):
        group = self._group(respawn_budget=0)
        try:
            group.sync_weights(_module_params())
            ray_tpu.kill(group.runners[1])
            time.sleep(0.3)
            out = group.sample(4)
            assert len(out) == 1
            assert len(group.runners) == 1
            assert group.dropped_runners == 1
            # the group keeps operating at reduced strength
            assert len(group.sample(4)) == 1
        finally:
            group.stop()


def _module_params():
    import jax

    from ray_tpu.rl.models import ActorCriticModule

    return ActorCriticModule(4, 2, (8,)).init(jax.random.PRNGKey(0))


# ---------------------------------------------------------------------------
# the standing chaos runner (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
class TestRLHFChaosRunner:
    """Each scenario replays one registry fault against the whole loop —
    the new rl.* sites plus the existing drain/collective/serve sites."""

    def _run(self, name):
        from benchmarks.rlhf_chaos import run_scenario

        rec = run_scenario(name)
        assert rec["ok"], rec["problems"]
        return rec

    @pytest.mark.usefixtures("no_cluster")
    def test_rollout_hang_cancelled_at_deadline(self):
        self._run("rollout_hang")

    @pytest.mark.usefixtures("no_cluster")
    def test_rollout_sigkill_mid_sample(self):
        self._run("rollout_sigkill")

    @pytest.mark.usefixtures("no_cluster")
    def test_gcs_flake_absorbed(self):
        self._run("gcs_flake")

    @pytest.mark.usefixtures("ray_isolated")
    def test_serve_hosted_reward_with_router_fault(self):
        self._run("serve_reward")

    @pytest.mark.usefixtures("no_cluster")
    def test_train_node_drain_mid_epoch(self):
        """The acceptance drain leg: drain the node hosting the train
        worker mid-epoch; the loop restarts from the checkpoint and
        publication resumes above the committed version."""
        rec = self._run("drain")
        assert rec["metrics"]["publisher_epoch"] >= 1

    @pytest.mark.usefixtures("ray_isolated")
    def test_collective_abort_restarts_loop(self):
        self._run("collective")
