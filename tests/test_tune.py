"""Tune tier tests (reference model: python/ray/tune/tests/)."""

import numpy as np
import pytest

# JAX-compile-heavy tier: deselect with -m 'not slow' for fast runs
pytestmark = pytest.mark.slow

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import (
    ASHAScheduler,
    PopulationBasedTraining,
    Trainable,
    TuneConfig,
    Tuner,
)


def test_generate_variants_grid_and_random():
    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "wd": tune.uniform(0, 1),
        "arch": {"depth": tune.grid_search([2, 4])},
    }
    variants = tune.generate_variants(space, num_samples=3, seed=0)
    assert len(variants) == 2 * 2 * 3
    assert {v["lr"] for v in variants} == {0.1, 0.01}
    assert {v["arch"]["depth"] for v in variants} == {2, 4}
    assert all(0 <= v["wd"] <= 1 for v in variants)
    # deterministic under seed
    again = tune.generate_variants(space, num_samples=3, seed=0)
    assert variants == again


def test_sample_domains():
    space = {
        "a": tune.loguniform(1e-4, 1e-1),
        "b": tune.randint(0, 10),
        "c": tune.choice(["x", "y"]),
        "d": tune.quniform(0, 1, 0.25),
        "e": tune.sample_from(lambda cfg: cfg["b"] * 2),
    }
    v = tune.generate_variants(space, 5, seed=1)
    assert all(1e-4 <= x["a"] <= 1e-1 for x in v)
    assert all(x["e"] == x["b"] * 2 for x in v)
    assert all(x["d"] in {0, 0.25, 0.5, 0.75, 1.0} for x in v)


def test_function_trainable_basic(ray_start):
    def train_fn(config):
        for i in range(3):
            tune.report({"loss": config["x"] * (3 - i)})

    grid = Tuner(
        train_fn,
        param_space={"x": tune.grid_search([1.0, 2.0, 3.0])},
        tune_config=TuneConfig(metric="loss", mode="min", num_samples=1),
    ).fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.config["x"] == 1.0
    assert best.metrics["loss"] == 1.0
    df = grid.get_dataframe()
    assert "config/x" in df.columns and len(df) == 3


def test_class_trainable_and_stop_criteria(ray_start):
    class Quad(Trainable):
        def setup(self, config):
            self.x = config["x"]

        def step(self):
            return {"score": -(self.x ** 2) + self.iteration}

    grid = tune.run(Quad, config={"x": tune.grid_search([-1.0, 0.0, 2.0])},
                    metric="score", mode="max",
                    stop={"training_iteration": 4})
    best = grid.get_best_result()
    assert best.config["x"] == 0.0
    assert all(r.metrics_history[-1]["training_iteration"] == 4
               for r in grid)


def test_asha_stops_bad_trials(ray_start):
    def train_fn(config):
        for i in range(20):
            tune.report({"acc": config["q"] + i * 0.01})

    # descending order: the strong trial fills rungs first, so weak trials
    # get cut even when actor starts are staggered (ASHA is asynchronous —
    # a weak trial that fills rungs before any strong one reports is allowed
    # to run on)
    grid = tune.run(
        train_fn, config={"q": tune.grid_search([0.9, 0.4, 0.2, 0.0])},
        metric="acc", mode="max", max_concurrent_trials=4,
        scheduler=ASHAScheduler(grace_period=2, reduction_factor=2, max_t=20),
    )
    assert grid.get_best_result().config["q"] == 0.9
    iters = sorted(len(r.metrics_history) for r in grid)
    assert iters[0] < 20  # at least one trial was stopped early


def test_trial_error_surfaces(ray_start):
    def train_fn(config):
        if config["x"] > 1:
            raise RuntimeError("bad config")
        tune.report({"loss": config["x"]})

    grid = tune.run(train_fn, config={"x": tune.grid_search([0.0, 5.0])},
                    metric="loss", mode="min")
    assert len(grid.errors) == 1
    assert grid.get_best_result().config["x"] == 0.0


def test_pbt_exploit(ray_start):
    class Learner(Trainable):
        def setup(self, config):
            self.weight = 0.0

        def step(self):
            self.weight += self.config["lr"]
            return {"score": self.weight}

        def save_checkpoint(self):
            return {"weight": self.weight}

        def load_checkpoint(self, state):
            self.weight = state["weight"]

    pbt = PopulationBasedTraining(
        perturbation_interval=2, seed=0,
        hyperparam_mutations={"lr": tune.uniform(0.1, 1.0)})
    grid = tune.run(Learner, config={"lr": tune.uniform(0.05, 1.0)},
                    num_samples=4, metric="score", mode="max", scheduler=pbt,
                    stop={"training_iteration": 8}, seed=0,
                    max_concurrent_trials=4)
    best = grid.get_best_result()
    assert best.metrics["score"] > 0
    assert len(grid) == 4


def test_max_failures_retry_resumes_from_checkpoint(ray_start):
    class Flaky(Trainable):
        def setup(self, config):
            self.n = 0
            self.died = False

        def step(self):
            self.n += 1
            # die exactly once, at n==3 of the first incarnation (a restored
            # actor comes back with n>=2 from the checkpoint, so n==3 is only
            # revisited after restore if the checkpoint was applied)
            if self.n == 3 and not self.died:
                import os

                os._exit(1)  # hard-kill the actor process
            return {"loss": 1.0 / self.n, "n": self.n,
                    "done": self.n >= 5}

        def save_checkpoint(self):
            return {"n": self.n, "died": True}

        def load_checkpoint(self, state):
            self.n = state["n"]
            self.died = state["died"]

    grid = tune.run(Flaky, config={}, metric="loss", mode="min",
                    max_failures=1, checkpoint_freq=1, num_samples=1)
    assert len(grid) == 1
    r = grid[0]
    assert r.error is None, r.error
    # resumed from a checkpoint rather than restarting at 0: n==1 is never
    # revisited (the crash-racing n==2 save may be lost, in which case the
    # n==1 checkpoint is the fallback and n==2 repeats — that's allowed)
    ns = [m["n"] for m in r.metrics_history if "n" in m]
    assert ns[-1] == 5
    assert ns.count(1) == 1


def test_error_without_retry_budget(ray_start):
    class Dies(Trainable):
        def step(self):
            raise RuntimeError("no")

    grid = tune.run(Dies, config={}, metric="loss", mode="min", num_samples=1)
    assert len(grid) == 1 and grid[0].error is not None


class TestTPESearcher:
    def test_converges_on_1d_quadratic(self):
        from ray_tpu.tune.search import TPESearcher

        space = {"x": tune.uniform(-5.0, 5.0)}
        tpe = TPESearcher(space, num_samples=60, seed=4, metric="score",
                          mode="max")
        best = -1e9
        for i in range(60):
            cfg = tpe.suggest(f"t{i}")
            s = -(cfg["x"] - 2.0) ** 2
            best = max(best, s)
            tpe.on_trial_complete(f"t{i}", {"score": s})
        assert best > -0.05, best

    def test_converges_in_log_space(self):
        import math

        from ray_tpu.tune.search import TPESearcher

        space = {"lr": tune.loguniform(1e-6, 1.0)}
        tpe = TPESearcher(space, num_samples=60, seed=0, metric="score",
                          mode="max")
        best_lr, best = None, -1e9
        for i in range(60):
            cfg = tpe.suggest(f"t{i}")
            s = -abs(math.log10(cfg["lr"]) + 3.0)  # optimum 1e-3
            if s > best:
                best, best_lr = s, cfg["lr"]
            tpe.on_trial_complete(f"t{i}", {"score": s})
        assert 1e-4 < best_lr < 1e-2, best_lr

    def test_categorical_concentrates_on_winner(self):
        from ray_tpu.tune.search import TPESearcher

        space = {"opt": tune.choice(["sgd", "adam", "rmsprop"])}
        tpe = TPESearcher(space, num_samples=60, seed=1, metric="score",
                          mode="max")
        late = []
        for i in range(60):
            cfg = tpe.suggest(f"t{i}")
            s = {"sgd": 0.0, "adam": 5.0, "rmsprop": 1.0}[cfg["opt"]]
            if i >= 40:
                late.append(cfg["opt"])
            tpe.on_trial_complete(f"t{i}", {"score": s})
        assert late.count("adam") > len(late) * 0.5, late

    def test_min_mode_and_exhaustion(self):
        from ray_tpu.tune.search import TPESearcher

        space = {"x": tune.uniform(0.0, 10.0)}
        tpe = TPESearcher(space, num_samples=5, seed=0, metric="loss",
                          mode="min")
        for i in range(5):
            cfg = tpe.suggest(f"t{i}")
            tpe.on_trial_complete(f"t{i}", {"loss": cfg["x"]})
        assert tpe.suggest("t5") is None

    def test_tuner_integration(self, ray_start):
        from ray_tpu.tune.search import TPESearcher

        def train_fn(config):
            tune.report({"loss": (config["x"] - 1.0) ** 2})

        space = {"x": tune.uniform(-4.0, 4.0)}
        grid = Tuner(
            train_fn,
            param_space=space,
            tune_config=TuneConfig(
                metric="loss", mode="min", num_samples=20,
                search_alg=TPESearcher(space, num_samples=20, seed=0),
            ),
        ).fit()
        assert len(grid) == 20
        assert grid.get_best_result().metrics["loss"] < 1.0
