"""Public collective API (process-local group registry + module functions).

Parity: ``python/ray/util/collective/collective.py`` (GroupManager :40).
Each participating process calls ``init_collective_group`` (typically from
inside its actor/task), then the module-level ops.  ``create_collective_
group`` does the same from the driver for a set of actors, using the
generic ``_remote_call`` mechanism so user classes need no extra methods.

Every group is wrapped in a :class:`~ray_tpu.util.collective.supervision.
SupervisedGroup` — the watchdog/flight-recorder spine — so every public
op carries a sequence number, lands in the flight recorder, and raises
``CollectiveAbortError`` (instead of hanging) when the group aborts.
``destroy_collective_group`` + ``init_collective_group`` is the supported
re-init path after an abort.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.util.collective.supervision import (  # noqa: F401 — re-export
    SupervisedGroup,
    flight_recorder_dump,
    resolve_timeout,
)
from ray_tpu.util.collective.types import Backend, ReduceOp

logger = logging.getLogger(__name__)


class GroupManager:
    def __init__(self):
        self._groups: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def create(self, backend, world_size: int, rank: int, group_name: str,
               timeout_s: Optional[float] = None):
        backend = Backend.parse(backend)
        with self._lock:
            if group_name in self._groups:
                raise RuntimeError(
                    f"collective group {group_name!r} already initialized"
                )
        if backend == Backend.TCP:
            from ray_tpu.util.collective.collective_group.tcp_group import (
                TcpGroup,
            )

            inner = TcpGroup(world_size, rank, group_name,
                             timeout_s=timeout_s)
        elif backend == Backend.XLA_MESH:
            # one PROCESS owning the whole device mesh: "ranks" are its
            # devices, so the declared (actor) world size must be 1 and
            # the group spans every visible device — a device-resident
            # value crossing this group's ops never host-stages
            import jax

            from ray_tpu.util.collective.collective_group.xla_group import (
                XlaMeshGroup,
            )

            if world_size != 1:
                raise ValueError(
                    "backend='xla_mesh' is the single-controller fast "
                    "path: exactly one participating process owns the "
                    f"mesh (got world_size={world_size}); use "
                    "backend='xla' for rank-per-process meshes")
            inner = XlaMeshGroup(len(jax.devices()), 0, group_name)
        else:
            from ray_tpu.util.collective.collective_group.xla_group import (
                XlaDistributedGroup,
            )

            inner = XlaDistributedGroup(world_size, rank, group_name,
                                        timeout_s=timeout_s)
        g = SupervisedGroup(inner, timeout_s=timeout_s,
                            backend=backend.value)
        with self._lock:
            self._groups[group_name] = g
        return g

    def get(self, group_name: str):
        g = self._groups.get(group_name)
        if g is None:
            raise RuntimeError(
                f"collective group {group_name!r} is not initialized in "
                f"this process; call init_collective_group first"
            )
        return g

    def exists(self, group_name: str) -> bool:
        return group_name in self._groups

    def destroy(self, group_name: str):
        with self._lock:
            g = self._groups.pop(group_name, None)
        if g is not None:
            g.destroy_group()


_group_mgr = GroupManager()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "tcp",
    group_name: str = "default",
    timeout_s: Optional[float] = None,
) -> None:
    """Initialize this process's membership in a collective group.

    ``timeout_s`` bounds rendezvous AND every op on this member (watchdog
    abort past it); default from ``RAY_TPU_COLLECTIVE_TIMEOUT`` env or the
    ``collective_op_timeout_s`` config flag.
    """
    _group_mgr.create(backend, world_size, rank, group_name,
                      timeout_s=timeout_s)


def _drop_rendezvous_keys(group_name: str) -> None:
    from ray_tpu.util.collective.supervision import drop_group_keys

    drop_group_keys(group_name)


def create_collective_group(
    actors: List[Any],
    world_size: int,
    ranks: Optional[List[int]] = None,
    backend: str = "tcp",
    group_name: str = "default",
    timeout_s: Optional[float] = None,
) -> None:
    """Driver-side setup: make ``actors`` a collective group.

    Dispatches ``init_collective_group`` into every actor (via the generic
    in-actor call, so user classes need no special methods) and blocks until
    all ranks have joined — bounded: an actor that dies (or never schedules)
    before joining fails the call within the timeout instead of hanging the
    driver forever, and the partially-formed group is torn down (joined
    ranks destroyed, rendezvous keys dropped) so the name is reusable.
    """
    import ray_tpu

    if ranks is None:
        ranks = list(range(len(actors)))
    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError(
            f"{len(actors)} actors, {len(ranks)} ranks, world={world_size}"
        )
    op_timeout = resolve_timeout(timeout_s)

    def _join(_self, world_size, rank, backend, group_name, timeout_s):
        init_collective_group(world_size, rank, backend, group_name,
                              timeout_s=timeout_s)
        return rank

    def _leave(_self, group_name):
        try:
            destroy_collective_group(group_name)
        except Exception:  # noqa: BLE001 — never joined / already gone
            pass
        return True

    try:
        refs = [
            a._remote_call.remote(_join, world_size, r, backend, group_name,
                                  timeout_s)
            for a, r in zip(actors, ranks)
        ]
        # margin above the rendezvous timeout: the join tasks themselves
        # need to schedule and run
        ray_tpu.get(refs, timeout=op_timeout + 30.0)
    except Exception:
        logger.warning(
            "collective group %r: not all %d rank(s) joined — tearing "
            "down the partial group", group_name, world_size)
        leave_refs = []
        for a in actors:
            try:
                leave_refs.append(a._remote_call.remote(_leave, group_name))
            except Exception:  # noqa: BLE001 — dead actor
                pass
        try:
            # ONE bounded wait for the whole teardown — a per-ref loop
            # would multiply the bound by world size
            ray_tpu.get(leave_refs, timeout=10)
        except Exception:  # noqa: BLE001
            pass
        _drop_rendezvous_keys(group_name)
        raise


def is_group_initialized(group_name: str = "default") -> bool:
    return _group_mgr.exists(group_name)


def destroy_collective_group(group_name: str = "default") -> None:
    _group_mgr.destroy(group_name)


def get_rank(group_name: str = "default") -> int:
    return _group_mgr.get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group_mgr.get(group_name).world_size


def get_group_state(group_name: str = "default") -> str:
    """Supervision state of this process's membership (READY | ABORTED).
    A destroyed group is removed from the registry entirely, so querying
    it raises RuntimeError like any other uninitialized name."""
    return _group_mgr.get(group_name).state.value


def allreduce(tensor, group_name: str = "default", op=ReduceOp.SUM):
    return _group_mgr.get(group_name).allreduce(tensor, op)


def barrier(group_name: str = "default") -> None:
    _group_mgr.get(group_name).barrier()


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op=ReduceOp.SUM):
    return _group_mgr.get(group_name).reduce(tensor, dst_rank, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group_mgr.get(group_name).broadcast(tensor, src_rank)


def allgather(tensor, group_name: str = "default"):
    return _group_mgr.get(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default", op=ReduceOp.SUM):
    return _group_mgr.get(group_name).reducescatter(tensor, op)


def send(tensor, dst_rank: int, group_name: str = "default", tag: int = 0):
    return _group_mgr.get(group_name).send(tensor, dst_rank, tag)


def recv(shape=None, dtype=None, src_rank: int = 0,
         group_name: str = "default", tag: int = 0):
    return _group_mgr.get(group_name).recv(shape, dtype, src_rank, tag)
