"""Shared helpers for building task specs from user calls.

Options normalization mirrors the reference's
``python/ray/_private/ray_option_utils.py``; argument promotion (large inline
args become objects) mirrors ``put_threshold`` behavior in
``python/ray/_raylet.pyx`` submit paths.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.config import config
from ray_tpu._private.ids import TaskID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.task_spec import SchedulingStrategy, TaskArg

_TASK_OPTIONS = {
    "num_cpus", "num_gpus", "num_tpus", "resources", "num_returns", "max_retries",
    "retry_exceptions", "scheduling_strategy", "name", "runtime_env", "memory",
    "label_selector", "priority", "_metadata",
    "_generator_backpressure_num_objects",
}
_ACTOR_OPTIONS = {
    "num_cpus", "num_gpus", "num_tpus", "resources", "max_restarts", "max_task_retries",
    "max_concurrency", "concurrency_groups", "name", "namespace",
    "lifetime", "get_if_exists",
    "scheduling_strategy", "runtime_env", "memory", "label_selector", "max_pending_calls",
    "priority", "_metadata",
}


def validate_options(options: Dict[str, Any], for_actor: bool) -> Dict[str, Any]:
    allowed = _ACTOR_OPTIONS if for_actor else _TASK_OPTIONS
    for k in options:
        if k not in allowed:
            kind = "actor" if for_actor else "task"
            raise ValueError(f"Invalid option {k!r} for {kind}; allowed: {sorted(allowed)}")
    return options


def coerce_num_returns(value) -> int:
    """``num_returns``: an int, or "streaming"/"dynamic" for generator
    tasks (reference ``num_returns="streaming"``, ``_raylet.pyx:279``)."""
    from ray_tpu._private.streaming import STREAMING_RETURNS

    if value in ("streaming", "dynamic"):
        return STREAMING_RETURNS
    n = int(value)
    if n < 0:
        raise ValueError("num_returns must be >= 0 or 'streaming'")
    return n


def build_resources(options: Dict[str, Any], default_num_cpus: float) -> Dict[str, float]:
    resources: Dict[str, float] = dict(options.get("resources") or {})
    reserved = {"CPU", "GPU", "TPU", "memory"} & resources.keys()
    if reserved:
        # reference: ray_option_utils rejects predefined keys in the custom
        # resources dict — silently overwriting them hides wrong demands
        raise ValueError(
            f"Use num_cpus/num_gpus/num_tpus/memory instead of passing "
            f"{sorted(reserved)} in resources="
        )
    num_cpus = options.get("num_cpus")
    resources["CPU"] = float(num_cpus if num_cpus is not None else default_num_cpus)
    if options.get("num_gpus"):
        resources["GPU"] = float(options["num_gpus"])
    if options.get("num_tpus"):
        resources["TPU"] = float(options["num_tpus"])
    if options.get("memory"):
        resources["memory"] = float(options["memory"])
    return {k: v for k, v in resources.items() if v != 0}


def normalize_strategy(strategy) -> SchedulingStrategy:
    if strategy is None or strategy == "DEFAULT":
        return SchedulingStrategy()
    if strategy == "SPREAD":
        return SchedulingStrategy(kind="SPREAD")
    if isinstance(strategy, SchedulingStrategy):
        return strategy
    # duck-typed public strategies from ray_tpu.util.scheduling_strategies
    kind = type(strategy).__name__
    if kind == "NodeAffinitySchedulingStrategy":
        return SchedulingStrategy(kind="NODE_AFFINITY", node_id=strategy.node_id,
                                  soft=strategy.soft)
    if kind == "PlacementGroupSchedulingStrategy":
        pg = strategy.placement_group
        if pg is None:
            # the explicit opt-OUT of gang inheritance (reference
            # semantics): a task inside a capture_child_tasks gang
            # passes placement_group=None to schedule outside it
            return SchedulingStrategy()
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP",
            placement_group_id=pg.id,
            bundle_index=strategy.placement_group_bundle_index,
            capture_child_tasks=strategy.placement_group_capture_child_tasks,
        )
    if kind == "NodeLabelSchedulingStrategy":
        return SchedulingStrategy(kind="NODE_LABEL", label_selector=dict(strategy.hard or {}))
    raise ValueError(f"Unsupported scheduling strategy: {strategy!r}")


def resolve_strategy(options_strategy, worker) -> SchedulingStrategy:
    """Normalize the user's strategy, inheriting gang membership.

    Reference semantics
    (``placement_group_capture_child_tasks``): a task/actor submitted
    INSIDE a gang whose own strategy captured child tasks lands in the
    same gang by default — nested scheduling stays on the reserved
    slice.  An explicit strategy (including an explicit None-PG
    strategy) always wins; only the no-strategy default inherits.
    """
    if options_strategy is None and worker is not None:
        pg_id, capture = worker.current_placement_group_info()
        if pg_id is not None and capture:
            return SchedulingStrategy(
                kind="PLACEMENT_GROUP", placement_group_id=pg_id,
                bundle_index=-1, capture_child_tasks=True)
    return normalize_strategy(options_strategy)


def build_args(worker, args: Tuple, kwargs: Dict
               ) -> Tuple[List[TaskArg], List[str], List[ObjectRef]]:
    """Serialize positional + keyword args; promote large values to objects.

    Returns ``(task_args, kw_keys, nested_refs)`` — ``nested_refs`` are the
    live ObjectRefs serialized *inside* inline argument values.  The
    submitter must hold them until the task reply (alongside the top-level
    arg refs) so a task queued arbitrarily long can never have a nested
    argument object freed underneath it (no TTL in this path; the
    reference's submitted-task borrow count, ``reference_count.h``).
    """
    task_args: List[TaskArg] = []
    nested_refs: List[ObjectRef] = []
    kw_keys = list(kwargs.keys())
    for value in list(args) + [kwargs[k] for k in kw_keys]:
        if isinstance(value, ObjectRef):
            task_args.append(TaskArg(is_ref=True, payload=value))
            continue
        payload, refs = serialization.serialize(value)
        if len(payload) > config.max_inline_object_size:
            ref = worker.put(value)
            task_args.append(TaskArg(is_ref=True, payload=ref))
        else:
            nested_refs.extend(refs)
            task_args.append(TaskArg(is_ref=False, payload=payload))
    return task_args, kw_keys, nested_refs


def next_task_id(worker) -> TaskID:
    ctx = worker.current_ctx()
    ctx.submit_index += 1
    return TaskID.of(ctx.task_id, ctx.submit_index)
