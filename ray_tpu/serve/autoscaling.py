"""Signal-driven replica autoscaling: the pool-target decision logic.

The serve controller's original autoscaler watched ONE signal (average
in-flight requests per replica).  Disaggregated LLM serving needs
per-pool targets driven by the signals that actually distinguish the
pools: a **prefill** pool saturates on *queue depth* (prompts waiting
for a prefill slot — the router's bounded queue plus the engines' own
admission queues) and on overload verdicts (sheds, deadline misses),
while a **decode** pool saturates on *slot occupancy* and *block-pool
pressure* (every decode slot busy / KV blocks near exhaustion) long
before its request queue grows — a decode request parks in a slot for
its whole generation.

This module is the PURE half: :func:`desired_delta` maps one pool's
:class:`PoolSignals` snapshot to ``+1 / 0 / -1`` with no clocks and no
cluster state, so the synthetic-ramp tests drive it directly.  The
controller (``serve/controller.py``) owns the stateful half: collecting
signals (replica probes, aggregated ``OverloadStats``, the engine-stats
records LLM replicas publish to the GCS KV namespace ``"llm"``),
applying the up/downscale delays, and actuating ``goal_replicas``
through the existing reconcile/start-first machinery.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from ray_tpu.serve.deployment import AutoscalingConfig


@dataclasses.dataclass
class PoolSignals:
    """One deployment's load snapshot for a single autoscale tick.

    ``shed_delta`` / ``expired_delta`` are events since the LAST tick
    (monotonic counters differenced by the controller); everything else
    is an instantaneous gauge.  Engine signals default to ``None`` for
    deployments that publish no engine stats (plain serve apps) — a
    missing signal never votes."""

    replicas: int = 0
    ongoing_avg: float = 0.0          # in-flight requests per replica
    router_queued: int = 0            # aggregated router queue gauge
    shed_delta: int = 0               # sheds since last tick
    expired_delta: int = 0            # deadline misses since last tick
    engine_queue_avg: Optional[float] = None   # engine-queued per replica
    slot_occupancy: Optional[float] = None     # avg slots_used/slots_total
    block_pressure: Optional[float] = None     # avg 1 - available/capacity


def pool_signals_from_engine_records(
        records, replicas: int, *, ongoing_avg: float = 0.0,
        router_queued: int = 0, shed_delta: int = 0,
        expired_delta: int = 0) -> PoolSignals:
    """Fold the engine-stats KV records of one deployment's replicas
    into a :class:`PoolSignals` (records: the dicts LLM replicas publish
    — ``queued``/``adopt_queued``/``slot_occupancy``/``block_pressure``).
    """
    sig = PoolSignals(replicas=replicas, ongoing_avg=ongoing_avg,
                      router_queued=router_queued, shed_delta=shed_delta,
                      expired_delta=expired_delta)
    recs = [r for r in records or [] if isinstance(r, dict)]
    if recs:
        n = len(recs)
        sig.engine_queue_avg = sum(
            float(r.get("queued", 0)) + float(r.get("adopt_queued", 0))
            for r in recs) / n
        sig.slot_occupancy = sum(
            float(r.get("slot_occupancy", 0.0)) for r in recs) / n
        sig.block_pressure = sum(
            float(r.get("block_pressure", 0.0)) for r in recs) / n
    return sig


def desired_delta(cfg: AutoscalingConfig, sig: PoolSignals) -> int:
    """+1 (scale up), -1 (scale down), or 0 — pure decision.

    Upscale when ANY enforced signal crosses its target: load must be
    relieved even if only one dimension is saturated (a decode pool at
    full slot occupancy with an empty queue still needs a replica).
    Downscale only when EVERY enforced signal sits below half its
    target and no overload events landed this tick — one hot dimension
    vetoes shrinking.  Delays/hysteresis are the controller's job."""
    replicas = max(1, sig.replicas)
    queue_depth = sig.router_queued / replicas
    if sig.engine_queue_avg is not None:
        queue_depth += sig.engine_queue_avg

    up = False
    if cfg.target_ongoing_requests is not None \
            and sig.ongoing_avg > cfg.target_ongoing_requests:
        up = True
    if cfg.target_queue_depth is not None \
            and queue_depth > cfg.target_queue_depth:
        up = True
    if cfg.upscale_on_overload and (sig.shed_delta > 0
                                    or sig.expired_delta > 0):
        up = True
    if cfg.target_slot_occupancy is not None \
            and sig.slot_occupancy is not None \
            and sig.slot_occupancy > cfg.target_slot_occupancy:
        up = True
    if cfg.target_block_pressure is not None \
            and sig.block_pressure is not None \
            and sig.block_pressure > cfg.target_block_pressure:
        up = True
    if up:
        return 1

    down = True
    if cfg.target_ongoing_requests is not None \
            and sig.ongoing_avg >= 0.5 * cfg.target_ongoing_requests:
        down = False
    if cfg.target_queue_depth is not None \
            and queue_depth >= 0.5 * cfg.target_queue_depth:
        down = False
    if cfg.target_slot_occupancy is not None \
            and sig.slot_occupancy is not None \
            and sig.slot_occupancy >= 0.5 * cfg.target_slot_occupancy:
        down = False
    if cfg.target_block_pressure is not None \
            and sig.block_pressure is not None \
            and sig.block_pressure >= 0.5 * cfg.target_block_pressure:
        down = False
    if sig.shed_delta > 0 or sig.expired_delta > 0:
        down = False
    return -1 if down else 0


def autoscaling_config_from_dict(asc: Dict[str, Any]) -> AutoscalingConfig:
    """Rebuild an :class:`AutoscalingConfig` from the controller's stored
    config dict, tolerating records written before the signal fields
    existed.  Legacy ongoing-average semantics are preserved, with ONE
    deliberate upgrade: overload events (sheds, deadline misses) now
    vote for upscale by default — a pool sized to shed sustained excess
    on purpose should set ``upscale_on_overload=False``."""
    names = {f.name for f in dataclasses.fields(AutoscalingConfig)}
    return AutoscalingConfig(**{k: v for k, v in (asc or {}).items()
                                if k in names})
