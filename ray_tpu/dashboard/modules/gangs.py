"""Gangs module: the slice-native gang-scheduling panel.

Serves the persisted GCS gang table (state machine, priorities,
preemption claims, fate-share markers, bounded transition history) and
the derived slice-topology table — the same records ``raytpu status``
and ``util.state.list_gangs`` read, so all three surfaces agree.
"""

from __future__ import annotations


def _jsonable_gang(g):
    out = dict(g)
    out["gang_id"] = g["gang_id"].hex()
    if out.get("preempted_by"):
        out["preempted_by"] = out["preempted_by"].hex()
    return out


def routes(gcs, helpers):
    jresp = helpers["jresp"]

    async def api_gangs(_req):
        gangs = [_jsonable_gang(g) for g in await gcs.handle_list_gangs()]
        slices = await gcs.handle_get_slice_topology()
        return jresp({"gangs": gangs, "slices": slices})

    return [("GET", "/api/gangs", api_gangs)]
