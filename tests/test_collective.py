"""Collective layer tests.

The 4-CPU-worker allreduce is the north-star smoke config (BASELINE.md:
"collective allreduce — 4 CPU workers").
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import collective as col
from ray_tpu.util.collective.types import ReduceOp


@ray_tpu.remote
class Worker:
    def __init__(self, rank: int, world: int):
        self.rank = rank
        self.world = world

    def setup(self, group_name):
        col.init_collective_group(self.world, self.rank, "tcp", group_name)
        return self.rank

    def do_allreduce(self, group_name):
        x = np.full((4,), float(self.rank + 1))
        return col.allreduce(x, group_name)

    def do_ops(self, group_name):
        out = {}
        out["bcast"] = col.broadcast(
            np.full((2,), float(self.rank)), src_rank=2,
            group_name=group_name,
        )
        out["gather"] = col.allgather(
            np.array([self.rank]), group_name=group_name
        )
        out["rs"] = col.reducescatter(
            np.arange(8, dtype=np.float64), group_name=group_name
        )
        out["max"] = col.allreduce(
            np.array([float(self.rank)]), group_name, op=ReduceOp.MAX
        )
        col.barrier(group_name)
        out["rank"] = col.get_rank(group_name)
        return out

    def do_sendrecv(self, group_name):
        if self.rank == 0:
            col.send(np.array([42.0]), dst_rank=3, group_name=group_name)
            return None
        if self.rank == 3:
            return col.recv(src_rank=0, group_name=group_name)
        return None


@pytest.fixture
def group4(ray_start):
    import uuid

    name = f"g-{uuid.uuid4().hex[:8]}"
    workers = [Worker.remote(i, 4) for i in range(4)]
    ray_tpu.get([w.setup.remote(name) for w in workers])
    yield workers, name
    for w in workers:
        ray_tpu.kill(w)


class TestTcpCollective:
    def test_allreduce_4_cpu_workers(self, group4):
        workers, name = group4
        outs = ray_tpu.get([w.do_allreduce.remote(name) for w in workers])
        for o in outs:
            np.testing.assert_allclose(o, np.full((4,), 10.0))

    def test_all_ops(self, group4):
        workers, name = group4
        outs = ray_tpu.get([w.do_ops.remote(name) for w in workers])
        for r, o in enumerate(outs):
            np.testing.assert_allclose(o["bcast"], np.full((2,), 2.0))
            np.testing.assert_allclose(
                np.concatenate(o["gather"]), np.arange(4)
            )
            # reducescatter of 4x arange(8): each rank gets its 2-chunk x4.
            np.testing.assert_allclose(
                o["rs"], 4 * np.arange(8)[r * 2:(r + 1) * 2]
            )
            assert o["max"][0] == 3.0
            assert o["rank"] == r

    def test_send_recv(self, group4):
        workers, name = group4
        outs = ray_tpu.get([w.do_sendrecv.remote(name) for w in workers])
        np.testing.assert_allclose(outs[3], np.array([42.0]))

    def test_create_collective_group_from_driver(self, ray_start):
        import uuid

        name = f"g-{uuid.uuid4().hex[:8]}"
        workers = [Worker.remote(i, 2) for i in range(2)]
        col.create_collective_group(workers, 2, group_name=name)
        outs = ray_tpu.get([w.do_allreduce.remote(name) for w in workers])
        np.testing.assert_allclose(outs[0], np.full((4,), 3.0))
        for w in workers:
            ray_tpu.kill(w)

    def test_uninitialized_group_raises(self, ray_start):
        with pytest.raises(RuntimeError, match="not initialized"):
            col.allreduce(np.zeros(2), "nope")


@ray_tpu.remote
class XlaDistWorker:
    """One rank of a rank-per-process jax.distributed group — a REAL OS
    process (dedicated actor worker), not a thread or a virtual device."""

    def __init__(self, rank: int, world: int):
        self.rank = rank
        self.world = world

    def setup(self, group_name):
        col.init_collective_group(self.world, self.rank, "xla", group_name)
        import jax

        return {
            "rank": self.rank,
            "pid": __import__("os").getpid(),
            "n_global_devices": len(jax.devices()),
            "n_local_devices": len(jax.local_devices()),
            "process_index": jax.process_index(),
        }

    def do_ops(self, group_name):
        out = {}
        out["ar"] = col.allreduce(
            np.full((4,), float(self.rank + 1), np.float32), group_name)
        out["max"] = col.allreduce(
            np.array([float(self.rank)], np.float32), group_name,
            op=ReduceOp.MAX)
        out["bcast"] = col.broadcast(
            np.full((2,), float(self.rank), np.float32), src_rank=1,
            group_name=group_name)
        out["gather"] = col.allgather(
            np.array([self.rank], np.float32), group_name=group_name)
        out["rs"] = col.reducescatter(
            np.arange(4, dtype=np.float32), group_name=group_name)
        col.barrier(group_name)
        return out

    def do_sendrecv(self, group_name):
        if self.rank == 0:
            col.send(np.array([7.0, 8.0]), dst_rank=1,
                     group_name=group_name)
            col.send(np.array([9.0]), dst_rank=1, group_name=group_name)
            return None
        first = col.recv(src_rank=0, group_name=group_name)
        second = col.recv(src_rank=0, group_name=group_name)
        return first, second

    def teardown(self, group_name):
        col.destroy_collective_group(group_name)


class TestXlaDistributedGroup:
    """VERDICT r4 missing #1 / weak #1: the multi-PROCESS SPMD path
    executed for real — N OS worker processes rendezvous through the
    internal KV, call jax.distributed.initialize, and run collectives
    over the global mesh (reference: NCCLGroup rank-per-process,
    ``nccl_collective_group.py``)."""

    @pytest.fixture
    def dist2(self, ray_start):
        import uuid

        name = f"xd-{uuid.uuid4().hex[:8]}"
        workers = [XlaDistWorker.remote(i, 2) for i in range(2)]
        # setup must be CONCURRENT: initialize blocks until all ranks join
        infos = ray_tpu.get([w.setup.remote(name) for w in workers],
                            timeout=180)
        yield workers, name, infos
        try:
            ray_tpu.get([w.teardown.remote(name) for w in workers],
                        timeout=60)
        except Exception:
            pass
        for w in workers:
            ray_tpu.kill(w)

    def test_global_mesh_formed_across_processes(self, dist2):
        _, _, infos = dist2
        # two DISTINCT OS processes, one jax world
        assert infos[0]["pid"] != infos[1]["pid"]
        for i, info in enumerate(infos):
            assert info["process_index"] == i
            # global view spans both processes' local devices
            assert info["n_global_devices"] == 2 * info["n_local_devices"]

    def test_collectives_over_global_mesh(self, dist2):
        workers, name, _ = dist2
        outs = ray_tpu.get([w.do_ops.remote(name) for w in workers],
                           timeout=300)
        for r, o in enumerate(outs):
            np.testing.assert_allclose(o["ar"], np.full((4,), 3.0))
            assert o["max"][0] == 1.0
            np.testing.assert_allclose(o["bcast"], np.full((2,), 1.0))
            np.testing.assert_allclose(
                np.concatenate(o["gather"]), [0.0, 1.0])
            # reducescatter of 2x arange(4): rank r gets its 2-chunk x2
            np.testing.assert_allclose(
                o["rs"], 2 * np.arange(4, dtype=np.float32)[r * 2:(r + 1) * 2])

    def test_send_recv_across_processes(self, dist2):
        workers, name, _ = dist2
        outs = ray_tpu.get([w.do_sendrecv.remote(name) for w in workers],
                           timeout=120)
        first, second = outs[1]
        np.testing.assert_allclose(first, [7.0, 8.0])
        np.testing.assert_allclose(second, [9.0])


@ray_tpu.remote
class ChaosWorker:
    """One rank of a supervised TCP group, with in-process fault arming."""

    def __init__(self, rank: int, world: int):
        self.rank = rank
        self.world = world

    def setup(self, group_name, timeout_s=None):
        col.init_collective_group(self.world, self.rank, "tcp", group_name,
                                  timeout_s=timeout_s)
        return self.rank

    def arm(self, site, nth=1, count=1, kind="connection"):
        from ray_tpu.util import fault_injection as fi

        fi.arm(site, nth=nth, count=count, exc=kind)
        return True

    def do_allreduce(self, group_name, dim=4):
        x = np.full((dim,), float(self.rank + 1))
        return col.allreduce(x, group_name)

    def do_reduce(self, group_name, dim=4):
        x = np.full((dim,), float(self.rank + 1))
        return col.reduce(x, dst_rank=0, group_name=group_name)

    def group_state(self, group_name):
        return col.get_group_state(group_name)

    def dump(self, group_name):
        return col.flight_recorder_dump(group_name)

    def destroy(self, group_name):
        col.destroy_collective_group(group_name)
        return True


def _chaos_group(n, timeout_s=4.0):
    import uuid

    name = f"cg-{uuid.uuid4().hex[:8]}"
    workers = [ChaosWorker.remote(i, n) for i in range(n)]
    ray_tpu.get([w.setup.remote(name, timeout_s) for w in workers],
                timeout=60)
    return workers, name


def _expect_abort(ref, timeout=60):
    """get(ref) must raise with CollectiveAbortError in the remote trace;
    returns the error text for diagnosis assertions."""
    import time as _t

    t0 = _t.monotonic()
    with pytest.raises(Exception) as ei:
        ray_tpu.get(ref, timeout=timeout)
    text = str(ei.value)
    assert "CollectiveAbortError" in text, text
    return text, _t.monotonic() - t0


@pytest.mark.chaos
class TestCollectiveWatchdog:
    """The collective supervision layer under deterministic chaos: hangs,
    member/leader death, desync — surviving ranks must raise
    ``CollectiveAbortError`` with the culprit named, never block forever.
    """

    def test_injected_hang_aborts_peers_within_timeout(self, ray_start):
        """`delay` fault on one rank = a mid-collective hang: peers abort
        within the watchdog timeout and the diagnosis names the lagging
        rank/seq (acceptance: chaos proof, hang leg)."""
        workers, name = _chaos_group(4, timeout_s=4.0)
        try:
            # rank 3 sleeps 30s inside its next collective op — far past
            # the 4s group timeout
            assert ray_tpu.get(
                workers[3].arm.remote("collective.op", kind="delay:30"))
            refs = [w.do_allreduce.remote(name) for w in workers[:3]]
            for r in refs:
                text, elapsed = _expect_abort(r)
                assert elapsed < 25.0, "peer blocked past the watchdog"
                assert "rank(s) [3]" in text or "rank 3" in text, text
                assert "seq=1" in text, text
            # the flight recorder on a surviving rank shows the aborted op
            entries = ray_tpu.get(workers[0].dump.remote(name), timeout=30)
            assert any(e["status"] == "aborted" and e["op"] == "allreduce"
                       for e in entries), entries
            assert ray_tpu.get(
                workers[0].group_state.remote(name)) == "ABORTED"
        finally:
            for w in workers:
                ray_tpu.kill(w)

    def test_member_sigkill_mid_allreduce(self, ray_isolated):
        """A member dying mid-collective (real SIGKILL, the preempted-host
        shape): the leader detects the closed connection and aborts every
        peer promptly, naming the dead rank."""
        workers, name = _chaos_group(4, timeout_s=8.0)
        assert ray_tpu.get(
            workers[2].arm.remote("collective.op", kind="sigkill"))
        # rank 2 dies inside the op; don't wait on its ref
        workers[2].do_allreduce.remote(name)
        refs = [workers[i].do_allreduce.remote(name) for i in (0, 1, 3)]
        for r in refs:
            text, elapsed = _expect_abort(r)
            assert elapsed < 30.0
            assert "rank 2" in text, text

    def test_leader_death_aborts_members(self, ray_isolated):
        """The leader process dying mid-collective: members' sockets
        collapse and every survivor raises CollectiveAbortError instead
        of blocking on a dead server."""
        workers, name = _chaos_group(3, timeout_s=8.0)
        assert ray_tpu.get(
            workers[0].arm.remote("collective.op", kind="sigkill"))
        workers[0].do_allreduce.remote(name)
        refs = [workers[i].do_allreduce.remote(name) for i in (1, 2)]
        for r in refs:
            text, elapsed = _expect_abort(r)
            assert elapsed < 30.0

    def test_shape_desync_aborts_naming_diverging_rank(self, ray_start):
        """Mismatched shapes across ranks at one seq = desync: the leader
        majority-votes and aborts the group naming the diverger."""
        workers, name = _chaos_group(4, timeout_s=30.0)
        try:
            refs = [w.do_allreduce.remote(name, dim=(6 if i == 1 else 4))
                    for i, w in enumerate(workers)]
            for r in refs:
                text, _ = _expect_abort(r)
                assert "desync" in text, text
                assert "rank(s) [1]" in text, text
        finally:
            for w in workers:
                ray_tpu.kill(w)

    def test_reduce_shape_desync_aborts(self, ray_start):
        """`reduce` is shape-strict too: a ragged reduce must abort with
        the diverging rank named, not blow up the leader's compute."""
        workers, name = _chaos_group(3, timeout_s=30.0)
        try:
            refs = [w.do_reduce.remote(name, dim=(5 if i == 2 else 4))
                    for i, w in enumerate(workers)]
            for r in refs:
                text, _ = _expect_abort(r)
                assert "desync" in text and "rank(s) [2]" in text, text
        finally:
            for w in workers:
                ray_tpu.kill(w)

    def test_abort_destroy_reinit_allreduce(self, ray_start):
        """destroy + init on an aborted group is the supported re-init
        path: the re-formed group gets a fresh epoch and works."""
        workers, name = _chaos_group(4, timeout_s=30.0)
        try:
            refs = [w.do_allreduce.remote(name, dim=(6 if i == 1 else 4))
                    for i, w in enumerate(workers)]
            for r in refs:
                _expect_abort(r)
            ray_tpu.get([w.destroy.remote(name) for w in workers],
                        timeout=30)
            ray_tpu.get([w.setup.remote(name, 30.0) for w in workers],
                        timeout=60)
            outs = ray_tpu.get(
                [w.do_allreduce.remote(name) for w in workers], timeout=60)
            for o in outs:
                np.testing.assert_allclose(o, np.full((4,), 10.0))
        finally:
            for w in workers:
                ray_tpu.kill(w)

    def test_stale_leader_rendezvous_rejected(self, ray_isolated):
        """A crashed leader leaves its KV entry dangling; a re-formed
        group under the same name must epoch past it, never adopt the
        dead address (satellite: stale-leader rendezvous)."""
        workers, name = _chaos_group(2, timeout_s=6.0)
        assert ray_tpu.get(
            workers[0].arm.remote("collective.op", kind="sigkill"))
        workers[0].do_allreduce.remote(name)
        _expect_abort(workers[1].do_allreduce.remote(name))
        # the dead leader's entry is still in the KV (no destroy ran);
        # fresh workers re-form the SAME group name
        fresh = [ChaosWorker.remote(i, 2) for i in range(2)]
        ray_tpu.get([w.setup.remote(name, 6.0) for w in fresh], timeout=60)
        outs = ray_tpu.get([w.do_allreduce.remote(name) for w in fresh],
                           timeout=60)
        for o in outs:
            np.testing.assert_allclose(o, np.full((4,), 3.0))

    def test_create_collective_group_dead_actor_times_out(self,
                                                          ray_isolated):
        """Driver-side join must not hang when an actor dies before
        joining: bounded get + partial-group teardown (satellite)."""
        import time as _t
        import uuid

        name = f"cg-{uuid.uuid4().hex[:8]}"
        workers = [ChaosWorker.remote(i, 2) for i in range(2)]
        ray_tpu.kill(workers[1])
        t0 = _t.monotonic()
        with pytest.raises(Exception):
            col.create_collective_group(workers, 2, group_name=name,
                                        timeout_s=5.0)
        assert _t.monotonic() - t0 < 60.0
        # rendezvous keys were swept (only the epoch counter survives,
        # so a straggler from the failed join can't chase the next
        # incarnation), and the name is reusable
        from ray_tpu.experimental import internal_kv

        left = internal_kv._internal_kv_list(
            f"collective/{name}/", namespace="collective")
        assert set(left) <= {f"collective/{name}/epoch"}, left

    def test_drain_abort_phrase_contract(self):
        """The controller's drain-abort classifier string-matches the
        watchdog's abort phrasing across a process boundary — this test
        pins producer and matcher together so a reword can't silently
        start charging planned migrations to the failure budget."""
        import inspect

        from ray_tpu.train.controller import _drain_caused_collective_abort
        from ray_tpu.util.collective import supervision

        producer_src = inspect.getsource(
            supervision.Watchdog._check_membership)
        for phrase in ("lost to node drain", "drain deadline expired"):
            assert phrase in producer_src, phrase
        assert _drain_caused_collective_abort(
            "TaskError: CollectiveAbortError: collective group "
            "'train::r/g1' aborted (rank 0, seq 3): rank 1 lost to node "
            "drain: node ab12 drain deadline expired (spot reclaim)")
        # a run NAMED "drain" must not classify, nor non-abort errors
        assert not _drain_caused_collective_abort(
            "TaskError: CollectiveAbortError: collective group "
            "'train::drain-run/g1' aborted (rank 0, seq 3): op allreduce "
            "seq=3 exceeded timeout")
        assert not _drain_caused_collective_abort(
            "ValueError: node drain something")
        assert not _drain_caused_collective_abort(None)

    def test_list_collective_groups_surfaces_members(self, ray_start):
        """State-API surfacing: member records with progress appear while
        a group is live (watchdog heartbeats into the KV)."""
        from ray_tpu.util import state as state_api

        workers, name = _chaos_group(2, timeout_s=30.0)
        try:
            ray_tpu.get([w.do_allreduce.remote(name) for w in workers],
                        timeout=60)
            groups = [g for g in state_api.list_collective_groups()
                      if g["group_name"] == name]
            assert groups and groups[0]["world_size"] == 2
            assert groups[0]["epoch"] >= 1
            assert {m["rank"] for m in groups[0]["members"]} == {0, 1}
            # the dashboard panel serves the same aggregation
            import json as json_mod
            import urllib.request

            url = ray_tpu.dashboard_url()
            with urllib.request.urlopen(f"{url}/api/collective",
                                        timeout=10) as resp:
                dash = json_mod.loads(resp.read())
            mine = [g for g in dash["groups"] if g["group_name"] == name]
            assert mine and mine[0]["joined"] == 2, dash
        finally:
            for w in workers:
                ray_tpu.kill(w)


@pytest.mark.chaos
@pytest.mark.slow
class TestTrainCollectiveRecovery:
    def test_train_recovers_from_collective_hang(self, ray_start,
                                                 tmp_path):
        """Acceptance e2e: a mid-allreduce hang in one rank aborts the
        collective within the timeout, surfaces as a worker failure, and
        the controller restarts the group from the latest checkpoint —
        the re-formed generation gets a fresh group and finishes."""
        from ray_tpu import train

        def loop(config):
            import os
            import tempfile

            import numpy as np

            from ray_tpu import train
            from ray_tpu.train.checkpoint import Checkpoint
            from ray_tpu.util import collective as col
            from ray_tpu.util import fault_injection as fi

            ctx = train.get_context()
            group = ctx.collective_group(timeout_s=4.0)
            start = 0
            ckpt = ctx.get_checkpoint()
            if ckpt is not None:
                with open(os.path.join(ckpt.path, "step.txt")) as f:
                    start = int(f.read()) + 1
            for step in range(start, 4):
                if (step == 2 and ckpt is None
                        and ctx.get_world_rank() == 1):
                    # first generation only: rank 1 hangs inside the
                    # step-2 allreduce, far past the 4s group timeout
                    fi.arm("collective.op", nth=1, exc="delay:60")
                out = col.allreduce(
                    np.full((2,), float(step)), group)
                d = tempfile.mkdtemp()
                with open(os.path.join(d, "step.txt"), "w") as f:
                    f.write(str(step))
                train.report({"step": step, "allreduce0": float(out[0])},
                             checkpoint=Checkpoint(d))

        res = train.DataParallelTrainer(
            loop,
            scaling_config=train.ScalingConfig(num_workers=2),
            run_config=train.RunConfig(
                name="coll-hang-run", storage_path=str(tmp_path),
                failure_config=train.FailureConfig(max_failures=2)),
        ).fit()
        assert res.error is None, res.error
        assert res.metrics["step"] == 3
        # both generations contributed: the run recovered, it did not
        # just succeed first try
        steps = [m["step"] for m in res.metrics_history]
        assert steps[-1] == 3 and 2 in steps, steps


class TestXlaMeshGroup:
    def test_mesh_collectives(self):
        from ray_tpu.util.collective.collective_group.xla_group import (
            XlaMeshGroup,
        )

        g = XlaMeshGroup(8)
        x = np.arange(8, dtype=np.float32)[:, None]  # one scalar per device
        out = np.asarray(g.allreduce(x))
        np.testing.assert_allclose(out, [28.0])
        out = np.asarray(g.allgather(np.arange(8, dtype=np.float32)[:, None]))
        np.testing.assert_allclose(out[:, 0], np.arange(8))
        out = np.asarray(g.broadcast(x, src_rank=3))
        np.testing.assert_allclose(out[:, 0], np.full((8,), 3.0))
        g.barrier()
