"""Pipeline parallelism (mesh "pp" axis): numerics + trainer integration.

The reference has no in-graph PP (delegated to vLLM,
``vllm_models.py:127``); these tests validate the shard_map/ppermute
schedule against the plain scan path on the virtual 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# JAX-compile-heavy tier: deselect with -m 'not slow' for fast runs
pytestmark = pytest.mark.slow

from ray_tpu.parallel import MeshConfig, create_mesh, pipeline_apply


def test_pipeline_apply_matches_scan():
    n_layers, b, d = 4, 8, 16
    ws = jax.random.normal(jax.random.PRNGKey(0), (n_layers, d, d)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (b, d))
    layer_fn = lambda h, w: jnp.tanh(h @ w)

    def plain(x):
        for i in range(n_layers):
            x = layer_fn(x, ws[i])
        return x

    mesh = create_mesh(MeshConfig(dp=2, pp=4))
    out = jax.jit(
        lambda ws, x: pipeline_apply(layer_fn, ws, x, mesh=mesh,
                                     num_microbatches=4)
    )(ws, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(plain(x)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_grad_matches_scan():
    n_layers, b, d = 4, 4, 8
    ws = jax.random.normal(jax.random.PRNGKey(0), (n_layers, d, d)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (b, d))
    layer_fn = lambda h, w: jnp.tanh(h @ w)

    def loss_plain(ws):
        h = x
        for i in range(n_layers):
            h = layer_fn(h, ws[i])
        return jnp.sum(h**2)

    mesh = create_mesh(MeshConfig(dp=1, pp=2, tp=2, sp=2))
    def loss_pp(ws):
        h = pipeline_apply(layer_fn, ws, x, mesh=mesh, num_microbatches=2)
        return jnp.sum(h**2)

    g_ref = jax.grad(loss_plain)(ws)
    g_pp = jax.jit(jax.grad(loss_pp))(ws)
    np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-6)


def test_llama_pp_loss_and_grads_match():
    from ray_tpu.models.llama import LlamaConfig, llama_init, llama_loss

    cfg = LlamaConfig.tiny(num_layers=2, attention_impl="ref")
    params = llama_init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 24), 0,
                                cfg.vocab_size)
    batch = {"tokens": tokens}
    ref = llama_loss(params, batch, cfg)

    mesh = create_mesh(MeshConfig(dp=2, fsdp=2, pp=2))
    pp = jax.jit(lambda p, b: llama_loss(p, b, cfg, mesh=mesh))(params, batch)
    np.testing.assert_allclose(float(pp), float(ref), rtol=2e-5)

    g_ref = jax.grad(lambda p: llama_loss(p, batch, cfg))(params)
    g_pp = jax.jit(
        jax.grad(lambda p: llama_loss(p, batch, cfg, mesh=mesh))
    )(params)
    errs = jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), g_ref, g_pp
    )
    assert max(jax.tree.leaves(errs)) < 1e-4


def test_trainer_pp_tp_step():
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.models.training import make_llama_trainer

    cfg = LlamaConfig.tiny(num_layers=2, attention_impl="ref")
    mesh = create_mesh(MeshConfig(dp=2, pp=2, tp=2))
    tr = make_llama_trainer(cfg, mesh)
    state = tr.init_state(jax.random.PRNGKey(0))
    # Stage-sharded layer stack: leading (layers) dim over pp.
    layer_sh = jax.tree.leaves(state["params"]["layers"])[0].sharding
    assert layer_sh.spec[0] == "pp"
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                cfg.vocab_size)
    batch = tr.shard_batch({"tokens": tokens})
    losses = []
    for _ in range(4):
        state, m = tr.step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_pipeline_validates_divisibility():
    ws = jnp.zeros((3, 4, 4))
    x = jnp.zeros((4, 4))
    mesh = create_mesh(MeshConfig(dp=4, pp=2))
    with pytest.raises(ValueError):
        pipeline_apply(lambda h, w: h, ws, x, mesh=mesh)
    ws2 = jnp.zeros((4, 4, 4))
    x2 = jnp.zeros((5, 4))
    with pytest.raises(ValueError):
        pipeline_apply(lambda h, w: h, ws2, x2, mesh=mesh,
                       num_microbatches=2)
