"""Public collective API (process-local group registry + module functions).

Parity: ``python/ray/util/collective/collective.py`` (GroupManager :40).
Each participating process calls ``init_collective_group`` (typically from
inside its actor/task), then the module-level ops.  ``create_collective_
group`` does the same from the driver for a set of actors, using the
generic ``_remote_call`` mechanism so user classes need no extra methods.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.util.collective.types import Backend, ReduceOp

logger = logging.getLogger(__name__)


class GroupManager:
    def __init__(self):
        self._groups: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def create(self, backend, world_size: int, rank: int, group_name: str):
        backend = Backend.parse(backend)
        with self._lock:
            if group_name in self._groups:
                raise RuntimeError(
                    f"collective group {group_name!r} already initialized"
                )
        if backend == Backend.TCP:
            from ray_tpu.util.collective.collective_group.tcp_group import (
                TcpGroup,
            )

            g = TcpGroup(world_size, rank, group_name)
        elif backend == Backend.XLA_MESH:
            # one PROCESS owning the whole device mesh: "ranks" are its
            # devices, so the declared (actor) world size must be 1 and
            # the group spans every visible device — a device-resident
            # value crossing this group's ops never host-stages
            import jax

            from ray_tpu.util.collective.collective_group.xla_group import (
                XlaMeshGroup,
            )

            if world_size != 1:
                raise ValueError(
                    "backend='xla_mesh' is the single-controller fast "
                    "path: exactly one participating process owns the "
                    f"mesh (got world_size={world_size}); use "
                    "backend='xla' for rank-per-process meshes")
            g = XlaMeshGroup(len(jax.devices()), 0, group_name)
        else:
            from ray_tpu.util.collective.collective_group.xla_group import (
                XlaDistributedGroup,
            )

            g = XlaDistributedGroup(world_size, rank, group_name)
        with self._lock:
            self._groups[group_name] = g
        return g

    def get(self, group_name: str):
        g = self._groups.get(group_name)
        if g is None:
            raise RuntimeError(
                f"collective group {group_name!r} is not initialized in "
                f"this process; call init_collective_group first"
            )
        return g

    def exists(self, group_name: str) -> bool:
        return group_name in self._groups

    def destroy(self, group_name: str):
        with self._lock:
            g = self._groups.pop(group_name, None)
        if g is not None:
            g.destroy_group()


_group_mgr = GroupManager()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "tcp",
    group_name: str = "default",
) -> None:
    """Initialize this process's membership in a collective group."""
    _group_mgr.create(backend, world_size, rank, group_name)


def create_collective_group(
    actors: List[Any],
    world_size: int,
    ranks: Optional[List[int]] = None,
    backend: str = "tcp",
    group_name: str = "default",
) -> None:
    """Driver-side setup: make ``actors`` a collective group.

    Dispatches ``init_collective_group`` into every actor (via the generic
    in-actor call, so user classes need no special methods) and blocks until
    all ranks have joined.
    """
    import ray_tpu

    if ranks is None:
        ranks = list(range(len(actors)))
    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError(
            f"{len(actors)} actors, {len(ranks)} ranks, world={world_size}"
        )

    def _join(_self, world_size, rank, backend, group_name):
        init_collective_group(world_size, rank, backend, group_name)
        return rank

    refs = [
        a._remote_call.remote(_join, world_size, r, backend, group_name)
        for a, r in zip(actors, ranks)
    ]
    ray_tpu.get(refs)


def is_group_initialized(group_name: str = "default") -> bool:
    return _group_mgr.exists(group_name)


def destroy_collective_group(group_name: str = "default") -> None:
    _group_mgr.destroy(group_name)


def get_rank(group_name: str = "default") -> int:
    return _group_mgr.get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group_mgr.get(group_name).world_size


def allreduce(tensor, group_name: str = "default", op=ReduceOp.SUM):
    return _group_mgr.get(group_name).allreduce(tensor, op)


def barrier(group_name: str = "default") -> None:
    _group_mgr.get(group_name).barrier()


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op=ReduceOp.SUM):
    return _group_mgr.get(group_name).reduce(tensor, dst_rank, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group_mgr.get(group_name).broadcast(tensor, src_rank)


def allgather(tensor, group_name: str = "default"):
    return _group_mgr.get(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default", op=ReduceOp.SUM):
    return _group_mgr.get(group_name).reducescatter(tensor, op)


def send(tensor, dst_rank: int, group_name: str = "default", tag: int = 0):
    return _group_mgr.get(group_name).send(tensor, dst_rank, tag)


def recv(shape=None, dtype=None, src_rank: int = 0,
         group_name: str = "default", tag: int = 0):
    return _group_mgr.get(group_name).recv(shape, dtype, src_rank, tag)
