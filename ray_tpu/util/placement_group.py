"""Placement groups: gang resource reservation across nodes.

Equivalent of the reference's ``python/ray/util/placement_group.py`` backed by
the GCS placement-group manager (``gcs_placement_group_mgr.h:232``) and raylet
bundle reservations (``placement_group_resource_manager.h``).  Strategies:
PACK / SPREAD / STRICT_PACK / STRICT_SPREAD.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self._bundles = bundles

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self._bundles)

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    def ready(self):
        """Returns an ObjectRef resolving when the PG is placed (reference
        ``PlacementGroup.ready``)."""
        import ray_tpu

        pg = self

        @ray_tpu.remote
        def _pg_ready_probe():
            return True

        from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

        return _pg_ready_probe.options(
            num_cpus=0,
            scheduling_strategy=PlacementGroupSchedulingStrategy(placement_group=pg),
        ).remote()

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        from ray_tpu._private.worker import get_global_worker

        worker = get_global_worker()
        reply = worker.run_coro(
            worker.gcs.call("wait_placement_group_ready", pg_id=self.id.binary(),
                            timeout=timeout_seconds),
            timeout=timeout_seconds + 10,
        )
        return reply.get("state") == "CREATED"

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"Invalid strategy {strategy!r}; valid: {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for b in bundles:
        if not b or all(v == 0 for v in b.values()):
            raise ValueError("bundles must request positive resources")
    from ray_tpu._private.worker import get_global_worker

    worker = get_global_worker()
    pg_id_bytes = worker.run_coro(
        worker.gcs.call("create_placement_group", bundles=bundles, strategy=strategy,
                        name=name)
    )
    return PlacementGroup(PlacementGroupID(pg_id_bytes), bundles)


def remove_placement_group(pg: PlacementGroup):
    from ray_tpu._private.worker import get_global_worker

    worker = get_global_worker()
    worker.run_coro(worker.gcs.call("remove_placement_group", pg_id=pg.id.binary()))


def placement_group_table(pg: Optional[PlacementGroup] = None):
    from ray_tpu._private.worker import get_global_worker

    worker = get_global_worker()
    if pg is not None:
        return worker.run_coro(worker.gcs.call("get_placement_group", pg_id=pg.id.binary()))
    return worker.run_coro(worker.gcs.call("list_placement_groups"))


def get_current_placement_group() -> Optional[PlacementGroup]:
    return None
