"""sharding-discipline: model code shards by LOGICAL axis names only.

The multichip bench spent five rounds paying silent full-layout round
trips ("involuntary full rematerialization") on its hottest gather
because an activation layout was pinned against the params' rule table
instead of THROUGH it — two halves of one program disagreeing about
where the model dim lives.  The repo-wide contract that prevents the
class: ``ray_tpu/models/`` never names a device mesh axis.  Layouts are
expressed as logical axis names ("batch", "embed", "heads", ...) and
resolved through the rule table (``DEFAULT_RULES`` /
``ShardedTrainer(rules=...)``) by the ``ray_tpu.parallel.sharding``
helpers — ``with_logical_constraint`` / ``with_named_sharding`` for
intermediates, ``logical_to_pspec`` / ``spec_tree_to_shardings`` for
specs — so switching parallelism strategy stays a rule-table change and
params + activations always move together.

Flagged inside ``ray_tpu/models/``:

- any call to ``with_sharding_constraint`` (bare or dotted): raw
  constraints bypass the rule table — use ``with_logical_constraint``;
- ``PartitionSpec(...)`` / ``P(...)`` literals naming an axis (any
  string argument, directly or inside a tuple/list): device-axis
  layouts hard-code one strategy.  ``P()`` / ``P(None)`` (explicit
  replication, no axis named) stay legal — replicated scaffolding like
  an optimizer's scalar-state sharding names no device axis.

``NamedSharding`` built from such a literal is caught via the literal
itself; ``NamedSharding(mesh, P())`` stays legal.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ray_tpu._private.analysis.core import (
    Checker,
    Finding,
    ParsedFile,
    dotted_name,
    register,
)

_SPEC_NAMES = ("PartitionSpec", "P")


def _names_an_axis(call: ast.Call) -> bool:
    """True when the P(...) literal names at least one axis (a string
    constant anywhere in its positional args)."""
    for a in call.args:
        for node in ast.walk(a):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                return True
    return False


@register
class ShardingDisciplineChecker(Checker):
    rule = "sharding-discipline"
    description = ("models/ must shard via logical-axis rules "
                   "(parallel.sharding helpers), never raw "
                   "with_sharding_constraint calls or device-axis "
                   "PartitionSpec literals")
    hint = ("express the layout as logical axis names and resolve it "
            "through the rule table: with_logical_constraint(x, mesh, "
            "\"batch\", \"seq\", rules=rules) for intermediates, "
            "logical_to_pspec / spec_tree_to_shardings for specs "
            "(ray_tpu/parallel/sharding.py)")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("ray_tpu/models/")

    def check(self, pf: ParsedFile) -> Iterable[Finding]:
        out: List[Finding] = []
        if pf.tree is None:
            return out
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            last = dotted_name(node.func).split(".")[-1]
            if last == "with_sharding_constraint":
                out.append(self.finding(
                    pf, node,
                    "raw with_sharding_constraint in model code bypasses "
                    "the logical-axis rule table — params and activations "
                    "can disagree about a dim's mesh axis, which XLA "
                    "patches with involuntary full rematerializations"))
            elif last in _SPEC_NAMES and _names_an_axis(node):
                out.append(self.finding(
                    pf, node,
                    "device-axis PartitionSpec literal in model code "
                    "hard-codes one parallelism strategy — derive the "
                    "spec from logical axis names via the rule table"))
        return out
