"""Slice-native gang scheduling: STRICT_PACK_SLICE topology packing,
the persisted gang state machine (atomic all-or-nothing reservation
with rollback), deterministic priority preemption over the drain
protocol, gang fate-sharing, placement-group lifetime scoping, and
`get_current_placement_group` / capture-child-tasks semantics.

Three layers, mirroring test_drain.py:

1. pure scheduler units (pack matrix, victim selection determinism);
2. in-process GCS + raylet servers on one event loop (real sockets) for
   the gang state machine, rollback faults, preemption claims, and
   fate-sharing — with a no-partial-gang audit after transitions;
3. cluster-level e2e: the two-tenant priority preemption scenario (a
   high-priority gang lands within the drain deadline while the
   low-priority training job checkpoint-restarts on a clamp_to-smaller
   mesh with zero failure-budget charge) and the ChaosTimeline
   ``preempt_slice`` fate-share path.
"""

import asyncio
import json
import os
import tempfile
import threading
import time

import pytest

from ray_tpu.util import fault_injection as fi


# ---------------------------------------------------------------------------
# 1. scheduler units
# ---------------------------------------------------------------------------


def _slice_node(nid, slice_name, idx, cpu=4.0, tpu=4.0, avail=None):
    from ray_tpu._private.scheduling import NodeView

    total = {"CPU": cpu, "TPU": tpu}
    return NodeView(nid, total, avail or dict(total),
                    {"tpu-slice-name": slice_name,
                     "tpu-worker-index": str(idx)})


def test_strict_pack_slice_matrix():
    from ray_tpu._private.scheduling import pack_bundles

    s1 = [_slice_node("a0", "s1", 0), _slice_node("a1", "s1", 1)]
    s2 = [_slice_node("b0", "s2", 0), _slice_node("b1", "s2", 1),
          _slice_node("b2", "s2", 2), _slice_node("b3", "s2", 3)]
    nodes = s1 + s2
    # fits: 2 bundles land on the SMALLEST slice that fits, in ICI
    # (worker-index) order
    p = pack_bundles(nodes, [{"TPU": 4}, {"TPU": 4}], "STRICT_PACK_SLICE")
    assert p == ["a0", "a1"], p
    # a bigger gang picks the bigger slice — never straddles two
    p = pack_bundles(nodes, [{"TPU": 4}] * 4, "STRICT_PACK_SLICE")
    assert p == ["b0", "b1", "b2", "b3"], p
    # split-slice rejection: a gang that fits NO single slice is
    # rejected outright, not spread across s1+s2
    p = pack_bundles(nodes, [{"TPU": 4}] * 5, "STRICT_PACK_SLICE")
    assert p is None
    # adjacency preference: nodes fill along the worker-index chain even
    # when the list order is scrambled
    from ray_tpu._private.scheduling import ici_order

    scrambled = [s2[2], s2[0], s2[3], s2[1]]
    assert [n.node_id for n in ici_order(scrambled)] == \
        ["b0", "b1", "b2", "b3"]
    p = pack_bundles(scrambled, [{"TPU": 4}] * 3, "STRICT_PACK_SLICE")
    assert p == ["b0", "b1", "b2"], p
    # draining-slice soft-avoid: s1 draining -> the gang goes to s2;
    # but a gang that fits ONLY the draining slice still places there
    p = pack_bundles(nodes, [{"TPU": 4}, {"TPU": 4}], "STRICT_PACK_SLICE",
                     exclude_node_ids={"a0", "a1"})
    assert p == ["b0", "b1"], p
    busy_s2 = s1 + [_slice_node(n.node_id, "s2", i, avail={"CPU": 4.0,
                                                          "TPU": 0.0})
                    for i, n in enumerate(s2)]
    p = pack_bundles(busy_s2, [{"TPU": 4}, {"TPU": 4}],
                     "STRICT_PACK_SLICE", exclude_node_ids={"a0", "a1"})
    assert p == ["a0", "a1"], p
    # slice-less fallback: no slice labels anywhere degenerates to
    # STRICT_PACK (every node its own one-host slice)
    from ray_tpu._private.scheduling import NodeView

    plain = [NodeView("n1", {"CPU": 4}, {"CPU": 4}),
             NodeView("n2", {"CPU": 4}, {"CPU": 4})]
    p = pack_bundles(plain, [{"CPU": 2}, {"CPU": 2}], "STRICT_PACK_SLICE")
    assert p is not None and len(set(p)) == 1


def test_select_victims_deterministic():
    from ray_tpu._private.gangs import select_victims

    views = [_slice_node("a0", "s1", 0, tpu=4.0,
                         avail={"CPU": 4.0, "TPU": 0.0}),
             _slice_node("a1", "s1", 1, tpu=4.0,
                         avail={"CPU": 4.0, "TPU": 0.0})]
    placed = [
        {"gang_id": b"g1", "priority": 1,
         "placement": ["a0"], "bundles": [{"TPU": 4}]},
        {"gang_id": b"g2", "priority": 1,
         "placement": ["a1"], "bundles": [{"TPU": 4}]},
        {"gang_id": b"g3", "priority": 3,
         "placement": [], "bundles": []},
    ]
    # a 1-bundle gang needs only ONE victim (fewest-gangs-disturbed):
    # both candidates tie on priority, the seeded tiebreak decides —
    # and the SAME spec + seed always picks the same victim
    picks = {tuple(select_victims([{"TPU": 4}], "PACK", 5, b"preemptor",
                                  views, placed, seed=0))
             for _ in range(5)}
    assert len(picks) == 1
    (pick,) = picks
    assert len(pick) == 1 and pick[0] in (b"g1", b"g2")
    # a 2-bundle gang disturbs both
    two = select_victims([{"TPU": 4}, {"TPU": 4}], "PACK", 5,
                         b"preemptor", views, placed, seed=0)
    assert sorted(two) == [b"g1", b"g2"]
    # only STRICTLY lower priorities are candidates
    assert select_victims([{"TPU": 4}], "PACK", 1, b"preemptor",
                          views, placed, seed=0) is None
    # a different seed may (and here does) flip the equal-priority tie
    flipped = {tuple(select_victims([{"TPU": 4}], "PACK", 5, b"preemptor",
                                    views, placed, seed=s))
               for s in range(8)}
    assert len(flipped) >= 2, "seed never affected the tiebreak"


def test_priority_option_validates_and_rides_spec():
    from ray_tpu._private.api_utils import validate_options

    validate_options({"priority": 3}, for_actor=False)
    validate_options({"priority": 3}, for_actor=True)
    with pytest.raises(ValueError):
        validate_options({"priorty": 3}, for_actor=False)


def test_pg_strategy_none_is_the_capture_opt_out():
    """PlacementGroupSchedulingStrategy(None) is the documented opt-out
    of gang capture-inheritance: it must normalize to DEFAULT, not
    crash."""
    from ray_tpu._private.api_utils import normalize_strategy
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy)

    s = normalize_strategy(PlacementGroupSchedulingStrategy(None))
    assert s.kind == "DEFAULT" and s.placement_group_id is None


def test_placement_group_lifetime_validation():
    from ray_tpu.util.placement_group import placement_group

    with pytest.raises(ValueError, match="lifetime"):
        placement_group([{"CPU": 1}], lifetime="bogus")
    with pytest.raises(ValueError, match="strategy"):
        placement_group([{"CPU": 1}], strategy="PACK_SLICE")


# ---------------------------------------------------------------------------
# 2. in-process GCS + raylets: the gang state machine
# ---------------------------------------------------------------------------


def _gang_env(test_body, raylet_specs, flags=None):
    """Run ``test_body(gcs, raylets)`` against in-process servers on one
    event loop (the test_drain.py topology), with labelled raylets."""
    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.raylet import Raylet

    config.reload(dict({"health_check_period_s": 1.0}, **(flags or {})))

    async def main():
        sd = tempfile.mkdtemp()
        os.makedirs(os.path.join(sd, "logs"), exist_ok=True)
        g = GcsServer(sd)
        await g.start()
        raylets = []
        for resources, labels in raylet_specs:
            r = Raylet(sd, g.addr, resources, labels=labels)
            await r.start()
            raylets.append(r)
        try:
            await test_body(g, raylets)
        finally:
            for r in raylets:
                try:
                    await r.stop()
                except Exception:  # noqa: BLE001
                    pass
            await g.stop()

    try:
        asyncio.run(main())
    finally:
        config.reload()


def _assert_no_partial_gang(g, raylets):
    """The audit contract: outside RESERVING, a gang's raylet-side
    reservations are either complete or empty — never partial."""
    for gang_id, gang in g.gangs.items():
        if gang.get("state") == "RESERVING":
            continue
        held = sum(len(r.bundles.get(gang_id, {})) for r in raylets)
        n = gang.get("bundle_count", 0)
        assert held in (0, n), (
            f"partial gang {gang_id.hex()[:8]}: state={gang.get('state')} "
            f"holds {held}/{n} bundles")


async def _wait_gang_state(g, gang_id, state, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if g.gangs.get(gang_id, {}).get("state") == state:
            return
        await asyncio.sleep(0.05)
    raise AssertionError(
        f"gang never reached {state}; at "
        f"{g.gangs.get(gang_id, {}).get('state')} "
        f"(history {g.gangs.get(gang_id, {}).get('history')})")


_SLICE_2X = [({"CPU": 2.0}, {"tpu-slice-name": "s1",
                             "tpu-worker-index": "0"}),
             ({"CPU": 2.0}, {"tpu-slice-name": "s1",
                             "tpu-worker-index": "1"})]


def test_gang_reserve_fault_rolls_back_all_siblings():
    """A bundle that fails to reserve releases EVERY sibling reservation
    in the same transition back to PENDING — then the retry loop places
    the gang once the fault clears."""
    async def body(g, raylets):
        # every attempt faults on bundle 2 until disarm, so rollback is
        # the steady state the test can observe without racing the
        # async retry loop
        fi.arm("gang.reserve", nth=2, count=1 << 30,
               exc=ConnectionError("mid-gang fault"))
        try:
            pg_id = await g.handle_create_placement_group(
                bundles=[{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
            # the armed fault fails bundle 2 -> rollback to PENDING,
            # audited via the persisted history note
            deadline = time.time() + 5
            while time.time() < deadline:
                notes = [h.get("note", "")
                         for h in g.gangs[pg_id]["history"]]
                # observe rollback COMPLETE: note recorded and the gang
                # back in PENDING (all sibling releases awaited before
                # that transition) — then audit synchronously, before
                # the retry loop can start another attempt
                if g.gangs[pg_id]["state"] == "PENDING" and \
                        any("fault" in n or "reserve" in n for n in notes):
                    _assert_no_partial_gang(g, raylets)
                    assert all(not r.bundles.get(pg_id)
                               for r in raylets), \
                        "rollback left a sibling reservation behind"
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError(
                    f"rollback never audited: "
                    f"{g.gangs[pg_id]['history']}")
        finally:
            fi.disarm("gang.reserve")
        # fault cleared: the pending retry loop reserves atomically
        await _wait_gang_state(g, pg_id, "PLACED")
        _assert_no_partial_gang(g, raylets)
        assert sum(len(r.bundles.get(pg_id, {})) for r in raylets) == 2
        states = [h["to"] for h in g.gangs[pg_id]["history"]]
        assert states[:3] == ["PENDING", "RESERVING", "PENDING"]
        assert states[-2:] == ["RESERVING", "PLACED"]

    _gang_env(body, _SLICE_2X)


def test_gang_fate_share_on_node_death_and_restartable_rereserve():
    """A node death inside a PLACED gang fails the WHOLE gang in one
    transition (surviving reservations released) and a restartable gang
    re-runs atomic reservation onto the surviving capacity."""
    async def body(g, raylets):
        r1, r2 = raylets
        # SPREAD (best-effort one-per-node): lands [r1, r2], and after
        # the death the re-reservation may double up on the survivor
        pg_id = await g.handle_create_placement_group(
            bundles=[{"CPU": 1}, {"CPU": 1}], strategy="SPREAD",
            restartable=True)
        await _wait_gang_state(g, pg_id, "PLACED")
        assert len(r1.bundles.get(pg_id, {})) == 1
        assert len(r2.bundles.get(pg_id, {})) == 1
        # the production wiring for an observed chip death (the
        # autoscaler's provider reconcile reports it): dead FINAL —
        # never heartbeat-resurrects, still-running raylet ordered down
        assert await g.handle_report_node_failure(
            r1.node_id, reason="chip failure")
        assert g.nodes[r1.node_id]["death_final"] is True
        # fate-share: FAILED in ONE transition, then restartable
        # re-admission; both bundles re-reserve on the survivor
        await _wait_gang_state(g, pg_id, "PLACED")
        _assert_no_partial_gang(g, [r for r in raylets if r is not r1])
        gang = g.gangs[pg_id]
        states = [h["to"] for h in gang["history"]]
        assert "FAILED" in states, states
        i = states.index("FAILED")
        # the failure transition is atomic: the very next states are the
        # re-admission, never a partial continuation of the old gang
        assert states[i:] == ["FAILED", "PENDING", "RESERVING", "PLACED"]
        assert gang["fate_shared"] is True
        assert "chip failure" in gang["failure"]
        assert g.pgs[pg_id]["placement"] == [r2.node_id, r2.node_id]
        # the GCS orders the dead-final node down on its next heartbeat;
        # its stopped raylet then holds no reservations
        deadline = time.time() + 10
        while time.time() < deadline and r1.bundles.get(pg_id):
            await asyncio.sleep(0.1)
        assert not r1.bundles.get(pg_id)

    _gang_env(body, _SLICE_2X)


def test_gang_fate_share_nonrestartable_fails_terminally():
    async def body(g, raylets):
        r1, r2 = raylets
        pg_id = await g.handle_create_placement_group(
            bundles=[{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
        await _wait_gang_state(g, pg_id, "PLACED")
        await g._mark_node_dead(r2.node_id, reason="preempted",
                                final=True)
        await _wait_gang_state(g, pg_id, "FAILED")
        # the dead node clears its local tables on the heartbeat-ordered
        # shutdown; survivors released synchronously in the fate-share
        deadline = time.time() + 10
        while time.time() < deadline and r2.bundles.get(pg_id):
            await asyncio.sleep(0.1)
        _assert_no_partial_gang(g, raylets)
        assert g.pgs[pg_id]["state"] == "FAILED"
        # waiters resolve instead of hanging
        reply = await g.handle_wait_placement_group_ready(pg_id, timeout=1)
        assert reply["state"] == "FAILED"

    _gang_env(body, _SLICE_2X)


def test_priority_preemption_claims_drain_and_admission():
    """The two-tenant scenario at the control-plane level: a priority-5
    gang evicts the priority-0 gang over the drain protocol, holds a
    claim (no later arrival can steal the capacity), and is admitted the
    moment the victim's reservations release — the preempt drain is then
    CANCELLED, not ridden to node death."""
    async def body(g, raylets):
        r1, r2 = raylets
        low = await g.handle_create_placement_group(
            bundles=[{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK_SLICE")
        await _wait_gang_state(g, low, "PLACED")
        assert sorted(set(g.pgs[low]["placement"])) == \
            sorted([r1.node_id, r2.node_id])

        high = await g.handle_create_placement_group(
            bundles=[{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK_SLICE",
            priority=5)
        # the victim enters PREEMPTING and its nodes drain
        await _wait_gang_state(g, low, "PREEMPTING")
        assert g.gangs[low]["preempted_by"] == high
        assert sorted(g.gangs[high]["claim_nodes"]) == \
            sorted([r1.node_id, r2.node_id])
        for nid in (r1.node_id, r2.node_id):
            assert g.nodes[nid]["state"] == "DRAINING"
        _assert_no_partial_gang(g, raylets)

        # no-livelock: a later same-shape arrival cannot take the
        # claimed capacity once it frees
        late = await g.handle_create_placement_group(
            bundles=[{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK_SLICE")

        # the victim vacates (its controller checkpoint-restarted
        # elsewhere): reservations release -> drain cancelled -> the
        # CLAIMANT is admitted
        await g.handle_remove_placement_group(low)
        await _wait_gang_state(g, high, "PLACED")
        _assert_no_partial_gang(g, raylets)
        assert g.gangs[high].get("claim_nodes") in (None, []), \
            "claim must clear at admission"
        for nid in (r1.node_id, r2.node_id):
            assert g.nodes[nid]["state"] == "ALIVE", "drain not cancelled"
            assert g.nodes[nid]["alive"]
        # the raylets adopted the cancellation too (push or heartbeat)
        deadline = time.time() + 5
        while time.time() < deadline and (r1.draining or r2.draining):
            await asyncio.sleep(0.1)
        assert not r1.draining and not r2.draining
        # the late arrival is still waiting — it never jumped the claim
        assert g.gangs[late]["state"] == "PENDING"
        history = [h["to"] for h in g.gangs[late]["history"]]
        assert "PLACED" not in history

    _gang_env(body, _SLICE_2X)


def test_preempt_drain_fault_leaves_retryable_claim():
    """An injected fault on the preempt-drain leg must not leave a
    half-drained victim set: the claim stands and the next scheduler
    pass retries the drain."""
    async def body(g, raylets):
        r1, r2 = raylets
        low = await g.handle_create_placement_group(
            bundles=[{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK_SLICE")
        await _wait_gang_state(g, low, "PLACED")
        fi.arm("gang.preempt.drain", nth=1, count=1,
               exc=ConnectionError("drain RPC lost"))
        try:
            high = await g.handle_create_placement_group(
                bundles=[{"CPU": 2}, {"CPU": 2}],
                strategy="STRICT_PACK_SLICE", priority=5)
            # first node's drain faulted; the retry pass covers it
            deadline = time.time() + 10
            while time.time() < deadline:
                if all(g.nodes[n]["state"] == "DRAINING"
                       for n in (r1.node_id, r2.node_id)):
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError(
                    f"drains never completed: "
                    f"{[g.nodes[n]['state'] for n in (r1.node_id, r2.node_id)]}")
            assert sorted(g.gangs[high]["claim_nodes"]) == \
                sorted([r1.node_id, r2.node_id])
        finally:
            fi.disarm("gang.preempt.drain")

    _gang_env(body, _SLICE_2X)


def test_remove_mid_reserving_is_not_resurrected():
    """A pg removed while its reservation pass is in flight must stay
    REMOVED: the resuming commit releases everything instead of
    resurrecting a zombie gang that permanently holds raylet capacity."""
    async def body(g, raylets):
        # the 2nd bundle's reserve HANGS 1s: a removal lands mid-pass
        fi.arm("gang.reserve", nth=2, count=1, exc="delay:1.0")
        try:
            pg_id = await g.handle_create_placement_group(
                bundles=[{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
            await _wait_gang_state(g, pg_id, "RESERVING", timeout=5.0)
            await g.handle_remove_placement_group(pg_id)
            assert g.gangs[pg_id]["state"] == "REMOVED"
            # the in-flight pass resumes: it must NOT flip the gang back
            # to PLACED or keep any reservation behind
            deadline = time.time() + 10
            while time.time() < deadline and any(
                    r.bundles.get(pg_id) for r in raylets):
                await asyncio.sleep(0.1)
            assert g.gangs[pg_id]["state"] == "REMOVED"
            assert g.pgs[pg_id]["state"] == "REMOVED"
            assert all(not r.bundles.get(pg_id) for r in raylets), \
                "zombie reservation survived removal"
        finally:
            fi.disarm("gang.reserve")

    _gang_env(body, _SLICE_2X)


def test_claim_released_when_claimed_nodes_die():
    """A victim that rides the preempt drain into its deadline takes the
    claimed nodes down with it; the claimant must release the dead claim
    (not pin itself to corpses) and place the moment capacity exists."""
    async def body(g, raylets):
        from ray_tpu._private.raylet import Raylet

        r1, r2 = raylets
        low = await g.handle_create_placement_group(
            bundles=[{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK_SLICE")
        await _wait_gang_state(g, low, "PLACED")
        high = await g.handle_create_placement_group(
            bundles=[{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK_SLICE",
            priority=5)
        await _wait_gang_state(g, low, "PREEMPTING")
        # the victim never vacates: the 1s drain deadline expires, the
        # nodes die, the victim fate-shares FAILED, and the claim now
        # points at corpses — the claimant must shed it
        deadline = time.time() + 20
        while time.time() < deadline:
            if not (g.gangs[high].get("claim_nodes") or []):
                break
            await asyncio.sleep(0.2)
        assert not (g.gangs[high].get("claim_nodes") or []), \
            "claim over dead nodes never released"
        assert g.gangs[high]["state"] == "PENDING"
        notes = [h.get("note", "") for h in g.gangs[high]["history"]]
        assert any("claim released" in n for n in notes), notes
        # fresh capacity arrives: the unwedged claimant places on it
        extra = []
        try:
            for w in ("0", "1"):
                r = Raylet(r1.session_dir, g.addr, {"CPU": 2.0},
                           labels={"tpu-slice-name": "s2",
                                   "tpu-worker-index": w})
                await r.start()
                extra.append(r)
            await _wait_gang_state(g, high, "PLACED", timeout=15.0)
            assert set(g.pgs[high]["placement"]) == \
                {r.node_id for r in extra}
        finally:
            for r in extra:
                try:
                    await r.stop()
                except Exception:  # noqa: BLE001
                    pass

    _gang_env(body, _SLICE_2X,
              flags={"gang_preempt_drain_deadline_s": 1.0})


def test_unpreempt_when_claimant_satisfied_elsewhere():
    """A claimant that places on capacity freed ELSEWHERE before its
    victims vacate must release the claim: victims revert to PLACED and
    their preempt drains are cancelled — nobody needs that eviction."""
    async def body(g, raylets):
        from ray_tpu._private.raylet import Raylet

        r1, r2 = raylets
        low = await g.handle_create_placement_group(
            bundles=[{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK_SLICE")
        await _wait_gang_state(g, low, "PLACED")
        high = await g.handle_create_placement_group(
            bundles=[{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK_SLICE",
            priority=5)
        await _wait_gang_state(g, low, "PREEMPTING")
        # a fresh slice joins before the victim vacates
        extra = []
        try:
            for w in ("0", "1"):
                r = Raylet(r1.session_dir, g.addr, {"CPU": 2.0},
                           labels={"tpu-slice-name": "s2",
                                   "tpu-worker-index": w})
                await r.start()
                extra.append(r)
            await _wait_gang_state(g, high, "PLACED")
            placed_on = set(g.pgs[high]["placement"])
            assert placed_on == {r.node_id for r in extra}, placed_on
            # the victim is un-preempted, its drains cancelled
            await _wait_gang_state(g, low, "PLACED")
            assert g.gangs[low].get("preempted_by") is None
            notes = [h.get("note", "") for h in g.gangs[low]["history"]]
            assert any("preemption released" in n for n in notes), notes
            deadline = time.time() + 10
            while time.time() < deadline and any(
                    g.nodes[n]["state"] == "DRAINING"
                    for n in (r1.node_id, r2.node_id)):
                await asyncio.sleep(0.1)
            for nid in (r1.node_id, r2.node_id):
                assert g.nodes[nid]["state"] == "ALIVE"
            _assert_no_partial_gang(g, raylets + extra)
        finally:
            for r in extra:
                try:
                    await r.stop()
                except Exception:  # noqa: BLE001
                    pass

    _gang_env(body, _SLICE_2X)


def test_unpreempt_when_claimant_removed():
    """Removing a claimant gang mid-preemption releases its claim: the
    victim reverts to PLACED and keeps its capacity."""
    async def body(g, raylets):
        r1, r2 = raylets
        low = await g.handle_create_placement_group(
            bundles=[{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK_SLICE")
        await _wait_gang_state(g, low, "PLACED")
        high = await g.handle_create_placement_group(
            bundles=[{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK_SLICE",
            priority=5)
        await _wait_gang_state(g, low, "PREEMPTING")
        await g.handle_remove_placement_group(high)
        await _wait_gang_state(g, low, "PLACED")
        assert g.gangs[low].get("preempted_by") is None
        assert g.gangs[high]["state"] == "REMOVED"
        deadline = time.time() + 10
        while time.time() < deadline and any(
                g.nodes[n]["state"] == "DRAINING"
                for n in (r1.node_id, r2.node_id)):
            await asyncio.sleep(0.1)
        for nid in (r1.node_id, r2.node_id):
            assert g.nodes[nid]["state"] == "ALIVE"
        # the victim still holds its full reservation
        _assert_no_partial_gang(g, raylets)
        assert sum(len(r.bundles.get(low, {})) for r in raylets) == 2

    _gang_env(body, _SLICE_2X)


def test_pg_lifetime_scoping_and_detached_survival():
    """Non-detached placement groups are reclaimed when their job
    finishes; lifetime="detached" groups survive until explicit
    removal."""
    async def body(g, raylets):
        scoped = await g.handle_create_placement_group(
            bundles=[{"CPU": 1}], strategy="PACK", job_id=7)
        detached = await g.handle_create_placement_group(
            bundles=[{"CPU": 1}], strategy="PACK", job_id=7,
            lifetime="detached")
        other = await g.handle_create_placement_group(
            bundles=[{"CPU": 1}], strategy="PACK", job_id=8)
        for pg in (scoped, detached, other):
            await _wait_gang_state(g, pg, "PLACED")
        await g.handle_mark_job_finished(7)
        assert g.pgs[scoped]["state"] == "REMOVED"
        assert g.gangs[scoped]["state"] == "REMOVED"
        assert g.pgs[detached]["state"] == "CREATED", \
            "detached group must survive its driver's job"
        assert g.pgs[other]["state"] == "CREATED"
        _assert_no_partial_gang(g, raylets)

    _gang_env(body, _SLICE_2X)


def test_gcs_restart_mid_reserving_rolls_back(tmp_path):
    """A GCS that persisted a gang in RESERVING and crashed restores it
    as PENDING (reservation outcome unknown -> rollback), never as a
    gang claiming partial capacity."""
    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer

    config.reload({"gcs_storage": "file",
                   "gcs_storage_path": str(tmp_path / "gcs.pkl")})

    async def phase1():
        sd = tempfile.mkdtemp()
        os.makedirs(os.path.join(sd, "logs"), exist_ok=True)
        g = GcsServer(sd)
        await g.start()
        # no raylets: the gang parks in PENDING; force RESERVING as the
        # crash snapshot state through the one legal write path
        pg_id = await g.handle_create_placement_group(
            bundles=[{"CPU": 1}], strategy="PACK")
        g._gang_transition(pg_id, "RESERVING",
                           planned_placement=["gone-node"])
        g._write_snapshot()
        await g.stop()
        return sd, pg_id

    async def phase2(sd, pg_id):
        g = GcsServer(sd)
        assert g.gangs[pg_id]["state"] == "PENDING"
        notes = [h.get("note", "") for h in g.gangs[pg_id]["history"]]
        assert any("rolled back" in n for n in notes), notes
        await g.stop()

    try:
        sd, pg_id = asyncio.run(phase1())
        asyncio.run(phase2(sd, pg_id))
    finally:
        config.reload()


def test_slice_topology_table_and_list_gangs():
    async def body(g, raylets):
        pg_id = await g.handle_create_placement_group(
            bundles=[{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK_SLICE",
            name="gang-a", priority=2)
        await _wait_gang_state(g, pg_id, "PLACED")
        gangs = await g.handle_list_gangs()
        (row,) = [r for r in gangs if r["gang_id"] == pg_id]
        assert row["state"] == "PLACED" and row["priority"] == 2
        assert row["name"] == "gang-a"
        assert len(row["placement"]) == 2
        assert [h["to"] for h in row["history"]][-1] == "PLACED"
        topo = await g.handle_get_slice_topology()
        (s1,) = [s for s in topo if s["slice"] == "s1"]
        assert [h["worker_index"] for h in s1["hosts"]] == ["0", "1"]
        placed_on = [h for h in s1["hosts"] if h["gangs"]]
        assert placed_on, "slice table must show the placed gang"

    _gang_env(body, _SLICE_2X)


# ---------------------------------------------------------------------------
# 3. cluster-level e2e
# ---------------------------------------------------------------------------


def test_get_current_placement_group_and_capture(ray_start):
    """get_current_placement_group resolves from the runtime context and
    capture_child_tasks routes nested submissions into the same gang."""
    import ray_tpu
    from ray_tpu.util.placement_group import (
        get_current_placement_group, placement_group,
        remove_placement_group)
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy)

    pg = placement_group([{"CPU": 1}])
    assert pg.wait(30)
    try:
        assert get_current_placement_group() is None  # driver scope

        @ray_tpu.remote
        def inner():
            from ray_tpu.util.placement_group import (
                get_current_placement_group as gcp)

            cur = gcp()
            return cur.id.hex() if cur is not None else None

        @ray_tpu.remote
        def outer(capture):
            import ray_tpu as rt
            from ray_tpu.util.placement_group import (
                get_current_placement_group as gcp)

            cur = gcp()
            child = rt.get(inner.options(num_cpus=0).remote(), timeout=30)
            return (cur.id.hex() if cur is not None else None,
                    cur.bundle_count if cur is not None else 0, child)

        got = ray_tpu.get(
            outer.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
                pg, placement_group_capture_child_tasks=True),
            ).remote(True), timeout=60)
        assert got[0] == pg.id.hex()
        assert got[1] == 1
        assert got[2] == pg.id.hex(), \
            "capture_child_tasks must land the nested task in the gang"

        got = ray_tpu.get(
            outer.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(pg),
            ).remote(False), timeout=60)
        assert got[0] == pg.id.hex()
        assert got[2] is None, \
            "without capture the nested task must NOT inherit the gang"
    finally:
        remove_placement_group(pg)


def test_chaos_preempt_slice_fate_shares(no_cluster, monkeypatch):
    """The ChaosTimeline ``preempt_slice`` action kills a whole slice;
    the PLACED restartable gang there fate-shares (FAILED in one
    transition) and re-reserves atomically on the surviving slice —
    audited via the gang history."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.chaos import ChaosTimeline
    from ray_tpu.util.placement_group import placement_group
    from ray_tpu.util.state import list_gangs

    monkeypatch.setenv("RAY_TPU_HEALTH_CHECK_PERIOD_S", "1.0")
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        cluster.connect()
        for s, w in (("s1", 0), ("s1", 1), ("s2", 0), ("s2", 1)):
            cluster.add_node(num_cpus=2,
                             labels={"tpu-slice-name": s,
                                     "tpu-worker-index": str(w)})
        cluster.wait_for_nodes()
        pg = placement_group([{"CPU": 1}, {"CPU": 1}],
                             strategy="STRICT_PACK_SLICE",
                             restartable=True)
        assert pg.wait(30)
        # equal slice sizes: the name tiebreak places on s1
        tl = ChaosTimeline([{"at": 0.1, "kind": "preempt_slice",
                             "slice": "s1"}], seed=3)
        # determinism gate: same spec + seed -> identical plan
        assert tl.plan() == ChaosTimeline(
            [{"at": 0.1, "kind": "preempt_slice", "slice": "s1"}],
            seed=3).plan()
        tl.start()
        tl.join(timeout=30)
        (fired,) = tl.executed()
        assert fired["ok"], fired
        assert fired["result"]["slice"] == "s1"
        assert len(fired["result"]["preempted"]) == 2

        # the gang fate-shares and re-reserves on s2
        deadline = time.time() + 45
        row = None
        while time.time() < deadline:
            rows = [r for r in list_gangs()
                    if r["gang_id"] == pg.id.hex()]
            row = rows[0] if rows else None
            if row and row["state"] == "PLACED" and \
                    row.get("fate_shared"):
                break
            time.sleep(0.5)
        assert row is not None, "gang vanished"
        states = [h["to"] for h in row["history"]]
        assert "FAILED" in states, (row["state"], states)
        i = states.index("FAILED")
        assert states[i:] == ["FAILED", "PENDING", "RESERVING", "PLACED"], \
            states
        assert row["fate_shared"] is True
        assert row["state"] == "PLACED", states
        # the re-reservation landed on the surviving slice, whole-gang
        placement = row["placement"]
        assert placement is not None and len(placement) == 2
        dead = set(fired["result"]["preempted"])
        assert not (set(placement) & dead), (placement, dead)
    finally:
        cluster.shutdown()


def test_two_tenant_priority_preemption_e2e(no_cluster, tmp_path,
                                            monkeypatch):
    """THE acceptance scenario: a low-priority training gang occupies
    the slice; a high-priority gang arrives and lands within the drain
    deadline while the low-priority job checkpoint-restarts on a
    clamp_to-smaller worker group with ZERO failure-budget charge
    (max_failures=0 — any charged failure would fail the run)."""
    import ray_tpu
    from ray_tpu import train
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train.policies import ElasticScalingPolicy
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    from ray_tpu.util.state import list_gangs

    monkeypatch.setenv("RAY_TPU_HEALTH_CHECK_PERIOD_S", "1.0")
    monkeypatch.setenv("RAY_TPU_GANG_PREEMPT_DRAIN_DEADLINE_S", "12.0")
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        cluster.connect()
        # slice s1: two hosts (the contended slice); slice s2: one host
        # (where the preempted run re-meshes smaller)
        for i in range(2):
            cluster.add_node(num_cpus=2, resources={"trainer_slot": 1},
                             labels={"tpu-slice-name": "s1",
                                     "tpu-worker-index": str(i)})
        cluster.add_node(num_cpus=2, resources={"trainer_slot": 1},
                         labels={"tpu-slice-name": "s2",
                                 "tpu-worker-index": "0"})
        cluster.wait_for_nodes()
        side = str(tmp_path / "side")
        os.makedirs(side, exist_ok=True)

        def loop(config):
            import json as _json
            import os as _os
            import tempfile as _tempfile
            import time as _t

            from ray_tpu import train as _train

            ctx = _train.get_context()
            rank = ctx.get_world_rank()
            start = 0
            ck = ctx.get_checkpoint()
            if ck is not None:
                with open(_os.path.join(ck.path, "state.json")) as f:
                    start = _json.load(f)["step"] + 1
            for step in range(start, config["steps"]):
                with open(_os.path.join(
                        config["side_dir"],
                        f"r{rank}-step{step}-{_t.time_ns()}"), "w") as f:
                    _json.dump({"step": step, "rank": rank,
                                "world": ctx.get_world_size()}, f)
                _t.sleep(config["step_s"])
                d = _tempfile.mkdtemp()
                with open(_os.path.join(d, "state.json"), "w") as f:
                    _json.dump({"step": step}, f)
                _train.report({"step": step,
                               "world": ctx.get_world_size()},
                              checkpoint=_train.Checkpoint(d))

        # low-priority tenant: gang-scheduled onto slice s1
        # (STRICT_PACK_SLICE via topology=), elastic 1..2 workers,
        # ZERO failure budget — the preemption must ride the no-charge
        # drain path or this run fails
        trainer = train.DataParallelTrainer(
            loop,
            train_loop_config={"side_dir": side, "steps": 8,
                               "step_s": 0.5},
            scaling_config=train.ScalingConfig(
                num_workers=2, topology="v5e-8",
                resources_per_worker={"CPU": 1, "trainer_slot": 1}),
            run_config=train.RunConfig(
                name="low-pri", storage_path=str(tmp_path),
                failure_config=train.FailureConfig(max_failures=0)),
            scaling_policy=ElasticScalingPolicy(
                min_workers=1, max_workers=2, settle_s=1.0,
                resources_per_worker={"CPU": 1, "trainer_slot": 1}),
        )
        result_box = {}

        def run_trainer():
            try:
                result_box["result"] = trainer.fit()
            except BaseException as e:  # noqa: BLE001
                import traceback
                traceback.print_exc()
                result_box["error"] = e

        t = threading.Thread(target=run_trainer, daemon=True)
        t.start()

        # wait until the 2-worker gang is running on s1 (step evidence)
        deadline = time.time() + 90
        while time.time() < deadline:
            if any(n.startswith("r1-step1-") for n in os.listdir(side)):
                break
            time.sleep(0.2)
        else:
            raise AssertionError("low-pri run never reached step 1 at "
                                 "world 2")

        # high-priority tenant arrives: needs the whole contended slice
        t0 = time.time()
        pg = placement_group(
            [{"CPU": 1, "trainer_slot": 1}] * 2,
            strategy="STRICT_PACK_SLICE", priority=5, name="high-pri")
        assert pg.wait(timeout_seconds=30), \
            "high-priority gang did not land within the drain window"
        landed_after = time.time() - t0
        assert landed_after < 25.0, landed_after

        # the low-priority run finishes from its pre-drain checkpoint
        # with no failure-budget charge (max_failures=0) and no step gap
        t.join(timeout=120)
        assert not t.is_alive(), "trainer wedged after preemption"
        assert "error" not in result_box, result_box.get("error")
        result = result_box["result"]
        assert result.error is None, result.error
        steps = [m["step"] for m in result.metrics_history]
        assert steps[-1] == 7, steps
        for a, b in zip(steps, steps[1:]):
            assert b == a + 1 or b <= a, f"step gap: {steps}"
        # it re-meshed SMALLER (clamp_to path): post-preemption evidence
        # at world 1
        worlds = set()
        for name in os.listdir(side):
            with open(os.path.join(side, name)) as f:
                worlds.add(json.load(f)["world"])
        assert worlds == {2, 1}, worlds

        # audit the gang table: high-pri PLACED on the contended slice,
        # the victim generation preempted, nothing partial
        rows = list_gangs()
        (high,) = [r for r in rows if r["gang_id"] == pg.id.hex()]
        assert high["state"] == "PLACED"
        assert len(high["placement"]) == 2
        assert any(r.get("preempted_by") == pg.id.hex() for r in rows), \
            [(r["name"], r["state"]) for r in rows]
        for r in rows:
            if r["state"] == "PLACED":
                assert len(r["placement"]) == r["bundle_count"]
            elif r["state"] in ("FAILED", "REMOVED", "PENDING"):
                assert not r["placement"], r
        remove_placement_group(pg)
    finally:
        cluster.shutdown()
