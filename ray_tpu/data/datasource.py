"""Datasources: pluggable readers producing ReadTasks, and file datasinks.

Reference: ``python/ray/data/datasource/datasource.py`` (``Datasource``,
``ReadTask``) and the per-format datasources under
``python/ray/data/_internal/datasource/``.  A ``ReadTask`` is a serializable
zero-arg callable returning an iterator of output blocks, plus metadata
estimated *before* execution so the optimizer can plan parallelism.
"""

from __future__ import annotations

import glob as globlib
import json
import os
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import BlockMetadata, batch_to_block, rows_to_block


class ReadTask:
    def __init__(self, read_fn: Callable[[], Iterator[pa.Table]],
                 metadata: BlockMetadata):
        self._read_fn = read_fn
        self.metadata = metadata

    def __call__(self) -> Iterator[pa.Table]:
        return self._read_fn()


class Datasource:
    """ABC: estimate size, then produce up to ``parallelism`` ReadTasks."""

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Datasource", "")


class RangeDatasource(Datasource):
    def __init__(self, n: int, block_format: str = "int"):
        self._n = n
        self._format = block_format

    def estimate_inmemory_data_size(self) -> int:
        return self._n * 8

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self._n or 1))
        tasks = []
        per = -(-self._n // parallelism) if self._n else 0
        for i in range(parallelism):
            start, end = i * per, min((i + 1) * per, self._n)
            if start >= end and self._n > 0:
                break

            def make(start=start, end=end):
                def read() -> Iterator[pa.Table]:
                    yield pa.table({"id": np.arange(start, end, dtype=np.int64)})

                return read

            tasks.append(ReadTask(make(), BlockMetadata(
                num_rows=end - start, size_bytes=(end - start) * 8,
                schema=pa.schema([("id", pa.int64())]))))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self._items = list(items)

    def estimate_inmemory_data_size(self) -> int:
        return len(self._items) * 64

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self._items)
        parallelism = max(1, min(parallelism, n or 1))
        per = -(-n // parallelism) if n else 0
        tasks = []
        for i in range(parallelism):
            chunk = self._items[i * per:(i + 1) * per]
            if not chunk and n > 0:
                break

            def make(chunk=chunk):
                def read() -> Iterator[pa.Table]:
                    yield rows_to_block(chunk)

                return read

            tasks.append(ReadTask(make(), BlockMetadata(
                num_rows=len(chunk), size_bytes=len(chunk) * 64)))
        return tasks


class BlocksDatasource(Datasource):
    """In-memory tables (from_pandas / from_arrow / from_numpy)."""

    def __init__(self, blocks: List[pa.Table]):
        self._blocks = blocks

    def estimate_inmemory_data_size(self) -> int:
        return sum(b.nbytes for b in self._blocks)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for b in self._blocks:
            def make(b=b):
                def read() -> Iterator[pa.Table]:
                    yield b

                return read

            tasks.append(ReadTask(make(), BlockMetadata.for_block(b)))
        return tasks


def _expand_paths(paths, suffix: Optional[str]) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, "**", f"*{suffix}" if suffix else "*")
            out.extend(sorted(f for f in globlib.glob(pat, recursive=True)
                              if os.path.isfile(f)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"No input files found for {paths!r}")
    return out


class FileBasedDatasource(Datasource):
    """One ReadTask per group of files, grouped to meet the parallelism."""

    _suffix: Optional[str] = None

    def __init__(self, paths):
        self._paths = _expand_paths(paths, self._suffix)

    def estimate_inmemory_data_size(self) -> int:
        return sum(os.path.getsize(p) for p in self._paths)

    def _read_file(self, path: str) -> Iterator[pa.Table]:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        groups: List[List[str]] = [[] for _ in range(min(parallelism, len(self._paths)))]
        for i, p in enumerate(self._paths):
            groups[i % len(groups)].append(p)
        tasks = []
        for group in groups:
            def make(group=group, self=self):
                def read() -> Iterator[pa.Table]:
                    for path in group:
                        yield from self._read_file(path)

                return read

            tasks.append(ReadTask(make(), BlockMetadata(
                num_rows=0, size_bytes=sum(os.path.getsize(p) for p in group),
                input_files=group)))
        return tasks


class ParquetDatasource(FileBasedDatasource):
    _suffix = ".parquet"

    def __init__(self, paths, columns: Optional[List[str]] = None):
        super().__init__(paths)
        self._columns = columns

    def _read_file(self, path: str) -> Iterator[pa.Table]:
        import pyarrow.parquet as pq

        yield pq.read_table(path, columns=self._columns)


class CSVDatasource(FileBasedDatasource):
    _suffix = ".csv"

    def _read_file(self, path: str) -> Iterator[pa.Table]:
        import pyarrow.csv as pacsv

        yield pacsv.read_csv(path)


class JSONDatasource(FileBasedDatasource):
    """JSONL (one object per line) or a single top-level JSON array."""

    _suffix = ".json"

    def _read_file(self, path: str) -> Iterator[pa.Table]:
        with open(path, "r") as f:
            head = f.read(1)
            f.seek(0)
            if head == "[":
                rows = json.load(f)
            else:
                rows = [json.loads(line) for line in f if line.strip()]
        yield rows_to_block(rows)


class TextDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterator[pa.Table]:
        with open(path, "r") as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield pa.table({"text": lines})


class BinaryDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterator[pa.Table]:
        with open(path, "rb") as f:
            data = f.read()
        yield pa.table({"bytes": pa.array([data], type=pa.binary()),
                        "path": [path]})


class NumpyDatasource(FileBasedDatasource):
    _suffix = ".npy"

    def _read_file(self, path: str) -> Iterator[pa.Table]:
        arr = np.load(path)
        yield batch_to_block({"data": arr})


# ---------------------------------------------------------------------------
# Datasinks (write path): one file per block, task-parallel.
# Reference: ``python/ray/data/datasource/datasink.py`` + write_* in dataset.py
# ---------------------------------------------------------------------------

def write_block_file(block: pa.Table, path: str, file_format: str):
    if file_format == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(block, path)
    elif file_format == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(block, path)
    elif file_format == "json":
        with open(path, "w") as f:
            for row in block.to_pylist():
                f.write(json.dumps(_json_safe(row)) + "\n")
    else:
        raise ValueError(f"Unknown file format {file_format!r}")


def _json_safe(row: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in row.items():
        if isinstance(v, np.generic):
            v = v.item()
        elif isinstance(v, np.ndarray):
            v = v.tolist()
        elif isinstance(v, bytes):
            v = v.decode("utf-8", "replace")
        out[k] = v
    return out
