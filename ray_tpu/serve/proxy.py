"""HTTP proxy actor: routes requests to deployments.

Reference: ``python/ray/serve/_private/proxy.py`` (``ProxyActor :1137``,
HTTP handler :750) — an aiohttp server per node; the route table comes from
the controller (long-poll analog: refreshed on miss and periodically).

Request contract: ``GET/POST {route_prefix}[/suffix]`` → deployment's
``__call__`` receives the JSON body (POST) or query-param dict (GET);
the JSON-serialized return value is the response body.

Overload protection: every route mints a :class:`RequestContext` (the
``serve.proxy.admit`` fault site rides that edge) whose deadline comes
from the client's ``X-Request-Timeout-S`` header capped by the proxy's
``request_timeout_s``; the budget travels with the request through the
router and replica.  A shed (``BackPressureError``) maps to **503 +
``Retry-After``**, a spent budget to **504**; and a client that
disconnects mid-request gets its in-flight replica task
``ray_tpu.cancel``-ed instead of running to completion for nobody.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.context import new_request_context, scope
from ray_tpu.util.fault_injection import fault_point


def _unwrap_cause(e: BaseException) -> BaseException:
    """Peel TaskError wrappers (a replica- or composition-raised overload
    verdict arrives wrapped with the remote traceback)."""
    from ray_tpu.exceptions import TaskError

    depth = 0
    while isinstance(e, TaskError) and e.cause is not None and depth < 8:
        e = e.cause
        depth += 1
    return e


def classify_request_error(e: BaseException) -> str:
    """Map a serving-path exception to a degradation kind:
    ``"shed"`` (admission rejected — retryable by the CLIENT later),
    ``"expired"`` (deadline spent), ``"cancelled"``, or ``"error"``."""
    from ray_tpu.exceptions import (
        BackPressureError,
        DeadlineExceededError,
        GetTimeoutError,
        TaskCancelledError,
        TaskError,
    )

    cause = _unwrap_cause(e)
    if isinstance(cause, BackPressureError):
        return "shed"
    if isinstance(cause, (DeadlineExceededError, GetTimeoutError)):
        return "expired"
    if isinstance(cause, TaskCancelledError):
        return "cancelled"
    if isinstance(e, TaskError):
        # unpicklable cause: fall back to the repr the wrapper carries
        if "BackPressureError" in e.cause_repr:
            return "shed"
        if "DeadlineExceededError" in e.cause_repr:
            return "expired"
        if "TaskCancelledError" in e.cause_repr:
            return "cancelled"
    return "error"


def replica_counted_expiry(e: BaseException) -> bool:
    """True when an expiry verdict was raised replica-side (a drop in
    ``ReplicaActor._admit``) and arrived TaskError-wrapped: the replica
    process already bumped the ``serve_requests_expired`` registry
    counter, so a proxy must count it toward the controller aggregate
    only (``metric=False``) to keep /metrics 1:1 with actual drops.
    Shared by the HTTP and gRPC proxies — the accounting rule must not
    diverge between them."""
    from ray_tpu.exceptions import DeadlineExceededError, TaskError

    cause = _unwrap_cause(e)
    if cause is not e and isinstance(cause, DeadlineExceededError):
        return True
    return isinstance(e, TaskError) and "DeadlineExceededError" in e.cause_repr


class AbandonTracker:
    """Cancellation rendezvous between a route handler and its executor
    dispatch (shared by the HTTP and gRPC proxies).

    The dispatch may be blocked in the router's admission queue when the
    client walks away — a poll-for-N-seconds watcher would give up and
    let the work run to completion once a slot finally freed.  Instead,
    whichever of ``bind()`` (dispatch bound a response) / ``abandon()``
    (client disconnected) happens SECOND performs the cancel, so the
    abandon always reaches the in-flight task no matter how long
    admission took."""

    def __init__(self, note_cancelled, cancel_fn=None):
        self._lock = threading.Lock()
        self._note = note_cancelled
        self._cancel_fn = cancel_fn  # e.g. close a streaming generator
        self._resp = None
        self._abandoned = False
        self._cancelled = False

    @property
    def resp(self):
        return self._resp

    def bind(self, resp) -> None:
        with self._lock:
            self._resp = resp
            do = self._abandoned and not self._cancelled
            if do:
                self._cancelled = True
        if do:
            self._cancel()

    def abandon(self) -> None:
        with self._lock:
            self._abandoned = True
            do = self._resp is not None and not self._cancelled
            if do:
                self._cancelled = True
        if do:
            self._cancel()

    def abandon_async(self) -> None:
        """Abandon from an event-loop thread: the cancel is a blocking
        control-plane RPC, so hand it to a short-lived daemon thread."""
        threading.Thread(target=self.abandon, daemon=True,
                         name="serve-proxy-cancel").start()

    def _cancel(self) -> None:
        try:
            if self._cancel_fn is not None:
                self._cancel_fn(self._resp)
            else:
                ray_tpu.cancel(self._resp.ref)
        except Exception:  # noqa: BLE001 — already finished
            pass
        try:
            self._note()
        except Exception:  # noqa: BLE001 — visibility never masks teardown
            pass


class _PoolLease:
    """One admitted request's claim on a dispatch-pool thread (shared by
    the HTTP and gRPC proxies).

    ``_active`` must track pool OCCUPANCY, not handler liveness: when a
    client disconnects while its dispatch is still blocked on a pool
    thread (e.g. waiting in the router admission queue, or in a result
    wait), the decrement is deferred to the moment that thread actually
    returns.  Releasing eagerly on disconnect would let new arrivals
    pass the ``max_concurrent`` check and park in the executor's
    unbounded internal work queue — uncounted, deadline-unchecked, and
    invisible to the admission bounds."""

    def __init__(self, release, loop):
        self._release = release  # runs on the event loop, exactly once
        self._loop = loop
        self._done = False
        self._deferred = False

    def _fire(self):
        # event-loop-confined, like the counter it decrements
        if not self._done:
            self._done = True
            self._release()

    def defer_to(self, cf) -> None:
        """Hand the release to the executor future still pinning the
        thread (event-loop context; the callback may fire on the pool
        thread, so it trampolines back through the loop)."""
        self._deferred = True
        cf.add_done_callback(
            lambda _f: self._loop.call_soon_threadsafe(self._fire))

    def settle(self) -> None:
        """Release now unless a ``defer_to`` owns it (event-loop
        context; the handler's ``finally``)."""
        if not self._deferred:
            self._fire()


@ray_tpu.remote
class ProxyActor:
    def __init__(self, host: str, port: int,
                 request_timeout_s: float = 120.0,
                 max_concurrent_requests: int = 256):
        import concurrent.futures

        self._host = host
        self._port = port
        # reference: serve HTTPOptions.request_timeout_s — a big model's
        # FIRST request includes jit compilation and can far exceed a
        # one-size-fits-all minute
        self._request_timeout_s = request_timeout_s
        # Every in-flight request pins one dispatch-pool thread (that
        # blocking wait IS its router admission-queue entry), so the pool
        # is sized to the cap and arrivals beyond it shed with 503 at the
        # event loop — an undersized shared executor would instead park
        # them in its unbounded internal work queue: uncounted,
        # deadline-unchecked, and invisible to the admission bounds.
        self._max_concurrent = max_concurrent_requests
        self._active = 0  # event-loop-confined: handler increments/decrements
        self._dispatch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_concurrent_requests,
            thread_name_prefix="serve-proxy-dispatch")
        self._routes: Dict[str, str] = {}
        self._routes_at = 0.0
        self._handles: Dict[str, Any] = {}
        self._ready = threading.Event()
        self._error: Optional[str] = None
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="serve-proxy")
        self._thread.start()
        if not self._ready.wait(timeout=30):
            raise RuntimeError(f"proxy failed to bind: {self._error}")

    def ready(self) -> int:
        return self._port

    def _refresh_routes(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._routes_at < 2.0:
            return
        from ray_tpu.serve.controller import get_controller

        # bounded + degrade-to-stale: a hung controller must cost at most
        # one short stall per refresh window, not wedge route resolution
        # (and the executor thread running it) forever
        try:
            self._routes = ray_tpu.get(
                get_controller().get_routes.remote(), timeout=5)
        except Exception:  # noqa: BLE001 — keep serving the stale table
            pass
        self._routes_at = now

    def _resolve(self, path: str) -> Optional[str]:
        self._refresh_routes()
        # longest matching prefix wins
        best = None
        for prefix, dep in self._routes.items():
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") \
                    or (prefix == "/" and path.startswith("/")):
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, dep)
        if best is None:
            self._refresh_routes(force=True)
            for prefix, dep in self._routes.items():
                if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                    if best is None or len(prefix) > len(best[0]):
                        best = (prefix, dep)
        return best[1] if best else None

    def _handle_for(self, deployment: str, method: str = "__call__"):
        # cached per (deployment, method): a fresh DeploymentHandle per
        # request would rebuild its Router (controller round trip) and
        # lose the pow-2 scheduler's cross-request queue-length cache
        key = (deployment, method)
        h = self._handles.get(key)
        if h is None:
            from ray_tpu.serve.router import DeploymentHandle

            h = DeploymentHandle(deployment, method)
            self._handles[key] = h
        return h

    def _mint_context(self, request):
        """One RequestContext per route invocation (enforced by the
        ``test_every_proxy_route_mints_request_context`` tooling guard):
        the client may SHORTEN the budget via ``X-Request-Timeout-S``,
        never extend past the proxy's ``request_timeout_s`` cap."""
        fault_point("serve.proxy.admit")
        timeout_s = self._request_timeout_s
        hdr = request.headers.get("X-Request-Timeout-S", "")
        if hdr:
            try:
                timeout_s = max(0.0, min(float(hdr), timeout_s))
            except ValueError:
                pass
        return new_request_context(
            timeout_s=timeout_s,
            request_id=request.headers.get("X-Request-Id") or None)

    def _note_degradation(self, deployment: str, kind: str,
                          metric: bool = True):
        """Attribute a shed/expiry/cancel observed at the proxy to the
        deployment's overload stats (the router owns the counters so
        driver handles and proxies aggregate in one place).
        ``metric=False`` counts toward the controller aggregate only —
        used when the originating process already bumped the registry
        counter (a replica-stage drop) so /metrics isn't double-counted."""
        try:
            router = self._handle_for(deployment)._get_router()
        except Exception:  # noqa: BLE001 — visibility never masks the error
            return
        if kind == "cancelled":
            router.note_cancelled()
        elif kind == "expired":
            router.note_expired(bump_metric=metric)
        elif kind == "shed":
            router.note_shed()

    def _error_response(self, e: BaseException, deployment: str):
        from aiohttp import web
        from ray_tpu.exceptions import BackPressureError

        kind = classify_request_error(e)
        if kind == "shed":
            cause = _unwrap_cause(e)
            retry_after = cause.retry_after_s if isinstance(
                cause, BackPressureError) else 1.0
            # shed counter lives in the router (it raised); just map it
            return web.json_response(
                {"error": repr(e), "retry_after_s": retry_after},
                status=503,
                headers={"Retry-After": str(max(1, int(retry_after)))})
        if kind == "expired":
            from ray_tpu.exceptions import DeadlineExceededError

            # a BARE DeadlineExceededError was raised (and counted) by
            # this process's router; only count expiries the proxy itself
            # observed.  A replica-stage drop (TaskError-wrapped
            # DeadlineExceededError) already bumped the registry counter
            # in the replica process — count it toward the controller
            # aggregate only, so /metrics reports one expiry per drop.
            if not isinstance(e, DeadlineExceededError):
                self._note_degradation(
                    deployment, "expired",
                    metric=not replica_counted_expiry(e))
            return web.json_response({"error": repr(e)}, status=504)
        return web.json_response({"error": repr(e)}, status=500)

    async def _stream_sse(self, request, handle, body, loop, ctx, lease):
        """Proxy a streaming deployment call as Server-Sent Events."""
        from aiohttp import web

        _END = object()
        dep = handle._deployment
        # closing the ref generator releases the router's admission slot
        # and cancels the replica-side producer task; the tracker makes
        # that happen exactly once, whether the client drops the stream
        # while the dispatch is still acquiring a slot or mid-write
        tracker = AbandonTracker(
            lambda: self._note_degradation(dep, "cancelled"),
            cancel_fn=lambda resp: _close_stream(resp.ref_generator))

        def _dispatch():
            # a dispatch that raises never binds: abandon() then has
            # nothing to cancel and stays a no-op
            with scope(ctx):
                resp = handle.remote_streaming(body)
            it = iter(resp)
            tracker.bind(resp)
            return resp, it

        cf = self._dispatch_pool.submit(_dispatch)
        try:
            stream_resp, stream = await asyncio.wrap_future(cf)
        except asyncio.CancelledError:
            # client dropped the SSE request before the dispatch bound:
            # the bind (whenever the admission queue frees it) closes the
            # stream instead of letting the producer run for nobody; the
            # pool thread is still pinned until then, so the concurrency
            # slot follows it, not this handler
            tracker.abandon_async()
            lease.defer_to(cf)
            raise
        except Exception as e:  # noqa: BLE001
            return self._error_response(e, dep)

        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"})
        await resp.prepare(request)

        def _next():
            try:
                return next(stream)
            except StopIteration:
                return _END

        try:
            while True:
                cf = self._dispatch_pool.submit(_next)
                try:
                    item = await asyncio.wrap_future(cf)
                except asyncio.CancelledError:
                    lease.defer_to(cf)  # thread blocked in next(stream)
                    raise
                if item is _END:
                    break
                try:
                    frame = json.dumps(item)
                except TypeError:
                    frame = json.dumps({"text": str(item)})
                await resp.write(f"data: {frame}\n\n".encode())
        except asyncio.CancelledError:
            # client dropped the SSE stream mid-write
            tracker.abandon_async()
            raise
        except Exception as e:  # noqa: BLE001
            await resp.write(
                f"event: error\ndata: {json.dumps(repr(e))}\n\n".encode())
        await resp.write_eof()
        return resp

    def _serve(self):
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        from aiohttp import web

        async def handler(request: "web.Request") -> "web.Response":
            # route resolution can hit the controller (blocking get): keep it
            # off the event loop thread along with the routed call itself
            dep = await loop.run_in_executor(None, self._resolve, request.path)
            if dep is None:
                return web.json_response(
                    {"error": f"no deployment for {request.path}"}, status=404)
            if self._active >= self._max_concurrent:
                # dispatch pool fully pinned: shed HERE, at the event
                # loop, instead of parking the request in an executor
                # work queue where no bound, deadline check, or counter
                # can see it
                loop.run_in_executor(None, self._note_degradation,
                                     dep, "shed")
                return web.json_response(
                    {"error": "proxy at max_concurrent_requests "
                              f"({self._max_concurrent})",
                     "retry_after_s": 1.0},
                    status=503, headers={"Retry-After": "1"})
            self._active += 1  # event-loop-confined: no lock needed

            def _release():
                self._active -= 1
            lease = _PoolLease(_release, loop)
            try:
                return await routed(request, dep, lease)
            finally:
                # a disconnect mid-dispatch defers the release to the
                # pool thread still pinned by this request
                lease.settle()

        async def routed(request: "web.Request", dep: str,
                         lease: _PoolLease) -> "web.Response":
            if request.method == "POST":
                try:
                    body = await request.json()
                except Exception:
                    body = (await request.read()).decode("utf-8", "replace")
            else:
                body = dict(request.query)
            handle = self._handle_for(dep)
            # model multiplexing: the reference's serve_multiplexed_model_id
            # header routes to a replica that already holds the model
            mux_id = request.headers.get("serve_multiplexed_model_id", "")
            if mux_id:
                handle = handle.options(multiplexed_model_id=mux_id)
            # the request's end-to-end budget + id, minted ONCE per route
            # and carried through router → replica → nested handles
            ctx = self._mint_context(request)
            # SSE streaming: the deployment method is a generator and the
            # client opted in (Accept: text/event-stream or ?stream=1);
            # each yielded item becomes one `data:` event the moment the
            # replica produces it (reference: serve StreamingResponse).
            wants_stream = (
                "text/event-stream" in request.headers.get("Accept", "")
                or request.query.get("stream") in ("1", "true"))
            if wants_stream:
                # optional ?method= routes to a named generator method
                # (e.g. the LLM deployment's token `stream`)
                method = request.query.get("method")
                if method and not method.startswith("_"):
                    handle = self._handle_for(dep, method)
                return await self._stream_sse(request, handle, body, loop,
                                              ctx, lease)
            tracker = AbandonTracker(
                lambda: self._note_degradation(dep, "cancelled"))

            def _dispatch():
                # run_in_executor does not propagate contextvars: re-enter
                # the request scope explicitly on the executor thread
                with scope(ctx):
                    resp = handle.remote(body)
                tracker.bind(resp)
                return resp

            cf = None
            try:
                cf = self._dispatch_pool.submit(_dispatch)
                resp_obj = await asyncio.wrap_future(cf)
                cf = self._dispatch_pool.submit(
                    lambda: resp_obj.result(timeout=ctx.remaining_s()))
                out = await asyncio.wrap_future(cf)
            except asyncio.CancelledError:
                # client disconnected mid-request (handler_cancellation):
                # don't let the replica finish work nobody will read.
                # bind/abandon rendezvous: even if the dispatch is still
                # waiting in the router admission queue, the cancel lands
                # the moment it binds — however long that takes.  The
                # pool thread stays pinned until then, so the concurrency
                # slot is released by it, not by this unwinding handler
                tracker.abandon_async()
                if cf is not None:
                    lease.defer_to(cf)
                raise
            except Exception as e:
                kind = classify_request_error(e)
                if kind == "expired" and tracker.resp is not None:
                    # budget spent while we waited: the work is abandoned
                    # — cancel it so a stalled replica doesn't keep a
                    # slot pinned for a client that's gone
                    try:
                        ray_tpu.cancel(tracker.resp.ref)
                    except Exception:  # noqa: BLE001
                        pass
                return self._error_response(e, dep)
            try:
                return web.json_response(out)
            except TypeError:
                return web.Response(text=str(out))

        async def health(_request):
            return web.json_response({"status": "ok"})

        app = web.Application()
        app.router.add_route("GET", "/-/healthz", health)
        app.router.add_route("*", "/{tail:.*}", handler)
        # handler_cancellation: a client disconnect must CANCEL the
        # in-flight handler (and through it the replica task) — aiohttp
        # 3.9+ made that opt-in
        try:
            runner = web.AppRunner(app, handler_cancellation=True)
        except TypeError:  # older aiohttp: cancellation was the default
            runner = web.AppRunner(app)

        async def start():
            await runner.setup()
            site = web.TCPSite(runner, self._host, self._port)
            await site.start()

        try:
            loop.run_until_complete(start())
        except Exception as e:
            self._error = repr(e)
            self._ready.set()
            return
        self._ready.set()
        loop.run_forever()


def _close_stream(stream):
    close = getattr(stream, "close", None)
    if close is not None:
        try:
            close()
        except Exception:  # noqa: BLE001
            pass
