"""gang-table-discipline: gang state writes go through the persisted table.

The gang state machine (PENDING -> RESERVING -> PLACED -> PREEMPTING ->
REMOVED, docs/fault_tolerance.md "Gangs, slices & priority preemption")
is only crash-consistent because EVERY transition is one call to
``GcsServer._gang_transition`` — the single write path that updates the
snapshot/WAL-persisted ``gangs`` table, appends history, and publishes
the audit event.  A direct ``gang["state"] = ...`` (or a raw write into
``self.gangs[...]``) anywhere else would be an in-memory-only
transition: invisible to the audit stream, lost on a GCS restart, and a
re-opened door to the partial-gang bugs the table closed.

Flagged anywhere under ``ray_tpu/``:

- assignment to a ``["state"]`` subscript whose base names a gang
  (``gang``, ``victim_gang``, ``self.gangs[...]`` …);
- assignment into the gang table itself (``self.gangs[...] = ...`` or
  ``<x>.gangs[...] = ...``);

unless the enclosing function IS ``_gang_transition`` (the one place
the write is the point).  Reads are never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ray_tpu._private.analysis.core import (
    Checker, Finding, ParsedFile, dotted_name, register)


def _is_gang_name(node: ast.AST) -> bool:
    """True when the expression names a gang record: a variable whose
    name contains ``gang``, or a subscript of a ``gangs`` table."""
    if isinstance(node, ast.Name):
        return "gang" in node.id.lower()
    if isinstance(node, ast.Attribute):
        return "gang" in node.attr.lower()
    if isinstance(node, ast.Subscript):
        base = node.value
        if isinstance(base, ast.Attribute) and base.attr == "gangs":
            return True
        if isinstance(base, ast.Name) and base.id == "gangs":
            return True
    return False


def _is_gang_table(node: ast.AST) -> bool:
    """True for the gang table itself (``self.gangs`` / ``gcs.gangs``)."""
    if isinstance(node, ast.Attribute) and node.attr == "gangs":
        return True
    return isinstance(node, ast.Name) and node.id == "gangs"


def _enclosing_function(pf: ParsedFile, node: ast.AST) -> Optional[str]:
    fn = pf.enclosing(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    return fn.name if fn is not None else None


@register
class GangTableDisciplineChecker(Checker):
    rule = "gang-table-discipline"
    description = ("gang state transitions must write through "
                   "_gang_transition (the persisted GCS gang table) — "
                   "no in-memory-only transitions")
    hint = ("call self._gang_transition(gang_id, \"<STATE>\", ...) "
            "instead of assigning gang state or gang-table entries "
            "directly; the helper persists, appends history, and "
            "publishes the audit event in one step")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("ray_tpu/")

    def check(self, pf: ParsedFile) -> Iterable[Finding]:
        out: List[Finding] = []
        if pf.tree is None:
            return out
        for node in ast.walk(pf.tree):
            if not isinstance(node, (ast.Assign, ast.AugAssign)):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                if not isinstance(tgt, ast.Subscript):
                    continue
                if _enclosing_function(pf, node) == "_gang_transition":
                    continue
                # gang["state"] = ... on a gang-named receiver
                sl = tgt.slice
                if isinstance(sl, ast.Constant) and sl.value == "state" \
                        and _is_gang_name(tgt.value):
                    out.append(self.finding(
                        pf, node,
                        f"direct gang state assignment on "
                        f"{dotted_name(tgt.value) or 'a gang record'} — "
                        f"an in-memory-only transition bypasses the "
                        f"persisted table, history, and audit stream"))
                    continue
                # self.gangs[...] = ... raw table writes
                if _is_gang_table(tgt.value):
                    out.append(self.finding(
                        pf, node,
                        "raw write into the gang table — entries are "
                        "created/updated only by _gang_transition so "
                        "every record carries a consistent state + "
                        "history"))
        return out
