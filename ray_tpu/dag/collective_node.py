"""Collective nodes: allreduce & friends as first-class compiled-DAG ops.

Parity: ``python/ray/dag/collective_node.py:23`` (``_CollectiveOperation``
binds one node per participating actor; executing the compiled DAG runs the
collective jointly through the Communicator) and the comm/compute overlap
scheduling of ``python/ray/dag/dag_node_operation.py``.

Usage (one output node per input, each bound to the same actor)::

    with InputNode() as inp:
        g0 = w0.grad.bind(inp)
        g1 = w1.grad.bind(inp)
        r0, r1 = allreduce.bind([g0, g1])
        dag = MultiOutputNode([w0.apply.bind(r0), w1.apply.bind(r1)])
    cdag = dag.experimental_compile()

Execution model: at compile time the DAG's actors are joined into a
collective group (``util.collective``, tcp backend by default — XLA mesh
groups for in-process device meshes); inside each actor's exec loop the
collective task calls the group op with its local value.  Overlap: the
exec loop launches the collective on a background thread and only joins at
the first task that consumes its result, so independent compute between
the reduce and its consumer runs concurrently with communication
(``dag_node_operation.py`` READ/COMPUTE/WRITE overlap, economy form).

Error semantics: a rank whose upstream failed skips the collective and
propagates the TaskError; peer ranks then fail the iteration with the
collective timeout (``collective_op_timeout_s``) rather than hanging.
"""

from __future__ import annotations

import uuid
from typing import Any, List, Optional

from ray_tpu.dag.dag_node import ClassMethodNode, DAGNode


class _CollectiveGroup:
    """One joint operation over N actor-resident values."""

    def __init__(self, inputs: List[ClassMethodNode], op: str,
                 backend: str, timeout_s: Optional[float] = None):
        if not inputs:
            raise ValueError("collective bind() needs at least one node")
        for n in inputs:
            if not isinstance(n, ClassMethodNode):
                raise TypeError(
                    "collective inputs must be actor-method nodes, got "
                    f"{type(n).__name__}")
        actors = [n.actor._actor_id for n in inputs]
        if len(set(actors)) != len(actors):
            raise ValueError(
                "collective inputs must live on distinct actors (one rank "
                "per process)")
        self.inputs = list(inputs)
        self.op = op
        self.backend = backend
        # threaded into the supervised group at compile time: a rank
        # whose upstream failed leaves its peers to fail THIS iteration
        # via watchdog abort within timeout_s, not hang the exec loops
        self.timeout_s = timeout_s
        self.group_name = f"dag_collective_{uuid.uuid4().hex[:12]}"

    @property
    def world_size(self) -> int:
        return len(self.inputs)


class CollectiveNode(DAGNode):
    """Rank ``index``'s output of a joint collective op.  Lives on the same
    actor as its input node (reference ``CollectiveOutputNode``)."""

    def __init__(self, group: _CollectiveGroup, index: int):
        super().__init__((group.inputs[index],), {})
        self.group = group
        self.index = index
        self.method_name = f"__collective_{group.op}__"

    @property
    def actor(self):
        return self.group.inputs[self.index].actor

    @property
    def input_node(self) -> ClassMethodNode:
        return self.group.inputs[self.index]

    def __repr__(self):
        return (f"CollectiveNode({self.group.op}, rank={self.index}/"
                f"{self.group.world_size})")


class _CollectiveBinder:
    """``allreduce.bind([n0, n1, ...], op=...)`` — reference
    ``ray.experimental.collective.allreduce``."""

    def __init__(self, kind: str):
        self.kind = kind

    def bind(self, nodes: List[ClassMethodNode], *, op: str = "sum",
             backend: str = "tcp", timeout_s: Optional[float] = None,
             transport: Optional[Any] = None) -> List[CollectiveNode]:
        del transport  # custom Communicators select via backend string
        if self.kind == "allreduce":
            if op not in ("sum", "prod", "min", "max"):
                raise ValueError(
                    f"unsupported reduce op {op!r}: expected one of "
                    f"sum/prod/min/max")
            kind = f"allreduce_{op}"
        else:
            kind = self.kind
        group = _CollectiveGroup(nodes, kind, backend, timeout_s=timeout_s)
        return [CollectiveNode(group, i) for i in range(len(nodes))]


allreduce = _CollectiveBinder("allreduce")
allgather = _CollectiveBinder("allgather")
reducescatter = _CollectiveBinder("reducescatter")


def run_collective(kind: str, value, group_name: str):
    """Execute one collective op inside an actor's exec loop."""
    from ray_tpu.util.collective import collective as coll
    from ray_tpu.util.collective.types import ReduceOp

    if kind.startswith("allreduce_"):
        op = {"sum": ReduceOp.SUM, "prod": ReduceOp.PRODUCT,
              "min": ReduceOp.MIN, "max": ReduceOp.MAX}[
                  kind[len("allreduce_"):]]
        return coll.allreduce(value, group_name=group_name, op=op)
    if kind == "allgather":
        return coll.allgather(value, group_name=group_name)
    if kind == "reducescatter":
        return coll.reducescatter(value, group_name=group_name)
    raise ValueError(f"unknown collective kind {kind!r}")
