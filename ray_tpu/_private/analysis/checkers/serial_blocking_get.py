"""serial-blocking-get: the ingest hot path must not regress to one
blocking ``ray_tpu.get`` per block inside an iteration loop.

Migrated from ``tests/test_tooling.py::
test_no_serial_blocking_get_in_data_iteration_loops`` (PR 5's guard),
whose bespoke ``# allowed-blocking-get: <why>`` annotation this rule's
standard suppression grammar replaces::

    block = ray_tpu.get(ref)  # raylint: disable=serial-blocking-get -- prefetched

Any single-ref ``ray_tpu.get`` inside a for/while loop in
``data/iterator.py`` or ``data/dataset.py`` is the serial anti-pattern
the pipelined lookahead replaced (see docs/data_performance.md) unless
the suppression reason explains why the pull provably started earlier
(lookahead surface, split request issued one iteration ahead, …).
Batched gets on a list of refs are fine — that's one round trip.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ray_tpu._private.analysis.core import (
    Checker, Finding, ParsedFile, dotted_name, register)

_HOT_FILES = ("ray_tpu/data/iterator.py", "ray_tpu/data/dataset.py")


@register
class SerialBlockingGetChecker(Checker):
    rule = "serial-blocking-get"
    description = ("no per-block blocking ray_tpu.get inside data "
                   "iteration loops (serial ingest-stall guard)")
    hint = ("route the pull through the prefetch lookahead, batch the "
            "refs, or suppress with the reason the pull provably started "
            "earlier")

    def applies_to(self, relpath: str) -> bool:
        return relpath in _HOT_FILES

    def check(self, pf: ParsedFile) -> Iterable[Finding]:
        out: List[Finding] = []
        loops = [n for n in ast.walk(pf.tree)
                 if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]
        seen = set()
        for loop in loops:
            for n in ast.walk(loop):
                if id(n) in seen:
                    continue
                if not (isinstance(n, ast.Call)
                        and dotted_name(n.func) == "ray_tpu.get"):
                    continue
                seen.add(id(n))
                # a list of refs is a batched get, not the serial pattern
                if n.args and isinstance(n.args[0],
                                         (ast.List, ast.ListComp)):
                    continue
                out.append(self.finding(
                    pf, n,
                    "blocking ray_tpu.get on a single ref inside an "
                    "iteration loop — a per-block serial stall unless the "
                    "pull started earlier"))
        return out
