"""Compiled DAG: per-edge shm channels + per-actor exec loops.

Parity: ``python/ray/dag/compiled_dag_node.py`` (``CompiledDAG`` :805,
``execute`` :2552, ``teardown`` :3258) over the mutable-object channel
substrate.  After compile, a call crosses NO control plane: the driver
writes the input channel, each actor's exec-loop thread reads its in-edges,
runs the method, writes its out-edge, and the driver reads the output
channel — microseconds per hop instead of the milliseconds of the RPC task
path.

Same-actor edges short-circuit through a local cache (no channel).  Every
cross-process edge rides a tier-negotiated ``EdgeTransport``
(``experimental/channel/transport.py``): tier A in-mesh fusion (below),
tier B device frames for same-mesh/slice endpoints (zero-copy serialize
into shm, reader lands arrays with an alias-guarded ``device_put`` from
the segment view — the DMA leg on TPU), tier C zero-copy host shm
everywhere else.  Tiers are fixed once at compile time from actor
placement/device probes, recorded in ``stats()["channel_transport"]`` and
on the dag spans, and degrade to tier C on failure — docs/compiled_graphs.md.

In-mesh jit fusion: a method bound with ``.options(jit=True)`` promises a
jax-traceable body; adjacent jit-marked nodes on the same actor are fused
at compile time into ONE ``jax.jit`` program, so intermediates between
them never leave the device (no host staging, no per-node dispatch, XLA
fuses across node boundaries).  Cross-actor edges still host-stage —
measured in ``benchmarks/dag_fusion_bench.py``.

The ``jit=True`` contract is jax's: the method must be a pure function
of its ARGUMENTS.  Actor attributes it reads (``self.w``) are traced
once and baked into the compiled program as constants — state mutated
by other methods between iterations is NOT seen, exactly as with any
hand-written ``jax.jit`` over a bound method.  Methods that read
mutable actor state must stay unfused (omit ``jit=True``) or take the
state as a DAG argument.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag.collective_node import CollectiveNode, run_collective
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.exceptions import TaskError
from ray_tpu.experimental.channel import Channel, ChannelClosedError
from ray_tpu.experimental.channel import transport as transport_mod
from ray_tpu.experimental.channel.transport import (
    TIER_FUSED,
    EdgeTransport,
)

# node types that execute as tasks inside an actor's exec loop
_TASK_NODES = (ClassMethodNode, CollectiveNode)


class _Stop:
    """Teardown sentinel propagated through every channel."""

    def __reduce__(self):
        return (_Stop, ())


_STOP = _Stop()


class _StopSignal(BaseException):
    """Raised inside the exec loop when a channel delivers the _STOP
    sentinel (BaseException so user-level ``except Exception`` in resolve
    can't swallow it)."""


# --------------------------------------------------------------------------
# Actor-side exec loop (runs inside the actor process, in its own thread)
# --------------------------------------------------------------------------

_EXEC_LOOPS: Dict[str, Dict[str, Any]] = {}


def _start_exec_loop(instance, dag_id: str, spec_bytes: bytes) -> bool:
    from ray_tpu._private import serialization

    spec = serialization.loads(spec_bytes)
    # prune finished loops so long-lived actors don't accumulate state
    for done_id in [k for k, st in _EXEC_LOOPS.items() if st.get("done")]:
        _EXEC_LOOPS.pop(done_id, None)
    state: Dict[str, Any] = {"error": None, "done": False}
    _EXEC_LOOPS[dag_id] = state

    def _loop():
        try:
            _run_exec_loop(instance, spec)
        except ChannelClosedError:
            pass
        except BaseException as e:  # noqa: BLE001 — surfaced via status
            state["error"] = repr(e)
        finally:
            state["done"] = True

    t = threading.Thread(target=_loop, daemon=True,
                         name=f"dag-exec-{dag_id[:8]}")
    state["thread"] = t
    t.start()
    return True


def _exec_loop_status(instance, dag_id: str) -> Dict[str, Any]:
    st = _EXEC_LOOPS.get(dag_id)
    if st is None:
        return {"done": True, "error": None}
    return {"done": st["done"], "error": st["error"]}


class _Pending:
    """An in-flight overlapped collective; joined at first consumption."""

    __slots__ = ("fut",)

    def __init__(self, fut):
        self.fut = fut

    def join(self):
        try:
            return self.fut.result()
        except BaseException as e:  # noqa: BLE001 — propagated downstream
            return TaskError.from_exception(e)


def _fuse_jit_runs(tasks: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Merge maximal runs of ADJACENT jit-marked tasks into fused tasks.

    Safety rule: fusing hoists the run's channel reads before its channel
    writes (externals resolve first, emits write last).  A candidate that
    reads a cross-actor channel therefore may not join a run that has
    already written an out-channel — an A→B→A shape would deadlock (A's
    read of B's output would precede the write B needs).  DAG-input reads
    are always safe to hoist: the driver writes the input before any task
    runs.
    """
    out: List[Dict[str, Any]] = []
    i = 0
    while i < len(tasks):
        t = tasks[i]
        if not t.get("jit"):
            out.append(t)
            i += 1
            continue
        run = [t]
        wrote = t["out_channel"] is not None
        j = i + 1
        while j < len(tasks) and tasks[j].get("jit"):
            cand = tasks[j]
            reads_chan = any(
                a[0] == "chan"
                for a in list(cand["args"]) + list(cand["kwargs"].values()))
            if wrote and reads_chan:
                break
            run.append(cand)
            wrote = wrote or cand["out_channel"] is not None
            j += 1
        out.append(_make_fused_task(run, tasks[j:]))
        i = j
    return out


def _make_fused_task(run: List[Dict[str, Any]],
                     later_tasks: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Build the fused task dict: external argspecs in first-read order
    (preserving the unfused channel-read order), and the emit list — every
    sub-result consumed outside the run (out-channel or a later local)."""
    run_idx = {t["local_idx"] for t in run}
    later_refs = set()
    for lt in later_tasks:
        subs = lt["fused"] if lt.get("fused") is not None else [lt]
        for s in subs:
            for a in list(s["args"]) + list(s["kwargs"].values()):
                if a[0] == "local":
                    later_refs.add(a[1])
    ext: List[Tuple] = []
    seen = set()
    for t in run:
        for a in list(t["args"]) + list(t["kwargs"].values()):
            if a[0] == "const" or (a[0] == "local" and a[1] in run_idx):
                continue
            key = tuple(a)
            if key not in seen:
                seen.add(key)
                ext.append(a)
    emit = [(t["local_idx"], t["out_channel"]) for t in run
            if t["out_channel"] is not None or t["local_idx"] in later_refs]
    if not emit:  # nothing consumed outside: keep the tail result visible
        emit = [(run[-1]["local_idx"], None)]
    return {
        "fused": [{"method": t["method"], "args": t["args"],
                   "kwargs": t["kwargs"], "local_idx": t["local_idx"]}
                  for t in run],
        "ext": ext,
        "emit": emit,
        "out_channel": None,
        "local_idx": run[-1]["local_idx"],
    }


def _build_fused_fn(instance, t: Dict[str, Any]):
    """One jax.jit program over a run of adjacent jit-marked tasks.

    External values (channel reads, earlier locals, DAG input) are traced
    arguments; consts are closed over statically; intermediates between
    subtasks stay device-resident tracers.
    """
    import jax

    run = t["fused"]
    run_idx = {s["local_idx"] for s in run}
    ext_slot = {tuple(a): k for k, a in enumerate(t["ext"])}
    emit_idx = [idx for idx, _ch in t["emit"]]

    def fused(ext_vals):
        loc: Dict[int, Any] = {}

        def res(a):
            if a[0] == "const":
                return a[1]
            if a[0] == "local" and a[1] in run_idx:
                return loc[a[1]]
            return ext_vals[ext_slot[tuple(a)]]

        for s in run:
            args = [res(a) for a in s["args"]]
            kwargs = {k: res(v) for k, v in s["kwargs"].items()}
            loc[s["local_idx"]] = getattr(instance, s["method"])(
                *args, **kwargs)
        return tuple(loc[i] for i in emit_idx)

    return jax.jit(fused)


def _exec_fused(instance, t: Dict[str, Any], resolve, local) -> None:
    """Execute one fused task: resolve externals (lazy channel reads, in
    original task order), run the jitted program once, fan results out to
    the emitted locals/out-channels.

    Error semantics match unfused execution EXACTLY: an upstream TaskError
    propagates to every emit without running the program, and if the fused
    program itself raises, the run re-executes eagerly one subtask at a
    time so only the genuinely-failing subtask (and its downstream
    consumers) error — a fused sibling that would have succeeded unfused
    still emits its value."""
    try:
        ext_vals = [resolve(a) for a in t["ext"]]  # may raise _StopSignal
    except _StopSignal:
        raise
    except BaseException as e:  # noqa: BLE001 — bad input shape, closed chan
        # the fused task's top-level out_channel is always None, so the
        # generic per-task handler would write this error NOWHERE and
        # downstream consumers would hang — fan it out to every emit
        err = TaskError.from_exception(e)
        for idx, ch in t["emit"]:
            local[idx] = err
            if ch is not None:
                ch.write(err)
        return
    if any(isinstance(v, TaskError) for v in ext_vals):
        # per-subtask propagation: only subtasks that (transitively) consume
        # the failing input error; a fused sibling on a clean input path
        # still emits its value — exactly the unfused semantics
        _exec_fused_eager(instance, t, ext_vals, local)
        return
    fn = t.get("_fn")
    if fn is None:
        fn = t["_fn"] = _build_fused_fn(instance, t)
    try:
        outs = fn(ext_vals)
        for k, (idx, ch) in enumerate(t["emit"]):
            local[idx] = outs[k]
            if ch is not None:
                ch.write(outs[k])
        return
    except BaseException:  # noqa: BLE001 — localize via the eager path
        pass
    _exec_fused_eager(instance, t, ext_vals, local)


def _exec_fused_eager(instance, t: Dict[str, Any], ext_vals, local) -> None:
    """Per-subtask eager re-execution of a failed fused run (unfused
    semantics: each subtask errors individually, errors flow to their own
    consumers only)."""
    run_idx = {s["local_idx"] for s in t["fused"]}
    ext_slot = {tuple(a): k for k, a in enumerate(t["ext"])}
    loc: Dict[int, Any] = {}

    def res(a):
        if a[0] == "const":
            return a[1]
        if a[0] == "local" and a[1] in run_idx:
            return loc[a[1]]
        return ext_vals[ext_slot[tuple(a)]]

    for s in t["fused"]:
        try:
            args = [res(a) for a in s["args"]]
            kwargs = {k: res(v) for k, v in s["kwargs"].items()}
            up = next((v for v in list(args) + list(kwargs.values())
                       if isinstance(v, TaskError)), None)
            result = up if up is not None else getattr(
                instance, s["method"])(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — propagated downstream
            result = TaskError.from_exception(e)
        loc[s["local_idx"]] = result
    for idx, ch in t["emit"]:
        local[idx] = loc[idx]
        if ch is not None:
            ch.write(loc[idx])


def _run_exec_loop(instance, spec: Dict[str, Any]) -> None:
    """One iteration per execute(): read in-edges, run tasks, write out-edges.

    spec = {"read_channels": {name: Channel}, "tasks": [
        {"method": str, "args": [argspec], "kwargs": {k: argspec},
         "out_channel": Channel|None, "local_idx": int,
         "collective": None | {"kind", "group"}}]}
    argspec = ("const", v) | ("input",) | ("input_attr", key)
             | ("chan", name) | ("local", idx)

    Comm/compute overlap (reference ``dag_node_operation.py``): a
    collective whose result is consumed only LATER on this actor runs on a
    background thread; tasks between the collective and its first consumer
    execute concurrently with the communication.
    """
    read_channels: Dict[str, Channel] = spec["read_channels"]
    tasks = spec["tasks"]
    coll_pool = None
    if any(t.get("collective") for t in tasks):
        from concurrent.futures import ThreadPoolExecutor

        coll_pool = ThreadPoolExecutor(max_workers=1,
                                       thread_name_prefix="dag-coll")
    try:
        _exec_iterations(instance, spec, read_channels, tasks, coll_pool)
    finally:
        if coll_pool is not None:
            coll_pool.shutdown(wait=False)


def _exec_iterations(instance, spec, read_channels, tasks, coll_pool):
    while True:
        # Channels are read LAZILY, at first use within the iteration: an
        # A->B->A shape needs A to run its first task (filling B's input)
        # before blocking on B's output — an eager read-all would deadlock.
        cache: Dict[str, Any] = {}

        def get_chan(name: str):
            if name not in cache:
                cache[name] = read_channels[name].read()
            if isinstance(cache[name], _Stop):
                # raise BEFORE any unpacking of the value (the input argspec
                # does `args, kwargs = get_chan(...)`)
                raise _StopSignal()
            return cache[name]

        local: Dict[int, Any] = {}

        def resolve(a):
            kind = a[0]
            if kind == "const":
                return a[1]
            if kind == "input":
                args, kwargs = get_chan(spec["input_channel"])
                if len(args) == 1 and not kwargs:
                    return args[0]
                raise TypeError(
                    "DAG input consumed whole but execute() got multiple "
                    "args; bind inp[i]/inp.key instead")
            if kind == "input_attr":
                args, kwargs = get_chan(spec["input_channel"])
                key = a[1]
                return kwargs[key] if isinstance(key, str) else args[key]
            if kind == "chan":
                return get_chan(a[1])
            if kind == "local":
                v = local[a[1]]
                if isinstance(v, _Pending):  # join an overlapped collective
                    v = local[a[1]] = v.join()
                return v
            raise ValueError(f"bad argspec {a!r}")

        stopping = False
        for t in tasks:
            try:
                if t.get("fused") is not None:
                    _exec_fused(instance, t, resolve, local)
                    continue
                args = [resolve(a) for a in t["args"]]
                kwargs = {k: resolve(v) for k, v in t["kwargs"].items()}
                vals = list(args) + list(kwargs.values())
                upstream_err = next(
                    (v for v in vals if isinstance(v, TaskError)), None)
                coll = t.get("collective")
                if upstream_err is not None:
                    # skip the op (a collective's peers fail the iteration
                    # via the group timeout instead of hanging forever)
                    result = upstream_err
                elif coll is not None:
                    if t["out_channel"] is None:
                        # result consumed later on this actor: overlap the
                        # communication with the compute in between
                        local[t["local_idx"]] = _Pending(coll_pool.submit(
                            run_collective, coll["kind"], args[0],
                            coll["group"]))
                        continue
                    result = run_collective(coll["kind"], args[0],
                                            coll["group"])
                else:
                    result = getattr(instance, t["method"])(*args, **kwargs)
            except _StopSignal:
                stopping = True
                break
            except BaseException as e:  # noqa: BLE001 — propagated downstream
                result = TaskError.from_exception(e)
            local[t["local_idx"]] = result
            if t["out_channel"] is not None:
                t["out_channel"].write(result)
        if stopping:
            for t in tasks:
                if t.get("fused") is not None:
                    for idx, ch in t["emit"]:
                        if ch is not None and idx not in local:
                            ch.write(_STOP)
                    continue
                out = t["out_channel"]
                if out is not None and t["local_idx"] not in local:
                    out.write(_STOP)
            return


# --------------------------------------------------------------------------
# Driver side
# --------------------------------------------------------------------------

class CompiledDAGRef:
    """Result handle for one execute().  Results may be gotten out of
    submission order (earlier executions' values are buffered, capped by
    ``max_buffered_results``); each ref can be gotten once."""

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx
        self._result: Any = None
        self._has_result = False

    def get(self, timeout: Optional[float] = None):
        from ray_tpu._private import tracing

        with tracing.span("dag.get", kind="dag",
                          attrs={"exec_idx": self._idx,
                                 "channel_transport":
                                     self._dag._tier_summary()}):
            return self._dag._get_result(self, timeout)

    def __repr__(self):
        return f"CompiledDAGRef(idx={self._idx})"


class CompiledDAGFuture:
    """Awaitable result of ``execute_async()`` (reference:
    ``compiled_dag_node.py:2633 execute_async`` → ``CompiledDAGFuture``).
    Await resolves when this execution's outputs arrive; earlier
    executions' results are drained into the buffer, so futures may be
    awaited in any order and N>1 executions can be in flight."""

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx
        self._awaited = False

    def __await__(self):
        if self._awaited:
            raise ValueError(
                "a CompiledDAGFuture can only be awaited once")
        self._awaited = True
        return self._dag._await_result(self._idx).__await__()

    def __repr__(self):
        return f"CompiledDAGFuture(idx={self._idx})"


class CompiledDAG:
    def __init__(self, root: DAGNode, *, buffer_size_bytes: int = 1 << 20,
                 submit_timeout: float = 30.0,
                 max_buffered_results: int = 1000):
        self.root = root
        self.buffer_size = buffer_size_bytes
        self.submit_timeout = submit_timeout
        self.max_buffered_results = max_buffered_results
        self.dag_id = uuid.uuid4().hex
        self._input_channel: Optional[EdgeTransport] = None
        self._output_channels: List[EdgeTransport] = []
        self._all_channels: List[Channel] = []
        # edge label -> negotiated transport tier (fixed at compile time;
        # surfaced in stats() and on dag.execute/dag.get spans)
        self._edge_tiers: Dict[str, str] = {}
        self._actors: List[Any] = []
        self._collective_groups: List[Any] = []
        self._next_exec_idx = 0
        self._next_get_idx = 0
        # values already drained from output channels for the execution
        # currently being gotten (lets a timed-out get() resume without
        # re-reading channels it already consumed)
        self._partial_values: List[Any] = []
        # out-of-order delivery: executions drained past a waiter's index
        # park here until their ref/future claims them
        self._buffered_results: Dict[int, List[Any]] = {}
        self._torn_down = False
        # a DAG actor observed DEAD mid-execution poisons the pipeline:
        # every pending/future result raises this instead of hanging on
        # channels no exec loop will ever write again
        self._dead_actor_error: Optional[BaseException] = None
        self._last_liveness_probe = 0.0
        # separate locks: a producer blocked in a backpressured execute()
        # must not prevent a consumer's get() from draining the pipeline
        self._submit_lock = threading.Lock()
        self._get_lock = threading.Lock()
        self._drain_task: Optional[Any] = None  # eager async drainer
        self._drain_error: Optional[BaseException] = None
        # (loop, Event) pairs pulsed (threadsafe) after each drained
        # execution so futures waiting on any event loop wake up
        self._result_waiters: List[Any] = []

    # -- compilation -------------------------------------------------------
    def _compile(self) -> None:
        try:
            self._compile_inner()
        except BaseException:
            # no shm leak on failed compile
            for ch in self._all_channels:
                ch.destroy()
            self._all_channels = []
            self._torn_down = True
            raise

    def _compile_inner(self) -> None:
        from ray_tpu._private import serialization

        nodes = self.root._collect()
        input_nodes = [n for n in nodes if isinstance(n, InputNode)]
        if any(isinstance(n, FunctionNode) for n in nodes):
            raise TypeError(
                "compiled graphs support actor methods only (reference "
                "semantics); FunctionNode requires interpreted execute()")
        if len(input_nodes) != 1:
            raise ValueError(
                f"a compiled DAG needs exactly one InputNode, found "
                f"{len(input_nodes)}")
        self._input_node = input_nodes[0]

        terminals: List[DAGNode]
        if isinstance(self.root, MultiOutputNode):
            terminals = self.root.outputs
        else:
            terminals = [self.root]
        for t in terminals:
            if not isinstance(t, _TASK_NODES):
                raise TypeError(
                    f"compiled DAG outputs must be actor-method nodes, got "
                    f"{type(t).__name__}")

        method_nodes = [n for n in nodes if isinstance(n, _TASK_NODES)]
        # collective groups: every rank's output node must be part of THIS
        # dag — an absent rank would deadlock the group at runtime
        group_members: Dict[int, List[CollectiveNode]] = {}
        self._collective_groups = []
        for n in method_nodes:
            if isinstance(n, CollectiveNode):
                members = group_members.setdefault(id(n.group), [])
                if not members:
                    self._collective_groups.append(n.group)
                members.append(n)
        for group in self._collective_groups:
            found = {m.index for m in group_members[id(group)]}
            if len(found) != group.world_size:
                raise ValueError(
                    f"collective group over {group.world_size} actors but "
                    f"only ranks {sorted(found)} are reachable in this DAG "
                    f"— bind ALL returned collective nodes")
        # every task must depend (transitively) on the input: the exec loop
        # paces iterations by channel reads, so a read-less task would spin
        depends: Dict[int, bool] = {}
        for n in nodes:
            if isinstance(n, (InputNode, InputAttributeNode)):
                depends[id(n)] = True
            else:
                depends[id(n)] = any(depends.get(id(u), False)
                                     for u in n._upstream())
        for n in method_nodes:
            if not depends[id(n)]:
                raise ValueError(
                    f"{n!r} does not depend on the DAG input; compiled "
                    f"tasks must be reachable from InputNode")
        node_idx = {id(n): i for i, n in enumerate(method_nodes)}
        actor_of = {id(n): n.actor._actor_id for n in method_nodes}
        handles: Dict[Any, Any] = {n.actor._actor_id: n.actor
                                   for n in method_nodes}
        self._actors = list(handles.values())

        # transport negotiation: one placement/device probe per actor,
        # once, at compile time — every edge's tier is fixed before the
        # first execute (reference: per-edge NCCL channel init at dag
        # compilation, torch_tensor_nccl_channel.py)
        infos = transport_mod.gather_endpoint_info(
            self._actors, timeout=self.submit_timeout)
        driver_info = transport_mod.local_endpoint_info()

        def _label(n) -> str:
            return f"{n.method_name}@{actor_of[id(n)].hex()[:6]}"

        def _aid_label(aid) -> str:
            return f"@{aid.hex()[:6]}"

        # consumer sets
        consumes_input: Dict[Any, bool] = {aid: False for aid in handles}
        consumers: Dict[int, List[Any]] = {id(n): [] for n in method_nodes}
        for n in method_nodes:
            for dep in n._upstream():
                if isinstance(dep, (InputNode, InputAttributeNode)):
                    consumes_input[actor_of[id(n)]] = True
                elif isinstance(dep, _TASK_NODES):
                    if actor_of[id(dep)] != actor_of[id(n)]:
                        consumers[id(dep)].append(actor_of[id(n)])

        terminal_counts: Dict[int, int] = {}
        for t in terminals:
            terminal_counts[id(t)] = terminal_counts.get(id(t), 0) + 1
        terminal_ids = set(terminal_counts)

        # Tiered channels run the pure-Python data plane (native=False):
        # zero-copy value writes and deferred-ack reads need direct
        # segment access.  The buffer gets frame-header slack so the
        # user-visible payload capacity stays buffer_size_bytes.
        chan_capacity = self.buffer_size + 256

        # input channel: one writer (driver), one reader slot per actor
        # that consumes the input
        input_actors = [aid for aid, used in consumes_input.items() if used]
        input_ch = Channel(buffer_size=chan_capacity,
                           num_readers=max(1, len(input_actors)),
                           native=False)
        input_tier = transport_mod.negotiate_channel(
            driver_info, [infos.get(aid) for aid in input_actors])
        for aid in input_actors:
            # record the EFFECTIVE tier: one channel serves every reader
            # with one encoding, so a weakest-link downgrade applies to
            # all its edges (stats must not claim a device frame that
            # never ships)
            self._edge_tiers[f"input->{_aid_label(aid)}"] = input_tier
        self._input_channel = EdgeTransport(input_ch, input_tier, "input")
        self._all_channels.append(input_ch)
        input_slot = {aid: i for i, aid in enumerate(input_actors)}

        # per-node output channels (cross-actor consumers + driver)
        out_channel: Dict[int, Optional[Channel]] = {}
        out_tier: Dict[int, str] = {}
        out_slots: Dict[int, Dict[Any, int]] = {}
        for n in method_nodes:
            readers = sorted(set(consumers[id(n)]), key=repr)
            writer_info = infos.get(actor_of[id(n)])
            # a node listed k times in MultiOutputNode gets k driver slots
            # (each driver read consumes its own ack slot)
            n_driver = terminal_counts.get(id(n), 0)
            n_readers = len(readers) + n_driver
            if n_readers == 0:
                out_channel[id(n)] = None
                continue
            ch = Channel(buffer_size=chan_capacity, num_readers=n_readers,
                         native=False)
            self._all_channels.append(ch)
            out_channel[id(n)] = ch
            tier = transport_mod.negotiate_channel(
                writer_info,
                [infos.get(aid) for aid in readers]
                + [driver_info] * n_driver)
            out_tier[id(n)] = tier
            # record the EFFECTIVE channel tier per edge (weakest-link:
            # one encoding serves every reader — stats must not claim a
            # device frame a mixed reader set downgrades away)
            for aid in readers:
                self._edge_tiers[f"{_label(n)}->{_aid_label(aid)}"] = tier
            if n_driver:
                self._edge_tiers[f"{_label(n)}->driver"] = tier
            out_slots[id(n)] = {aid: i for i, aid in enumerate(readers)}

        # same-actor edges never leave the process: record them as tier A
        # (jit-fused runs literally compile away; unfused locals pass by
        # reference) so DAG stats account for every edge
        for n in method_nodes:
            for dep in n._upstream():
                if isinstance(dep, _TASK_NODES) and \
                        actor_of[id(dep)] == actor_of[id(n)]:
                    self._edge_tiers[f"{_label(dep)}->{_label(n)}"] = \
                        TIER_FUSED

        # driver's output channels, in terminal order (driver slots follow
        # the actor-consumer slots)
        self._output_channels = []
        next_driver_slot = {nid: len(out_slots.get(nid, {}))
                            for nid in terminal_ids}
        for t in terminals:
            ch = out_channel[id(t)]
            reader = Channel(ch.name, buffer_size=ch.buffer_size,
                             num_readers=ch.num_readers, _create=False)
            reader.set_reader_slot(next_driver_slot[id(t)])
            next_driver_slot[id(t)] += 1
            self._output_channels.append(EdgeTransport(
                reader, out_tier[id(t)], f"{_label(t)}->driver"))

        # per-actor exec specs
        specs: Dict[Any, Dict[str, Any]] = {}
        for aid, handle in handles.items():
            read_chs: Dict[str, EdgeTransport] = {}
            if consumes_input[aid]:
                rc = Channel(input_ch.name,
                             buffer_size=input_ch.buffer_size,
                             num_readers=input_ch.num_readers,
                             _create=False)
                rc.set_reader_slot(input_slot[aid])
                read_chs[input_ch.name] = EdgeTransport(
                    rc, input_tier, f"input->{_aid_label(aid)}")
            specs[aid] = {
                "read_channels": read_chs,
                "input_channel": input_ch.name,
                "tasks": [],
            }

        for n in method_nodes:
            aid = actor_of[id(n)]
            spec = specs[aid]

            def argspec(v):
                if isinstance(v, InputNode):
                    return ("input",)
                if isinstance(v, InputAttributeNode):
                    return ("input_attr", v.key)
                if isinstance(v, _TASK_NODES):
                    if actor_of[id(v)] == aid:
                        return ("local", node_idx[id(v)])
                    ch = out_channel[id(v)]
                    if ch.name not in spec["read_channels"]:
                        rc = Channel(ch.name, buffer_size=ch.buffer_size,
                                     num_readers=ch.num_readers, _create=False)
                        rc.set_reader_slot(out_slots[id(v)][aid])
                        spec["read_channels"][ch.name] = EdgeTransport(
                            rc, out_tier[id(v)],
                            f"{_label(v)}->{_aid_label(aid)}")
                    return ("chan", ch.name)
                if isinstance(v, DAGNode):
                    raise TypeError(f"unsupported DAG arg {type(v).__name__}")
                return ("const", v)

            ch = out_channel[id(n)]
            task = {
                "method": n.method_name,
                "args": [argspec(a) for a in n._bound_args],
                "kwargs": {k: argspec(v) for k, v in n._bound_kwargs.items()},
                "out_channel": None if ch is None else EdgeTransport(
                    ch, out_tier[id(n)], _label(n)),
                "local_idx": node_idx[id(n)],
            }
            if isinstance(n, CollectiveNode):
                task["collective"] = {"kind": n.group.op,
                                      "group": n.group.group_name}
            elif n.options.get("jit"):
                task["jit"] = True
            spec["tasks"].append(task)

        # in-mesh jit fusion: adjacent jit-marked tasks per actor become one
        # jax.jit program (device-resident intermediates, one dispatch)
        for spec in specs.values():
            spec["tasks"] = _fuse_jit_runs(spec["tasks"])

        self._exec_specs = specs  # introspection (tests, debugging)

        # join each collective group's actors (rank order = bind order)
        # BEFORE exec loops start: the first iteration may hit the op
        # immediately (reference: Communicator init in dag compilation)
        from ray_tpu.util.collective import collective as _coll

        for group in self._collective_groups:
            _coll.create_collective_group(
                [inp.actor for inp in group.inputs], group.world_size,
                backend=group.backend, group_name=group.group_name,
                timeout_s=getattr(group, "timeout_s", None))

        # start exec loops
        import ray_tpu

        start_refs = []
        for aid, handle in handles.items():
            payload = serialization.dumps(specs[aid])
            start_refs.append(handle._remote_call.remote(
                _start_exec_loop, self.dag_id, payload))
        ray_tpu.get(start_refs, timeout=self.submit_timeout)

    # -- liveness ----------------------------------------------------------
    def _check_actors_alive(self, min_interval_s: float = 0.5) -> None:
        """Raise ``ActorDiedError`` if any DAG actor's process is gone.

        Called from channel-read timeout slices: a killed actor leaves
        its output channels unwritten forever, so without this probe a
        deadline-less ``get()`` hangs and a deadlined one burns its
        whole budget to report a generic channel timeout.  Probes the
        GCS actor table, throttled to ``min_interval_s``; the verdict is
        sticky — once a member is dead the whole pipeline is poisoned
        (exec-loop iterations cannot be resumed mid-execution)."""
        if self._dead_actor_error is not None:
            raise self._dead_actor_error
        import time as _time

        now = _time.monotonic()
        if now - self._last_liveness_probe < min_interval_s:
            return
        self._last_liveness_probe = now
        from ray_tpu._private import worker as _worker_mod

        w = _worker_mod.global_worker
        if w is None:
            return
        for handle in self._actors:
            try:
                info = w.run_coro(w.gcs.call(
                    "get_actor_info", actor_id=handle._actor_id.binary()))
            except Exception:  # noqa: BLE001 — GCS hiccup: keep waiting
                continue
            if info is not None and info.get("state") == "DEAD":
                from ray_tpu.exceptions import ActorDiedError

                cause = info.get("death_cause") or "actor process died"
                self._dead_actor_error = ActorDiedError(
                    handle._actor_id,
                    f"compiled DAG actor {handle._class_name} "
                    f"({handle._actor_id.hex()[:12]}) died mid-execution "
                    f"({cause}); the DAG cannot make progress — call "
                    f"teardown() and recompile on live actors")
                raise self._dead_actor_error

    # -- introspection -----------------------------------------------------
    def _tier_summary(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for tier in self._edge_tiers.values():
            out[tier] = out.get(tier, 0) + 1
        return out

    def stats(self) -> Dict[str, Any]:
        """Channel-plane introspection: the per-edge negotiated transport
        (``channel_transport``) plus driver-side channel counters (the
        actor-side read waits land in the ``channel_wait`` step-ledger
        bucket and the exec loops' transport stats)."""
        chans: Dict[str, Dict[str, Any]] = {}
        for tr in [self._input_channel] + list(self._output_channels):
            if tr is not None:
                chans[tr.edge] = {"tier": tr.tier, **tr.stats}
        return {
            "channel_transport": dict(self._edge_tiers),
            "tiers": self._tier_summary(),
            "driver_channels": chans,
        }

    # -- execution ---------------------------------------------------------
    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("compiled DAG has been torn down")
        if self._dead_actor_error is not None:
            raise self._dead_actor_error
        from ray_tpu._private import tracing

        with self._submit_lock:
            with tracing.span("dag.execute", kind="dag",
                              attrs={"exec_idx": self._next_exec_idx,
                                     "channel_transport":
                                         self._tier_summary()}):
                # the channel write is the (possibly backpressured) submit
                # hop; node execution runs in the actors' standing loops,
                # whose collective/nested spans join via their own paths
                self._input_channel.write((args, kwargs),
                                          timeout=self.submit_timeout)
            ref = CompiledDAGRef(self, self._next_exec_idx)
            self._next_exec_idx += 1
            return ref

    async def execute_async(self, *args, **kwargs) -> CompiledDAGFuture:
        """Asyncio twin of ``execute()``: submits without blocking the
        event loop (the backpressured channel write runs on the default
        executor) and returns an awaitable ``CompiledDAGFuture``.
        Multiple executions may be in flight; an eager background drainer
        moves completed executions into the result buffer (so pipelined
        submits never deadlock on full output slots) and futures resolve
        out-of-order-safely (reference:
        ``compiled_dag_node.py:2633 execute_async``)."""
        import asyncio

        if self._torn_down:
            raise RuntimeError("compiled DAG has been torn down")
        loop = asyncio.get_event_loop()
        # drain BEFORE blocking on the input write: submits past the
        # pipeline depth only proceed as earlier executions retire.
        # Cross-coroutine/-loop submit ordering comes from the threading
        # _submit_lock inside the executor call (an asyncio.Lock here
        # would bind to one loop and break multi-loop callers).
        self._ensure_drainer()

        def _submit():
            with self._submit_lock:
                self._input_channel.write((args, kwargs),
                                          timeout=self.submit_timeout)
                idx = self._next_exec_idx
                self._next_exec_idx += 1
                return idx

        idx = await loop.run_in_executor(None, _submit)
        self._ensure_drainer()
        return CompiledDAGFuture(self, idx)

    def _ensure_drainer(self) -> None:
        """Start (or restart) the eager drain task on the current event
        loop.  One drainer runs at a time; it exits when every submitted
        execution has been drained into the buffer."""
        import asyncio

        if self._drain_task is None or self._drain_task.done():
            self._drain_error = None  # fresh drainer, fresh slate
            self._drain_task = asyncio.ensure_future(self._drain_loop())

    async def _drain_loop(self) -> None:
        import asyncio
        import time

        loop = asyncio.get_event_loop()
        while not self._torn_down:
            with self._get_lock:
                drained_all = self._next_get_idx >= self._next_exec_idx
            if drained_all:
                break

            def _drain_one():
                # bounded budget per round: the drainer must not camp on
                # _get_lock in a deadline-less read, or a concurrent sync
                # ref.get(timeout=...) could never honor its timeout
                with self._get_lock:
                    if self._next_get_idx >= self._next_exec_idx:
                        return
                    self._read_next_execution(time.monotonic() + 0.25)

            try:
                await loop.run_in_executor(None, _drain_one)
            except TimeoutError:  # partial drain; resume next round
                continue
            except Exception as e:  # noqa: BLE001 — closed channel /
                # buffer-cap RuntimeError: record it so waiters RAISE
                # instead of hanging on a silently-dead drainer
                self._drain_error = e
                break
            finally:
                self._pulse_waiters()
        self._pulse_waiters()

    def _pulse_waiters(self) -> None:
        """Wake every future waiting on any event loop (threadsafe)."""
        for lp, ev in list(self._result_waiters):
            try:
                lp.call_soon_threadsafe(ev.set)
            except RuntimeError:  # that loop is closed; its waiter is gone
                try:
                    self._result_waiters.remove((lp, ev))
                except ValueError:
                    pass

    def _read_next_execution(self, deadline) -> None:
        """Read one full execution's outputs (in pipeline order) into the
        result buffer.  Caller holds ``_get_lock``.  A timeout mid-way
        leaves the partially-drained values in ``_partial_values`` so the
        next attempt resumes from the first unread channel (each read
        consumes its ack slot — re-reading would desync the pipeline)."""
        import time

        if len(self._buffered_results) >= self.max_buffered_results:
            raise RuntimeError(
                f"{len(self._buffered_results)} executions are buffered "
                f"and unclaimed (max_buffered_results="
                f"{self.max_buffered_results}); get()/await results to "
                f"drain the pipeline")
        from ray_tpu.experimental.channel import ChannelTimeoutError

        while len(self._partial_values) < len(self._output_channels):
            ch = self._output_channels[len(self._partial_values)]
            # read in bounded slices with a liveness probe between them:
            # a killed exec-loop actor never writes its out-edge, and
            # without the probe a deadline-less get() waits forever (a
            # deadlined one burns the full budget on a generic channel
            # timeout instead of naming the dead actor)
            while True:
                budget = (None if deadline is None
                          else max(0.0, deadline - time.monotonic()))
                slice_budget = 0.25 if budget is None else min(0.25, budget)
                try:
                    value = ch.read(slice_budget)
                    break
                except ChannelTimeoutError:
                    self._check_actors_alive()
                    if budget is not None and \
                            time.monotonic() >= deadline:
                        raise
            self._partial_values.append(value)
        self._buffered_results[self._next_get_idx] = self._partial_values
        self._partial_values = []
        self._next_get_idx += 1

    def _deliver(self, values: List[Any]):
        err = next((v for v in values if isinstance(v, TaskError)), None)
        if err is not None:
            raise err
        if isinstance(self.root, MultiOutputNode):
            return values
        return values[0]

    def _get_result(self, ref: CompiledDAGRef, timeout: Optional[float]):
        import time

        if ref._has_result:
            raise ValueError("a CompiledDAGRef can only be gotten once")
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._get_lock:
            while ref._idx not in self._buffered_results:
                self._read_next_execution(deadline)
            ref._has_result = True
            values = self._buffered_results.pop(ref._idx)
        return self._deliver(values)

    async def _await_result(self, idx: int):
        """Resolve one execution's result for ``CompiledDAGFuture``: the
        eager drainer buffers executions as they retire; this waits for
        ``idx``'s values on an event pulsed after every drained
        execution (with a short timeout re-check as a safety net), so
        futures resolve in any order — including from different event
        loops."""
        import asyncio

        loop = asyncio.get_event_loop()
        ev = asyncio.Event()
        self._result_waiters.append((loop, ev))
        try:
            while True:
                with self._get_lock:
                    if idx in self._buffered_results:
                        values = self._buffered_results.pop(idx)
                        return self._deliver(values)
                if self._torn_down:
                    raise RuntimeError("compiled DAG has been torn down")
                if self._drain_error is not None:
                    raise self._drain_error
                self._ensure_drainer()
                ev.clear()
                try:
                    await asyncio.wait_for(ev.wait(), timeout=0.25)
                except asyncio.TimeoutError:
                    pass  # re-check the buffer (missed-pulse safety net)
        finally:
            try:
                self._result_waiters.remove((loop, ev))
            except ValueError:
                pass

    # -- teardown ----------------------------------------------------------
    def teardown(self, *, timeout: float = 10.0) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        import time

        import ray_tpu

        try:
            self._input_channel.write(_STOP, timeout=min(1.0, timeout))
        except Exception:
            pass
        # Close everything FIRST: un-gotten results leave exec loops blocked
        # writing to output channels that the driver will never read — close
        # unblocks them (ChannelClosedError exits the loop).
        for ch in self._all_channels:
            ch.close()
        deadline = time.monotonic() + timeout
        for handle in self._actors:
            while time.monotonic() < deadline:
                try:
                    st = ray_tpu.get(handle._remote_call.remote(
                        _exec_loop_status, self.dag_id), timeout=5)
                except Exception:
                    break
                if st["done"]:
                    break
                time.sleep(0.05)
        for group in self._collective_groups:

            def _destroy(_self, name):
                from ray_tpu.util.collective import collective as coll

                coll.destroy_collective_group(name)
                return True

            for inp in group.inputs:
                try:
                    ray_tpu.get(inp.actor._remote_call.remote(
                        _destroy, group.group_name), timeout=5)
                except Exception:  # noqa: BLE001 - actor may be gone
                    pass
        for ch in self._all_channels:
            ch.destroy()

    def __del__(self):
        try:
            if not self._torn_down:
                for ch in self._all_channels:
                    ch.destroy()  # close + unlink: no shm leak on GC
        except Exception:
            pass
