"""Cluster-wide internal key-value store (GCS-backed).

Parity: ``ray.experimental.internal_kv`` (``python/ray/experimental/
internal_kv.py``) — the store the reference's collective groups use for
rendezvous (``NCCLUniqueIDStore``, and GLOO's ``ray_internal_kv`` store at
``python/ray/util/collective/collective_group/gloo_util.py``).
"""

from __future__ import annotations

from typing import Dict, List, Optional


def _worker():
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        raise RuntimeError("ray_tpu.init() must be called first")
    return w


def _internal_kv_put(
    key: bytes, value: bytes, overwrite: bool = True, namespace: str = "kv"
) -> bool:
    w = _worker()
    return w.run_coro(
        w.gcs.call(
            "kv_put",
            ns=namespace,
            key=key.decode() if isinstance(key, bytes) else key,
            value=value,
            overwrite=overwrite,
        )
    )


def _internal_kv_get(key: bytes, namespace: str = "kv") -> Optional[bytes]:
    w = _worker()
    return w.run_coro(
        w.gcs.call(
            "kv_get",
            ns=namespace,
            key=key.decode() if isinstance(key, bytes) else key,
        )
    )


def _internal_kv_del(key: bytes, namespace: str = "kv") -> bool:
    w = _worker()
    return w.run_coro(
        w.gcs.call(
            "kv_del",
            ns=namespace,
            key=key.decode() if isinstance(key, bytes) else key,
        )
    )


def _internal_kv_list(prefix: str = "", namespace: str = "kv") -> List[str]:
    w = _worker()
    return w.run_coro(w.gcs.call("kv_keys", ns=namespace, prefix=prefix))


def _internal_kv_get_prefix(prefix: str = "",
                            namespace: str = "kv") -> Dict[str, bytes]:
    """Batched prefix read (key -> value) in one round trip."""
    w = _worker()
    return w.run_coro(w.gcs.call("kv_get_prefix", ns=namespace,
                                 prefix=prefix))


def _internal_kv_exists(key: bytes, namespace: str = "kv") -> bool:
    w = _worker()
    return w.run_coro(
        w.gcs.call(
            "kv_exists",
            ns=namespace,
            key=key.decode() if isinstance(key, bytes) else key,
        )
    )
