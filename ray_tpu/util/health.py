"""Health-plane primitives: robust outlier math, verdict records, and
the passive signal extractors the straggler detector scores.

A *degraded* chip is worse than a dead one: in a synchronous GSPMD mesh
one 3x-slow rank stalls every collective on every step, and nothing in
the failure plane (heartbeats, drain, fate-sharing) sees it — the rank
is alive, it is just late, forever.  This module owns the *pure* half of
the detection stack; the driving loop lives in
``ray_tpu._private.health_plane.HealthMonitor``:

1. **Robust statistics** — rolling median + MAD outlier test
   (:func:`robust_z`, :func:`mad_outliers`) with a
   :class:`HysteresisTracker` demanding N *consecutive* outlier windows
   before promotion, so one noisy window never trips the ladder and a
   clean cluster never false-positives.
2. **Passive signal extractors** — pure functions over ledgers the
   runtime already publishes: per-rank step breakdowns from the PR 9
   StepLedger (:func:`score_step_records` — the FAST ranks accumulate
   ``collective_wait`` blocking on the straggler; the rank with high
   *own time* and low collective wait is the one everybody waits for),
   flight-recorder pending ages from the collective status records
   (:func:`pending_age_lags`), and per-edge channel transfer latency
   (:func:`note_edge_latency` / :func:`edge_latency_snapshot`, fed by
   the channel plane's transports and shipped inside the StepLedger
   records).
3. **SDC canary** — :func:`sdc_digest`: a fixed-seed reference
   computation with a deterministic output digest; a digest mismatch on
   one device while the reference agrees means the chip is *corrupting
   data*, not merely slow (hardware-confirmed, final).
4. **Verdict records** — :class:`HealthVerdict` published to the GCS KV
   (namespace ``"health"``, key ``verdict/<kind>/<subject>``) so
   ``util.state.list_node_health`` / ``raytpu health`` / the dashboard
   ``/api/health`` panel render the same aggregation
   (:func:`aggregate_health_records`), with stale records swept like
   collective and SLO records.
5. **Device memory** — :func:`device_memory_stats`: per-device HBM
   occupancy (``memory_stats()`` where the backend exposes it), the
   health plane's memory-pressure input and the node panel's
   long-missing complement to host RSS.

Verdict ladder: ``HEALTHY -> SUSPECT -> QUARANTINED``.  Passive scoring
alone only reaches SUSPECT; QUARANTINED requires active confirmation
(probe or SDC canary) by the monitor.  Thresholds ride
``_private.config`` (``health_*`` knobs) — see docs/fault_tolerance.md.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

# verdict records older than this are swept from listings — the same
# observability window the SLO / collective records use
STALE_S = 600.0

_KV_NAMESPACE = "health"
_KV_PREFIX = "verdict/"

HEALTHY = "HEALTHY"
SUSPECT = "SUSPECT"
QUARANTINED = "QUARANTINED"

# |x - median| / (1.4826 * MAD) is ~ a z-score under normality; 1.4826
# is the consistency constant making MAD estimate sigma
_MAD_SIGMA = 1.4826
# MAD collapses to 0 on near-identical samples (every clean synthetic
# trace); below this scale we fall back to a noise floor of 5% of the
# median so a clean cluster scores ~0 instead of dividing by zero
_NOISE_FLOOR_FRAC = 0.05


# ---------------------------------------------------------------------------
# robust statistics
# ---------------------------------------------------------------------------


def median(values: Sequence[float]) -> float:
    s = sorted(values)
    n = len(s)
    if n == 0:
        raise ValueError("median of empty sequence")
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def mad(values: Sequence[float], med: Optional[float] = None) -> float:
    """Median absolute deviation — the robust spread estimator: up to
    half the samples can be arbitrarily bad without moving it, which is
    exactly the property a straggler detector needs (the straggler must
    not inflate the yardstick it is measured against)."""
    if med is None:
        med = median(values)
    return median([abs(v - med) for v in values])


def robust_z(values: Sequence[float]) -> List[float]:
    """Signed robust z-score per sample: ``(x - median) / (1.4826 *
    MAD)``, with a 5%-of-median noise floor on the scale so identical
    samples score 0.0 rather than dividing by zero."""
    if not values:
        return []
    med = median(values)
    scale = _MAD_SIGMA * mad(values, med)
    floor = _NOISE_FLOOR_FRAC * abs(med)
    scale = max(scale, floor, 1e-12)
    return [(v - med) / scale for v in values]


def mad_outliers(values: Sequence[float], threshold: float = 3.5,
                 *, one_sided: bool = True) -> List[int]:
    """Indices of outlier samples by the robust-z test.  ``one_sided``
    (the default) flags only the *slow* side — a rank that is unusually
    fast is not a health problem."""
    zs = robust_z(values)
    if one_sided:
        return [i for i, z in enumerate(zs) if z > threshold]
    return [i for i, z in enumerate(zs) if abs(z) > threshold]


class HysteresisTracker:
    """Promotion gate: a key must be an outlier in ``windows``
    *consecutive* observations before :meth:`observe` reports it.  Any
    clean window resets the streak — transient noise (GC pause, one
    slow host op) can never accumulate into a verdict.  Thread-safe;
    one instance per signal stream."""

    def __init__(self, windows: int):
        if windows < 1:
            raise ValueError(f"hysteresis windows must be >= 1, "
                             f"got {windows}")
        self.windows = int(windows)
        self._lock = threading.Lock()
        self._streaks: Dict[Any, int] = {}

    def observe(self, outliers: Sequence[Any],
                population: Sequence[Any]) -> List[Any]:
        """Record one observation window.  ``outliers`` are the keys
        flagged this window, ``population`` every key observed (keys in
        the population but not flagged have their streak reset; keys
        absent from the population keep their streak — a rank that
        published no record is unknown, not clean).  Returns the keys
        whose streak just reached the promotion threshold."""
        flagged = set(outliers)
        promoted = []
        with self._lock:
            for key in population:
                if key in flagged:
                    self._streaks[key] = self._streaks.get(key, 0) + 1
                    if self._streaks[key] == self.windows:
                        promoted.append(key)
                else:
                    self._streaks.pop(key, None)
        return promoted

    def streak(self, key: Any) -> int:
        with self._lock:
            return self._streaks.get(key, 0)

    def reset(self, key: Any = None) -> None:
        with self._lock:
            if key is None:
                self._streaks.clear()
            else:
                self._streaks.pop(key, None)


# ---------------------------------------------------------------------------
# passive signal extractors
# ---------------------------------------------------------------------------


def score_step_records(records: Sequence[Dict[str, Any]],
                       *, mad_threshold: float = 3.5) -> Dict[str, Any]:
    """Score one collective group's per-rank StepLedger records for a
    straggler.

    The signature of a degraded rank in a synchronous mesh is an
    *asymmetry*: every healthy rank finishes its shard early and parks
    in the collective (``collective_wait`` grows), while the straggler
    arrives last and sails straight through (near-zero wait).  So the
    scored statistic is **own time** — step wall minus collective wait —
    and a suspect must be a slow-side own-time outlier whose collective
    wait is *below* the group median (the corroboration that everyone
    is waiting for *it*).

    Returns ``{"ranks": {rank: {own_s, wall_s, collective_wait_s, z}},
    "suspects": [rank, ...]}``.  Fewer than 3 ranks cannot support a
    median/MAD verdict and yield no suspects.
    """
    per_rank: Dict[int, Dict[str, float]] = {}
    for rec in records:
        try:
            rank = int(rec["rank"])
            # prefer the recent-window breakdown (fresh signal) over
            # the run-lifetime mean; fall back when the window is empty
            recent = rec.get("recent") or {}
            src = recent if recent.get("steps") else rec
            # the recent window publishes "wall_s_per_step"; the
            # lifetime breakdown block publishes "step_wall_s"
            wall = float(src["wall_s_per_step"]
                         if "wall_s_per_step" in src
                         else src["step_wall_s"])
            buckets = src.get("buckets_s") or {}
            coll = float(buckets.get("collective_wait", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        per_rank[rank] = {
            "wall_s": wall,
            "collective_wait_s": coll,
            "own_s": max(0.0, wall - coll),
        }
    ranks = sorted(per_rank)
    out: Dict[str, Any] = {"ranks": per_rank, "suspects": []}
    if len(ranks) < 3:
        return out
    own = [per_rank[r]["own_s"] for r in ranks]
    waits = [per_rank[r]["collective_wait_s"] for r in ranks]
    zs = robust_z(own)
    wait_med = median(waits)
    for i, r in enumerate(ranks):
        per_rank[r]["z"] = round(zs[i], 3)
        if zs[i] > mad_threshold and \
                per_rank[r]["collective_wait_s"] <= wait_med:
            out["suspects"].append(r)
    return out


def pending_age_lags(status_records: Sequence[Dict[str, Any]],
                     *, now: Optional[float] = None) -> Dict[int, float]:
    """Per-rank in-flight collective-op age, from the supervision status
    records (flight-recorder face): rank -> seconds its current op has
    been pending.  A rank whose peers all completed seq N while it still
    shows N in flight is the lagging rank the watchdog would eventually
    name — the health plane reads the same signal pre-timeout."""
    now = time.time() if now is None else now
    ages: Dict[int, float] = {}
    for rec in status_records:
        inflight = rec.get("inflight") or {}
        t0 = inflight.get("t_start")
        if t0 is None:
            continue
        try:
            ages[int(rec["rank"])] = max(0.0, now - float(t0))
        except (KeyError, TypeError, ValueError):
            continue
    return ages


# ---------------------------------------------------------------------------
# per-edge channel latency (process-local tracker)
# ---------------------------------------------------------------------------

_edge_lock = threading.Lock()
_edge_stats: Dict[str, Dict[str, float]] = {}
_EDGE_EWMA_ALPHA = 0.3


def note_edge_latency(edge: str, seconds: float) -> None:
    """Record one channel transfer on ``edge`` (an ``a->b`` transport
    identity).  Called by the channel plane next to its ``channel_wait``
    tracing note; EWMA + count per edge, cheap enough for every read."""
    with _edge_lock:
        st = _edge_stats.get(edge)
        if st is None:
            _edge_stats[edge] = {"ewma_s": seconds, "last_s": seconds,
                                 "count": 1}
        else:
            st["ewma_s"] += _EDGE_EWMA_ALPHA * (seconds - st["ewma_s"])
            st["last_s"] = seconds
            st["count"] += 1


def edge_latency_snapshot() -> Dict[str, Dict[str, float]]:
    """Copy of the per-edge latency table — shipped inside StepLedger
    records so the monitor can MAD-test edges cluster-wide."""
    with _edge_lock:
        return {e: dict(st) for e, st in _edge_stats.items()}


def reset_edge_latency() -> None:
    with _edge_lock:
        _edge_stats.clear()


# ---------------------------------------------------------------------------
# SDC canary
# ---------------------------------------------------------------------------


def sdc_digest(seed: int = 0, n: int = 32, iters: int = 4) -> str:
    """Deterministic reference-step digest: a fixed-seed matmul chain
    whose output bytes are hashed.  Integer arithmetic end to end —
    float matmuls reduce in backend-dependent orders, so a float canary
    would flag *reduction order* as corruption; int64 modular arithmetic
    is bit-exact on every backend.  Two honest executions of this
    function agree everywhere, forever; a mismatch means the executing
    hardware corrupted data (SDC), which is final — a corrupting chip is
    not quarantined pending review, it is reported as failed."""
    import numpy as np

    rng = np.random.default_rng(seed)
    m = rng.integers(0, 97, size=(n, n), dtype=np.int64)
    x = rng.integers(0, 97, size=(n, n), dtype=np.int64)
    for _ in range(iters):
        x = (m @ x) % 1_000_003
    return hashlib.sha256(x.tobytes()).hexdigest()


# ---------------------------------------------------------------------------
# device memory (HBM occupancy)
# ---------------------------------------------------------------------------


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-device memory occupancy for this process's accelerators.

    Uses ``jax.local_devices()[i].memory_stats()`` where the backend
    exposes it (PJRT TPU/GPU; ``bytes_in_use`` / ``bytes_limit``).  Only
    consulted when jax is *already imported* in this process — a raylet
    or CPU-only worker must never pay (or trigger) backend init just to
    report stats.  Returns ``[]`` when there is nothing to report, and
    rows shaped ``{"device", "kind", "bytes_in_use", "bytes_limit",
    "occupancy"}`` otherwise."""
    import sys

    if "jax" not in sys.modules:
        return []
    try:
        import jax
        from jax._src import xla_bridge

        # merely IMPORTED is not enough: jax.local_devices() on a
        # backend-less process would initialize one — which costs
        # seconds, and permanently breaks a later
        # jax.distributed.initialize() in that worker
        if not getattr(xla_bridge, "_backends", None):
            return []
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — backend not initialized / dead
        return []
    out: List[Dict[str, Any]] = []
    for d in devices:
        row: Dict[str, Any] = {"device": str(d),
                               "kind": getattr(d, "platform", "")}
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — interface node / cpu backend
            stats = None
        if stats:
            in_use = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit")
            row["bytes_in_use"] = in_use
            row["bytes_limit"] = limit
            if in_use is not None and limit:
                row["occupancy"] = round(in_use / limit, 4)
        out.append(row)
    return out


# ---------------------------------------------------------------------------
# verdict records: publish / aggregate
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class HealthVerdict:
    """One subject's position on the health ladder.

    ``kind`` is ``"node"`` or ``"rank"``; ``subject`` is the node id or
    ``<group>/<rank>``.  ``signals`` carries the evidence (robust z,
    collective-wait asymmetry, probe timings, canary digests) so a
    quarantine record is *readable* — the operator sees why, not just
    what.  ``hw_confirmed`` marks SDC/probe-proven hardware faults:
    those route to ``report_node_failure`` and the node's death is
    final (never resurrected by a late heartbeat)."""

    kind: str
    subject: str
    health: str                        # HEALTHY | SUSPECT | QUARANTINED
    reason: str = ""
    node_id: str = ""
    group: str = ""
    rank: Optional[int] = None
    signals: Dict[str, Any] = dataclasses.field(default_factory=dict)
    hw_confirmed: bool = False
    suspect_ts: Optional[float] = None
    quarantine_ts: Optional[float] = None
    ts: float = dataclasses.field(default_factory=time.time)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def publish_health_verdict(verdict: HealthVerdict) -> bool:
    """Write one verdict record into the GCS KV (namespace ``"health"``,
    key ``verdict/<kind>/<subject>``).  Best-effort: health *surfacing*
    must never fail the monitor that produced the verdict — actuation
    (quarantine) goes through its own GCS verb, not this record."""
    try:
        import ray_tpu

        if not ray_tpu.is_initialized():
            return False
        from ray_tpu.experimental import internal_kv

        key = f"{_KV_PREFIX}{verdict.kind}/{verdict.subject}"
        internal_kv._internal_kv_put(
            key.encode(), json.dumps(verdict.to_dict()).encode(),
            namespace=_KV_NAMESPACE)
        return True
    except Exception:  # noqa: BLE001 — visibility stays best-effort
        return False


def aggregate_health_records(records: List[Dict[str, Any]],
                             *, now: Optional[float] = None
                             ) -> List[Dict[str, Any]]:
    """Order raw health verdict records for display and sweep stale ones
    (older than :data:`STALE_S`): a monitor that died mid-run must not
    pin its last verdict in every listing forever.  Worst health first
    (QUARANTINED > SUSPECT > HEALTHY), then by subject — the same
    aggregate-records pattern the collective and SLO panels use."""
    now = time.time() if now is None else now
    rank_of = {QUARANTINED: 0, SUSPECT: 1, HEALTHY: 2}
    out = []
    for rec in records:
        ts = rec.get("ts")
        if ts is not None and now - ts > STALE_S:
            continue
        out.append(rec)
    out.sort(key=lambda r: (rank_of.get(r.get("health"), 3),
                            r.get("kind", ""), str(r.get("subject", ""))))
    return out
