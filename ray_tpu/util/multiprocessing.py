"""Drop-in ``multiprocessing.Pool`` over ray_tpu tasks.

Reference: ``python/ray/util/multiprocessing/pool.py`` — same API surface
(map/starmap/imap/imap_unordered/apply/apply_async/close/join), tasks run
across the cluster instead of forked locals.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    def __init__(self, refs, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        try:
            ray_tpu.get(self._refs)
            return True
        except Exception:
            return False


@ray_tpu.remote
def _run_callable(fn, args, kwargs):
    return fn(*args, **kwargs)


@ray_tpu.remote
def _run_chunk(fn, chunk, star: bool):
    return [fn(*item) if star else fn(item) for item in chunk]


class Pool:
    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._processes = processes or 8
        self._closed = False
        # initializer semantics differ (no dedicated pool processes); run it
        # inside each chunk-task via a wrapper when provided
        self._initializer = initializer
        self._initargs = initargs

    def _check(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _wrap(self, fn):
        if self._initializer is None:
            return fn
        init, initargs = self._initializer, self._initargs

        def wrapped(*a, **kw):
            init(*initargs)
            return fn(*a, **kw)

        return wrapped

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)], len(items)

    # -- map family ---------------------------------------------------------

    def map(self, fn, iterable: Iterable, chunksize: Optional[int] = None
            ) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        self._check()
        chunks, _ = self._chunks(iterable, chunksize)
        fn = self._wrap(fn)
        refs = [_run_chunk.remote(fn, c, False) for c in chunks]
        return _ChunkedResult(refs)

    def starmap(self, fn, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        self._check()
        chunks, _ = self._chunks(iterable, chunksize)
        fn = self._wrap(fn)
        refs = [_run_chunk.remote(fn, c, True) for c in chunks]
        return _ChunkedResult(refs).get()

    def imap(self, fn, iterable: Iterable, chunksize: int = 1):
        self._check()
        fn = self._wrap(fn)
        chunks, _ = self._chunks(iterable, chunksize)
        refs = [_run_chunk.remote(fn, c, False) for c in chunks]
        for ref in refs:  # submission order
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn, iterable: Iterable, chunksize: int = 1):
        self._check()
        fn = self._wrap(fn)
        chunks, _ = self._chunks(iterable, chunksize)
        pending = {_run_chunk.remote(fn, c, False) for c in chunks}
        while pending:
            done, pending_list = ray_tpu.wait(list(pending), num_returns=1)
            pending = set(pending_list)
            for ref in done:
                yield from ray_tpu.get(ref)

    # -- apply family -------------------------------------------------------

    def apply(self, fn, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args: tuple = (),
                    kwds: Optional[dict] = None) -> AsyncResult:
        self._check()
        ref = _run_callable.remote(self._wrap(fn), args, kwds or {})
        return AsyncResult([ref], single=True)

    # -- lifecycle ----------------------------------------------------------

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()


class _ChunkedResult(AsyncResult):
    def __init__(self, refs):
        super().__init__(refs, single=False)

    def get(self, timeout: Optional[float] = None):
        chunks = ray_tpu.get(self._refs, timeout=timeout)
        return [x for c in chunks for x in c]
