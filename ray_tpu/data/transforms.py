"""Block transform functions executed inside tasks/actors.

The unit shipped to a worker is a ``MapChain``: the (possibly fused) sequence
of row/batch transforms one task applies to one input block.  Output blocks
are ``put()`` into the object store from the worker and only their refs +
metadata travel back, so the driver never touches block data.

Reference: ``python/ray/data/_internal/execution/operators/map_transformer.py``
(MapTransformer and its Row/Batch transform fns).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import (
    BlockAccessor,
    BlockBuilder,
    BlockMetadata,
    batch_to_block,
    concat_blocks,
)


@dataclass
class MapStep:
    kind: str  # "batches" | "rows" | "flat" | "filter"
    fn: Any  # function, or a class to instantiate (stateful callable)
    fn_args: tuple = ()
    fn_kwargs: dict = field(default_factory=dict)
    batch_size: Optional[int] = None
    batch_format: str = "numpy"


@dataclass
class MapChain:
    steps: List[MapStep]
    target_max_block_size: int = 128 * 1024 * 1024


def _resolve_fn(step: MapStep, cache: Optional[Dict[int, Any]] = None) -> Callable:
    """Instantiate callable classes (once per actor when a cache is given)."""
    fn = step.fn
    if isinstance(fn, type):
        key = id(fn)
        if cache is not None and key in cache:
            return cache[key]
        inst = fn(*step.fn_args, **step.fn_kwargs)
        if cache is not None:
            cache[key] = inst
        return inst
    return fn


def _iter_batches(block: pa.Table, batch_size: Optional[int],
                  batch_format: str) -> Iterator[Any]:
    acc = BlockAccessor(block)
    if batch_size is None or batch_size >= block.num_rows:
        if block.num_rows:
            yield acc.to_batch(batch_format)
        return
    for start in range(0, block.num_rows, batch_size):
        yield BlockAccessor(acc.slice(start, min(start + batch_size,
                                                 block.num_rows))).to_batch(batch_format)


def apply_chain(blocks: List[pa.Table], chain: MapChain,
                fn_cache: Optional[Dict[int, Any]] = None) -> Iterator[pa.Table]:
    """Apply every step to the input blocks, yielding output blocks split at
    the target block size."""
    tables = blocks
    for step in chain.steps:
        fn = _resolve_fn(step, fn_cache)
        out = BlockBuilder(chain.target_max_block_size)
        produced: List[pa.Table] = []
        for block in tables:
            if step.kind == "batches":
                for batch in _iter_batches(block, step.batch_size, step.batch_format):
                    args, kwargs = ((), {}) if isinstance(step.fn, type) else (
                        step.fn_args, step.fn_kwargs)
                    res = fn(batch, *args, **kwargs)
                    if res is None:
                        continue
                    out.add_batch(res)
                    if out.should_flush():
                        produced.append(out.build())
            elif step.kind == "rows":
                for row in BlockAccessor(block).iter_rows():
                    out.add_row(fn(row))
            elif step.kind == "flat":
                for row in BlockAccessor(block).iter_rows():
                    for r in fn(row):
                        out.add_row(r)
            elif step.kind == "filter":
                for row in BlockAccessor(block).iter_rows():
                    if fn(row):
                        out.add_row(row)
            else:
                raise ValueError(f"unknown map kind {step.kind!r}")
        if out.num_rows() or not produced:
            produced.append(out.build())
        tables = produced
    yield from tables


def _finalize(blocks: Iterator[pa.Table], t0: float,
              input_files: Optional[List[str]] = None):
    """Put output blocks, return ([ref...], [meta...]) — the small task reply."""
    refs, metas = [], []
    for b in blocks:
        refs.append(ray_tpu.put(b))
        metas.append(BlockMetadata.for_block(b, input_files=input_files,
                                             start_time=t0))
    return refs, metas


@ray_tpu.remote
def run_map_task(chain: MapChain, *blocks: pa.Table):
    """Task-pool map: apply the chain to the input blocks."""
    t0 = time.perf_counter()
    return _finalize(apply_chain(list(blocks), chain), t0)


@ray_tpu.remote
def run_read_task(read_task, chain: Optional[MapChain]):
    """Execute a datasource ReadTask (+ optionally a fused downstream chain)."""
    t0 = time.perf_counter()
    blocks = list(read_task())
    if chain is not None and chain.steps:
        blocks = apply_chain(blocks, chain)
    return _finalize(blocks, t0, input_files=read_task.metadata.input_files)


@ray_tpu.remote(num_returns="streaming")
def run_read_task_streaming(read_task):
    """Streaming read: each produced block is announced to the consumer the
    moment it exists instead of after the whole ReadTask finishes
    (reference: Data's map tasks are built on streaming generators,
    ``_raylet.pyx:279``).  Yields ``(block_ref, metadata)`` per block."""
    t0 = time.perf_counter()
    for b in read_task():
        yield (ray_tpu.put(b),
               BlockMetadata.for_block(
                   b, input_files=read_task.metadata.input_files,
                   start_time=t0))


@ray_tpu.remote
class MapWorker:
    """Actor-pool map worker: caches stateful callables across calls.

    Reference: ``_MapWorker`` in
    ``python/ray/data/_internal/execution/operators/actor_pool_map_operator.py``.
    """

    def __init__(self):
        self._fn_cache: Dict[int, Any] = {}

    def ready(self) -> bool:
        return True

    def run(self, chain: MapChain, *blocks: pa.Table):
        t0 = time.perf_counter()
        return _finalize(apply_chain(list(blocks), chain, self._fn_cache), t0)


# -- shuffle-family task fns -------------------------------------------------


@ray_tpu.remote
def split_block(block: pa.Table, num_splits: int, seed_or_none):
    """Map side of random_shuffle/repartition(shuffle=True): permute rows and
    deal them into ``num_splits`` parts."""
    t0 = time.perf_counter()
    acc = BlockAccessor(block)
    n = block.num_rows
    rng = np.random.default_rng(seed_or_none)
    parts = np.array_split(rng.permutation(n), num_splits)
    return _finalize((acc.take_rows(p) for p in parts), t0)


@ray_tpu.remote
def merge_blocks(*blocks: pa.Table):
    """Reduce side: concatenate parts into one output block."""
    t0 = time.perf_counter()
    return _finalize(iter([concat_blocks(list(blocks))]), t0)


@ray_tpu.remote
def slice_block(block: pa.Table, start: int, end: int):
    t0 = time.perf_counter()
    return _finalize(iter([BlockAccessor(block).slice(start, end)]), t0)


@ray_tpu.remote
def sample_boundaries(block: pa.Table, key: str, n_samples: int):
    acc = BlockAccessor(block)
    sampled = acc.sample(min(n_samples, block.num_rows))
    return sampled.column(key).to_pylist() if sampled.num_rows else []


@ray_tpu.remote
def range_partition_block(block: pa.Table, key: str, boundaries: List[Any],
                          descending: bool):
    """Sort a block locally then split at the given key boundaries."""
    t0 = time.perf_counter()
    order = "descending" if descending else "ascending"
    block = block.sort_by([(key, order)])
    col = block.column(key).to_numpy(zero_copy_only=False)
    if descending:
        idx = len(col) - np.searchsorted(col[::-1], boundaries, side="left")
    else:
        idx = np.searchsorted(col, boundaries, side="left")
    parts = []
    prev = 0
    for i in list(idx) + [block.num_rows]:
        i = int(max(prev, i))
        parts.append(block.slice(prev, i - prev))
        prev = i
    return _finalize(iter(parts), t0)


@ray_tpu.remote
def merge_sorted_blocks(key: str, descending: bool, *blocks: pa.Table):
    t0 = time.perf_counter()
    merged = concat_blocks(list(blocks))
    if merged.num_rows:
        merged = merged.sort_by([(key, "descending" if descending else "ascending")])
    return _finalize(iter([merged]), t0)


@ray_tpu.remote
def hash_partition_block(block: pa.Table, key: str, num_partitions: int):
    """Map side of groupby: deal rows into partitions by key hash."""
    t0 = time.perf_counter()
    if block.num_rows == 0:
        return _finalize(iter([block] * num_partitions), t0)
    col = block.column(key).to_numpy(zero_copy_only=False)
    hashes = np.array([hash(v) % num_partitions for v in col.tolist()])
    acc = BlockAccessor(block)
    parts = [acc.take_rows(np.nonzero(hashes == p)[0])
             for p in range(num_partitions)]
    return _finalize(iter(parts), t0)


@ray_tpu.remote
def aggregate_partition(key: Optional[str], agg_specs: List[Tuple[str, str, str]],
                        *blocks: pa.Table):
    """Reduce side of groupby: arrow group_by aggregate on one partition.

    agg_specs: (column, arrow_fn, output_name).
    """
    t0 = time.perf_counter()
    merged = concat_blocks(list(blocks))
    if merged.num_rows == 0:
        return _finalize(iter([merged]), t0)
    if key is None:
        import pyarrow.compute as pc

        out: Dict[str, Any] = {}
        for col, fn, name in agg_specs:
            if fn == "count":
                out[name] = [merged.num_rows]
            else:
                out[name] = [getattr(pc, fn)(merged.column(col)).as_py()]
        return _finalize(iter([pa.table(out)]), t0)
    aggs = [(col if col else key, fn) for col, fn, _ in agg_specs]
    res = merged.group_by(key).aggregate(aggs)
    # arrow names outputs "<col>_<fn>"; rename to requested names
    rename = {f"{col if col else key}_{fn}": name for col, fn, name in agg_specs}
    res = res.rename_columns([rename.get(c, c) for c in res.column_names])
    return _finalize(iter([res]), t0)


def _concat_keep_schema(blocks: List[pa.Table]) -> pa.Table:
    """concat that keeps the schema even when every part is empty (an
    all-empty hash partition must still join correctly)."""
    nonempty = [b for b in blocks if b.num_rows]
    if nonempty:
        return concat_blocks(nonempty)
    return blocks[0].schema.empty_table() if blocks else pa.table({})


@ray_tpu.remote
def join_partition(on, how: str, left_count: int, *blocks: pa.Table):
    """Join one hash partition: blocks[:left_count] are the left side."""
    t0 = time.perf_counter()
    left = _concat_keep_schema(list(blocks[:left_count]))
    right = _concat_keep_schema(list(blocks[left_count:]))
    keys = [on] if isinstance(on, str) else list(on)
    if not left.schema.names or not right.schema.names:
        # A side with ZERO blocks globally (its schema is unknowable), so
        # every partition takes this branch — the output schema stays
        # consistent across partitions: inner -> empty; any outer -> the
        # populated side's rows/columns (there are no columns to null-fill
        # from a side that never existed).
        if how == "inner":
            out = pa.table({})
        else:
            out = left if left.schema.names else right
        return _finalize(iter([out]), t0)
    joined = left.join(right, keys=keys, join_type=how)
    return _finalize(iter([joined]), t0)


@ray_tpu.remote
def zip_blocks(left: pa.Table, right: pa.Table):
    t0 = time.perf_counter()
    assert left.num_rows == right.num_rows, (left.num_rows, right.num_rows)
    cols = {name: left.column(name) for name in left.column_names}
    for name in right.column_names:
        out_name = name if name not in cols else f"{name}_1"
        cols[out_name] = right.column(name)
    return _finalize(iter([pa.table(cols)]), t0)
