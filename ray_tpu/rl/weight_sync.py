"""Versioned live weight-sync: learner → generation actors, no cold restart.

The RLHF loop (``ray_tpu/rl/rlhf.py``) needs fresh learner weights on its
rollout/generation actors every iteration *while those actors keep
serving* — the continual-learning weight path real production RL systems
need.  This module is that path, hardened:

- **Monotonic versions.** Every publish carries a :class:`WeightVersion`
  ``(version, epoch)``: ``version`` is globally monotonic across the
  publisher's whole lifetime *including elastic restarts* (a resumed
  publisher reads the durable KV record and continues above it, bumping
  ``epoch``), so a consumer can assert non-decreasing versions no matter
  how many times the learner was preempted.

- **Torn publishes are never observed.**  A publish is three legs:
  payload into the object store (immutable, atomic), then the
  ``rl.weight_sync.publish`` fault site, then the *commit* — one KV write
  of the latest-record.  A publisher that dies (or faults) between
  payload and commit leaves only an orphan object; no subscriber can
  observe the half-published version because discovery goes through the
  commit record alone.  The payload additionally carries a digest over
  every leaf, validated before the consumer swap — a corrupt or mixed
  tree is rejected and counted, never served.

- **Atomic consumer swap.**  :meth:`WeightSubscriber.current` returns
  ``(params, WeightVersion)`` snapshotted under one lock, and the swap
  installs the whole validated tree in a single reference assignment —
  a replica never serves params from two versions at once.

- **Compiled-graph channel fast path, object-store fallback.**  The
  publisher can attach a compiled-graph shm channel
  (:class:`~ray_tpu.experimental.channel.Channel`); commits ride it with
  a bounded write (small payloads inline, large ones as the commit
  record).  A dead/slow reader times the write out → the channel is
  retired and publication continues on the always-written KV +
  object-store path.  A respawned subscriber needs no channel at all:
  it rejoins at the current version from the durable record
  (resubscribe-on-restart).

- **Bounded staleness backpressure.**  Subscribers count samples served
  per version; past ``staleness_bound`` without a newer publish, the
  :meth:`WeightSubscriber.gate` blocks (bounded) until the learner
  catches up — rollout cannot run away producing stale trajectories
  when the learner falls behind — and raises :class:`WeightsStaleError`
  if the learner stays silent past the deadline.
"""

from __future__ import annotations

import hashlib
import logging
import pickle
import threading
import time
from dataclasses import dataclass
from functools import total_ordering
from typing import Any, Dict, Optional, Tuple

from ray_tpu.util import fault_injection

logger = logging.getLogger(__name__)

_NAMESPACE = "rl_weights"


class WeightSyncError(RuntimeError):
    """Base for weight-sync failures."""


class WeightsStaleError(WeightSyncError):
    """The staleness gate timed out: the learner has not published within
    the bound while rollout kept sampling — backpressure gave up."""


class NoWeightsPublishedError(WeightSyncError):
    """A subscriber asked for weights before any publish committed."""


@total_ordering
@dataclass(frozen=True)
class WeightVersion:
    """Monotonic weight identity.  ``version`` is globally monotonic
    (never reused, survives publisher restarts); ``epoch`` counts
    publisher incarnations and exists for diagnostics."""

    version: int
    epoch: int = 0

    def __lt__(self, other: "WeightVersion") -> bool:
        return self.version < other.version

    def __int__(self) -> int:
        return self.version


def params_digest(params: Any, version: int, epoch: int) -> str:
    """Digest over every leaf's bytes + the version identity.  A payload
    whose tree was torn, truncated, or mixed across versions cannot
    reproduce it."""
    import jax
    import numpy as np

    h = hashlib.sha256(f"{version}:{epoch}".encode())
    leaves, treedef = jax.tree.flatten(params)
    h.update(repr(treedef).encode())
    for leaf in leaves:
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def _latest_key(name: str) -> bytes:
    return f"{name}/latest".encode()


def _read_latest_record(name: str) -> Optional[Dict[str, Any]]:
    """The durable commit record, or None when nothing has been
    published under ``name``.  This is the ONLY discovery path — a
    publish is visible iff this record points at it."""
    from ray_tpu.experimental import internal_kv

    raw = internal_kv._internal_kv_get(_latest_key(name),
                                       namespace=_NAMESPACE)
    if raw is None:
        return None
    return pickle.loads(raw)


class WeightPublisher:
    """Learner-side: assign versions, publish payloads, commit atomically.

    Payloads go to the object store (one immutable put per version; the
    publisher pins the last ``keep`` refs so an in-flight fetch of the
    previous version cannot lose the object mid-swap).  The commit is a
    single KV write of the latest-record.  An attached compiled-graph
    channel is a latency optimization only — every commit is durable in
    KV first, so losing the channel loses nothing but latency.
    """

    def __init__(self, name: str, *, keep: int = 2,
                 channel_write_timeout_s: float = 2.0,
                 resume: bool = True):
        self.name = name
        self.keep = max(1, keep)
        self.channel_write_timeout_s = channel_write_timeout_s
        self._pinned: Dict[int, Any] = {}  # version -> ObjectRef (alive)
        self._channel = None
        self._lock = threading.Lock()
        self.stats = {"publishes": 0, "publish_failures": 0,
                      "channel_commits": 0, "channel_retired": 0}
        self._epoch = 0
        self._version = 0  # last committed version
        if resume:
            rec = _read_latest_record(name)
            if rec is not None:
                self._version = int(rec["version"])
                self._epoch = int(rec["epoch"]) + 1

    # -- channel fast path -------------------------------------------------
    def rotate_channel(self, num_readers: int,
                       *, buffer_size: int = 1 << 20) -> Dict[str, Any]:
        """(Re)create the shm commit channel for ``num_readers``
        subscribers and return the attach info ``{"name", "num_readers",
        "buffer_size", "tier"}``.  Called whenever group membership
        changes (a respawned actor cannot inherit a dead reader's ack
        slot).  The channel rides the negotiated transport plane: params
        pytrees of jax arrays ship as device frames (zero-copy serialize
        into the segment; subscribers land them with an alias-guarded
        ``device_put`` from the shm view), anything else takes the
        zero-copy host encoding — the compiled-graph channel work for
        free."""
        from ray_tpu.experimental.channel.transport import (
            TIER_DEVICE,
            make_edge_transport,
        )

        self.retire_channel()
        if num_readers <= 0:
            return {}
        tr = make_edge_transport(
            tier=TIER_DEVICE, edge=f"weights:{self.name}",
            buffer_size=buffer_size, num_readers=num_readers)
        with self._lock:
            self._channel = tr
        return {"name": tr.channel.name, "num_readers": num_readers,
                "buffer_size": buffer_size, "tier": tr.tier}

    def retire_channel(self) -> None:
        with self._lock:
            ch, self._channel = self._channel, None
        if ch is not None:
            try:
                ch.destroy()
            except Exception:  # noqa: BLE001 — shm already unlinked is fine
                pass

    # -- publish -----------------------------------------------------------
    @property
    def latest_version(self) -> Optional[WeightVersion]:
        if self._version <= 0:
            return None
        return WeightVersion(self._version, self._epoch)

    def publish(self, params: Any, *, meta: Optional[Dict[str, Any]] = None
                ) -> WeightVersion:
        """Publish one version.  Raises without bumping the committed
        version if any leg fails — a retry re-publishes the SAME version
        number (idempotent), so an injected fault between payload and
        commit can never skip or tear a version."""
        import ray_tpu

        from ray_tpu._private import tracing

        t_pub0 = time.perf_counter()
        version = self._version + 1
        epoch = self._epoch
        digest = params_digest(params, version, epoch)
        payload = {"version": version, "epoch": epoch, "digest": digest,
                   "params": params, "meta": dict(meta or {})}
        try:
            ref = ray_tpu.put(payload)
            record = {"version": version, "epoch": epoch, "digest": digest,
                      "ref": pickle.dumps(ref), "published_at": time.time()}
            # the torn-publish seam: payload exists, commit has not
            # happened — a fault here must leave the version unobservable
            fault_injection.fault_point("rl.weight_sync.publish")
            from ray_tpu.experimental import internal_kv

            internal_kv._internal_kv_put(
                _latest_key(self.name), pickle.dumps(record),
                namespace=_NAMESPACE)
        except BaseException:
            self.stats["publish_failures"] += 1
            raise
        # committed: expose the version, pin the payload, drop old pins
        self._version = version
        self._pinned[version] = ref
        for v in sorted(self._pinned):
            if len(self._pinned) <= self.keep:
                break
            del self._pinned[v]
        self.stats["publishes"] += 1
        self._channel_notify(payload, record)
        # attribution: publish wall time is the step ledger's
        # weight_publish bucket, and a span in the caller's trace
        dt = time.perf_counter() - t_pub0
        tracing.note_duration("weight_publish", dt)
        if tracing.is_enabled():
            now = time.time()
            tracing.record_span(
                "weights.publish", now - dt, now,
                tracing.current_or_root().child(), kind="weight_publish",
                attrs={"name": self.name, "version": version,
                       "epoch": epoch})
        return WeightVersion(version, epoch)

    def _channel_notify(self, payload: Dict[str, Any],
                        record: Dict[str, Any]) -> None:
        """Best-effort fast-path commit broadcast.  Inline the full
        payload when it fits the channel buffer (the transport raises
        ``ValueError`` on oversize, measuring the bytes actually
        written); otherwise send the commit record (subscribers fetch
        from the object store).  A write timeout means a reader died or
        wedged: retire the channel — the KV commit already happened,
        nothing is lost."""
        with self._lock:
            ch = self._channel
        if ch is None:
            return
        try:
            try:
                ch.write(payload, timeout=self.channel_write_timeout_s)
            except ValueError:  # payload exceeds the segment: record only
                ch.write(dict(record),
                         timeout=self.channel_write_timeout_s)
            self.stats["channel_commits"] += 1
        except Exception as e:  # noqa: BLE001 — timeout/closed/unlinked
            logger.warning(
                "weight-sync %s: commit channel lost (%s); continuing on "
                "the object-store path", self.name, type(e).__name__)
            self.stats["channel_retired"] += 1
            self.retire_channel()

    def close(self) -> None:
        self.retire_channel()
        self._pinned.clear()


class WeightSubscriber:
    """Consumer-side: poll/receive commits, validate, swap atomically.

    Construction performs the resubscribe leg: one durable-record poll, so
    a respawned actor rejoins at the current version before serving
    anything.  ``current()`` raises :class:`NoWeightsPublishedError`
    until a first version commits.
    """

    def __init__(self, name: str, *, staleness_bound: Optional[int] = None,
                 poll_interval_s: float = 0.05,
                 fetch_timeout_s: float = 30.0,
                 verify_on_read: bool = False):
        self.name = name
        self.staleness_bound = staleness_bound
        self.poll_interval_s = poll_interval_s
        self.fetch_timeout_s = fetch_timeout_s
        self.verify_on_read = verify_on_read
        self._lock = threading.Lock()
        self._params: Any = None
        self._version: Optional[WeightVersion] = None
        self._digest: Optional[str] = None
        self._samples_at_version = 0
        self._channel = None
        # digest of the last REJECTED commit: a poisoned record would
        # otherwise be refetched and revalidated on every poll tick; a
        # legitimate re-publish of the version carries a fresh digest
        self._rejected_digest: Optional[str] = None
        self.stats = {"updates": 0, "rejected": 0, "stale_waits": 0,
                      "channel_updates": 0}
        self.poll(timeout_s=0.0)  # resubscribe: adopt the current version

    # -- channel fast path -------------------------------------------------
    def attach_channel(self, info: Dict[str, Any], slot: int) -> None:
        """Attach to the publisher's commit channel at reader ``slot``.
        Failure to attach (other host, channel gone) silently leaves the
        subscriber on the durable poll path."""
        if not info:
            return
        from ray_tpu.experimental.channel import Channel
        from ray_tpu.experimental.channel.transport import (
            TIER_HOST,
            EdgeTransport,
        )

        try:
            ch = Channel(info["name"], buffer_size=info["buffer_size"],
                         num_readers=info["num_readers"], _create=False)
            ch.set_reader_slot(slot)
            tr = EdgeTransport(ch, info.get("tier", TIER_HOST),
                               f"weights:{self.name}")
        except Exception:  # noqa: BLE001 — fall back to KV poll
            logger.warning("weight-sync %s: channel attach failed; "
                           "using object-store path", self.name)
            return
        with self._lock:
            self._channel = tr

    def detach_channel(self) -> None:
        with self._lock:
            ch, self._channel = self._channel, None
        if ch is not None:
            try:
                ch.close()
            except Exception:  # noqa: BLE001
                pass

    # -- consume -----------------------------------------------------------
    @property
    def version(self) -> Optional[WeightVersion]:
        with self._lock:
            return self._version

    def current(self) -> Tuple[Any, WeightVersion]:
        """Atomic ``(params, version)`` snapshot.  With
        ``verify_on_read`` the tree is re-hashed against the digest that
        was committed with it — direct evidence the served tree is not
        mixed across versions."""
        with self._lock:
            params, ver, digest = self._params, self._version, self._digest
        if ver is None:
            raise NoWeightsPublishedError(
                f"weight-sync {self.name!r}: no version committed yet")
        if self.verify_on_read:
            actual = params_digest(params, ver.version, ver.epoch)
            if actual != digest:
                raise WeightSyncError(
                    f"weight-sync {self.name!r}: served tree digest "
                    f"mismatch at v{ver.version} — mixed/torn params")
        return params, ver

    def note_sample(self) -> None:
        """Count one rollout batch served at the current version (the
        staleness gate's input)."""
        with self._lock:
            self._samples_at_version += 1

    def poll(self, timeout_s: float = 0.0) -> bool:
        """Check for (and adopt) a newer committed version.  Reads the
        channel first (cheap, may carry the payload inline), then the
        durable record.  Returns True when a newer version was
        installed.  Bounded by ``timeout_s``."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        updated = self._drain_channel()
        if self._poll_durable():
            updated = True
        while not updated and time.monotonic() < deadline:
            time.sleep(self.poll_interval_s)
            updated = self._drain_channel() or self._poll_durable()
        return updated

    def gate(self, timeout_s: float = 30.0) -> None:
        """Staleness backpressure.  No-op under the bound; past it, block
        (bounded) until a newer version commits, else raise
        :class:`WeightsStaleError` — rollout must not keep producing
        trajectories the learner can never catch up to."""
        if self.staleness_bound is None:
            return
        with self._lock:
            behind = self._samples_at_version >= self.staleness_bound
        if not behind:
            return
        self.stats["stale_waits"] += 1
        if self.poll(timeout_s=timeout_s):
            return
        with self._lock:
            ver = self._version
        raise WeightsStaleError(
            f"weight-sync {self.name!r}: {self._samples_at_version} "
            f"batches sampled at v{ver.version if ver else '?'} "
            f"(bound {self.staleness_bound}) and no newer publish within "
            f"{timeout_s:.1f}s — learner is behind or dead")

    # -- internals ---------------------------------------------------------
    def _drain_channel(self) -> bool:
        with self._lock:
            ch = self._channel
        if ch is None:
            return False
        updated = False
        while True:
            try:
                msg = ch.read(timeout=0.0)
            except Exception:  # noqa: BLE001 — empty (timeout) or torn down
                break
            got = self._commit(msg, from_channel=True)
            updated = updated or got
            if not got:
                break
        return updated

    def _poll_durable(self) -> bool:
        try:
            rec = _read_latest_record(self.name)
        except Exception:  # noqa: BLE001 — GCS hiccup: keep serving current
            return False
        if rec is None:
            return False
        with self._lock:
            cur = self._version
            rejected = self._rejected_digest
        if cur is not None and int(rec["version"]) <= cur.version:
            return False
        if rejected is not None and rec.get("digest") == rejected:
            return False  # already validated and refused this commit
        return self._commit(rec, from_channel=False)

    def _fetch_payload(self, record: Dict[str, Any]
                       ) -> Optional[Dict[str, Any]]:
        import ray_tpu

        try:
            ref = pickle.loads(record["ref"])
            return ray_tpu.get(ref, timeout=self.fetch_timeout_s)
        except Exception:  # noqa: BLE001 — publisher died with the payload
            logger.warning(
                "weight-sync %s: payload fetch for v%s failed; keeping "
                "current version", self.name, record.get("version"))
            return None

    def _commit(self, msg: Dict[str, Any], *, from_channel: bool) -> bool:
        """Validate and atomically install one commit message (payload
        inline or a record pointing at the object store)."""
        payload = msg if "params" in msg else self._fetch_payload(msg)
        if payload is None:
            return False
        version = int(payload["version"])
        epoch = int(payload["epoch"])
        with self._lock:
            if self._version is not None and \
                    version <= self._version.version:
                return False
        digest = params_digest(payload["params"], version, epoch)
        if digest != payload["digest"]:
            self.stats["rejected"] += 1
            with self._lock:
                self._rejected_digest = payload["digest"]
            logger.error(
                "weight-sync %s: digest mismatch on v%d — torn payload "
                "REJECTED, still serving %s", self.name, version,
                self._version)
            return False
        with self._lock:
            if self._version is not None and \
                    version <= self._version.version:
                return False  # raced a newer commit; keep it
            # the atomic swap: params+version+digest change together
            self._params = payload["params"]
            self._version = WeightVersion(version, epoch)
            self._digest = digest
            self._samples_at_version = 0
        self.stats["updates"] += 1
        if from_channel:
            self.stats["channel_updates"] += 1
        return True
