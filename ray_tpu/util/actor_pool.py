"""Fixed-pool actor work distribution.

Parity: ``python/ray/util/actor_pool.py:13`` (``ActorPool``: map /
map_unordered / submit / get_next / get_next_unordered / has_next /
has_free / pop_idle / push).  Rebuilt over ``ray_tpu.wait``: a FIFO of
idle actors, a FIFO of not-yet-dispatched submissions (work queued when
every actor is busy dispatches as completions free actors), and a
dispatch-order deque driving the ordered fetch path.

Stale-work semantics (``map`` after earlier ``submit`` calls): earlier
submissions still EXECUTE (their side effects are preserved and their
actors return to rotation on completion) but their results are never
yielded by the new map — and the new map never blocks on them.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class ActorPool:
    """Operate on a fixed pool of actors::

        pool = ActorPool([Actor.remote(), Actor.remote()])
        out = list(pool.map(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    """

    def __init__(self, actors: List[Any]):
        self._idle: collections.deque = collections.deque(actors)
        self._queued: collections.deque = collections.deque()  # (fn, value, stale)
        self._owner: dict = {}     # in-flight ref -> actor
        self._ordered: collections.deque = collections.deque()  # dispatch order
        self._consumed: set = set()  # refs taken by get_next_unordered
        self._stale: set = set()   # in-flight refs whose results are discarded

    # -- submission --------------------------------------------------------

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """Schedule ``fn(actor, value)`` on the next free actor; queued
        until one frees if all are busy."""
        self._queued.append((fn, value, False))
        self._dispatch()

    def _dispatch(self) -> None:
        while self._idle and self._queued:
            fn, value, stale = self._queued.popleft()
            actor = self._idle.popleft()
            ref = fn(actor, value)
            self._owner[ref] = actor
            if stale:
                self._stale.add(ref)  # executes, result never yielded
            else:
                self._ordered.append(ref)

    def _return_actor(self, ref) -> None:
        self._idle.append(self._owner.pop(ref))
        self._dispatch()

    def _stale_inflight(self) -> List[Any]:
        return [r for r in self._owner if r in self._stale]

    def _reap_stale(self, timeout: Optional[float] = 0) -> None:
        """Return actors of completed stale submissions (non-blocking by
        default); their results are dropped."""
        refs = self._stale_inflight()
        if not refs:
            return
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=timeout)
        for ref in ready:
            self._stale.discard(ref)
            self._return_actor(ref)

    # -- retrieval ---------------------------------------------------------

    def has_next(self) -> bool:
        return (any(r not in self._stale for r in self._owner)
                or any(not stale for _, _, stale in self._queued))

    def get_next(self, timeout: Optional[float] = None,
                 ignore_if_timedout: bool = False) -> Any:
        """Next result in SUBMISSION order (blocks up to ``timeout``)."""
        if not self.has_next():
            raise StopIteration("no pending results")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            while self._ordered and self._ordered[0] in self._consumed:
                self._consumed.discard(self._ordered.popleft())
            stale = self._stale_inflight()
            if self._ordered:
                head = self._ordered[0]
                waitset = [head] + stale
            elif stale:
                # all actors are busy with stale work; pending submissions
                # dispatch as those complete — wait on the stale refs
                head, waitset = None, stale
            else:
                # pending work is queued but the pool has no actors at all
                # (pop_idle drained it) — blocking would deadlock a
                # single-threaded caller forever
                raise RuntimeError(
                    "submissions are queued but the pool has no actors — "
                    "push() an actor to run them")
            t = (None if deadline is None
                 else max(0.0, deadline - time.monotonic()))
            ready, _ = ray_tpu.wait(waitset, num_returns=1, timeout=t)
            if not ready:
                if ignore_if_timedout:
                    return None
                raise TimeoutError("get_next timed out")
            ref = ready[0]
            if ref in self._stale:
                self._stale.discard(ref)
                self._return_actor(ref)
                continue  # head not ready yet; keep waiting
            self._ordered.popleft()
            self._return_actor(ref)
            return ray_tpu.get(ref)

    def get_next_unordered(self, timeout: Optional[float] = None,
                           ignore_if_timedout: bool = False) -> Any:
        """Next result in COMPLETION order (blocks up to ``timeout``)."""
        if not self.has_next():
            raise StopIteration("no pending results")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if not self._owner:  # everything queued and no actors to run it
                raise RuntimeError(
                    "submissions are queued but the pool has no actors — "
                    "push() an actor to run them")
            t = (None if deadline is None
                 else max(0.0, deadline - time.monotonic()))
            ready, _ = ray_tpu.wait(list(self._owner), num_returns=1,
                                    timeout=t)
            if not ready:
                if ignore_if_timedout:
                    return None
                raise TimeoutError("get_next_unordered timed out")
            ref = ready[0]
            if ref in self._stale:
                self._stale.discard(ref)
                self._return_actor(ref)
                continue  # discarded result; keep waiting for live work
            self._consumed.add(ref)
            self._return_actor(ref)
            # trim consumed refs off the ordered head NOW: a pure-unordered
            # consumer never calls get_next, and without this every result
            # ref (and its payload, via refcounting) stays pinned for the
            # pool's lifetime
            while self._ordered and self._ordered[0] in self._consumed:
                self._consumed.discard(self._ordered.popleft())
            return ray_tpu.get(ref)

    # -- bulk --------------------------------------------------------------

    def _drain_stale(self) -> None:
        """Mark every earlier submission stale so a map's output contains
        exactly its own results (reference ActorPool.map semantics).
        Non-blocking: completed stale results are reaped immediately with
        a zero timeout; a still-RUNNING earlier submission must not hang
        map() before any new work is submitted — it keeps executing (side
        effects preserved) and its actor re-enters rotation on completion,
        but its result is never yielded."""
        self._stale.update(self._owner)
        self._ordered.clear()
        self._consumed.clear()
        self._queued = collections.deque(
            (fn, value, True) for fn, value, _ in self._queued)
        self._reap_stale(timeout=0)

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]):
        """Apply over values; yields results in submission order."""
        self._drain_stale()
        for v in values:
            self.submit(fn, v)

        def gen():
            while self.has_next():
                yield self.get_next()

        return gen()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]):
        """Apply over values; yields results as they complete."""
        self._drain_stale()
        for v in values:
            self.submit(fn, v)

        def gen():
            while self.has_next():
                yield self.get_next_unordered()

        return gen()

    # -- pool management ---------------------------------------------------

    def has_free(self) -> bool:
        """True iff an actor is idle AND nothing is queued."""
        return bool(self._idle) and not self._queued

    def pop_idle(self) -> Optional[Any]:
        """Remove and return an idle actor (None if all are busy)."""
        if not self.has_free():
            return None
        return self._idle.popleft()

    def push(self, actor: Any) -> None:
        """Add an actor to the pool (queued work dispatches onto it)."""
        self._idle.append(actor)
        self._dispatch()
