"""Vision Transformer classifier — third family in the model zoo.

Same contract as the Llama/MoE families: pure ``apply(params, batch)``
functions plus a logical-axis spec tree, so the ShardedTrainer runs it
under any mesh layout (DP/FSDP/TP) without model changes.  Patch embedding
is a single reshaped gemm (MXU-friendly: no conv needed for ViT), encoder
blocks are pre-LN attention + GELU MLP stacked under lax.scan.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import dot_product_attention
from ray_tpu.ops.layers import rms_norm


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.num_channels

    @staticmethod
    def tiny(**kw) -> "ViTConfig":
        base = dict(image_size=32, patch_size=8, hidden_size=64,
                    num_layers=2, num_heads=4, mlp_dim=128, num_classes=10)
        base.update(kw)
        return ViTConfig(**base)

    @staticmethod
    def vit_b16() -> "ViTConfig":
        return ViTConfig()

    def num_params(self) -> int:
        h, m = self.hidden_size, self.mlp_dim
        per_layer = 4 * h * h + 2 * h * m + 2 * h  # qkv+o, mlp, norms
        return (self.patch_dim * h + h              # patch embed + bias
                + (self.num_patches + 1) * h        # pos embed (incl cls)
                + h                                  # cls token
                + self.num_layers * per_layer
                + h                                  # final norm
                + h * self.num_classes + self.num_classes)


def _layer_init(key, cfg: ViTConfig):
    h = cfg.hidden_size
    init = jax.nn.initializers.normal(0.02)
    ks = jax.random.split(key, 6)
    dt = cfg.param_dtype
    return {
        "attn_norm": jnp.ones((h,), dt),
        "wq": init(ks[0], (h, h), dt),
        "wk": init(ks[1], (h, h), dt),
        "wv": init(ks[2], (h, h), dt),
        "wo": init(ks[3], (h, h), dt),
        "mlp_norm": jnp.ones((h,), dt),
        "w_up": init(ks[4], (h, cfg.mlp_dim), dt),
        "w_down": init(ks[5], (cfg.mlp_dim, h), dt),
    }


def vit_init(key: jax.Array, cfg: ViTConfig) -> Dict[str, Any]:
    init = jax.nn.initializers.normal(0.02)
    ks = jax.random.split(key, cfg.num_layers + 4)
    layers = [_layer_init(k, cfg) for k in ks[:cfg.num_layers]]
    return {
        "patch_embed": init(ks[-4], (cfg.patch_dim, cfg.hidden_size),
                            cfg.param_dtype),
        "patch_bias": jnp.zeros((cfg.hidden_size,), cfg.param_dtype),
        "pos_embed": init(ks[-3], (cfg.num_patches + 1, cfg.hidden_size),
                          cfg.param_dtype),
        "cls_token": init(ks[-2], (cfg.hidden_size,), cfg.param_dtype),
        "layers": jax.tree.map(lambda *xs: jnp.stack(xs), *layers),
        "final_norm": jnp.ones((cfg.hidden_size,), cfg.param_dtype),
        "head_w": init(ks[-1], (cfg.hidden_size, cfg.num_classes),
                       cfg.param_dtype),
        "head_b": jnp.zeros((cfg.num_classes,), cfg.param_dtype),
    }


def vit_param_specs(cfg: ViTConfig) -> Dict[str, Any]:
    layer = {
        "attn_norm": ("norm",),
        "wq": ("embed", "heads"),
        "wk": ("embed", "heads"),
        "wv": ("embed", "heads"),
        "wo": ("heads", "embed"),
        "mlp_norm": ("norm",),
        "w_up": ("embed", "mlp"),
        "w_down": ("mlp", "embed"),
    }
    return {
        "patch_embed": (None, "embed"),
        "patch_bias": ("norm",),
        "pos_embed": (None, "embed"),
        "cls_token": ("norm",),
        "layers": {k: ("layers",) + v for k, v in layer.items()},
        "final_norm": ("norm",),
        "head_w": ("embed", "vocab"),
        "head_b": ("norm",),
    }


def _patchify(images: jnp.ndarray, cfg: ViTConfig) -> jnp.ndarray:
    """[b, H, W, C] -> [b, num_patches, patch_dim] (pure reshape/transpose)."""
    b, H, W, C = images.shape
    p = cfg.patch_size
    x = images.reshape(b, H // p, p, W // p, p, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (H // p) * (W // p), p * p * C)


def vit_apply(params: Dict[str, Any], images: jnp.ndarray, cfg: ViTConfig,
              *, mesh=None) -> jnp.ndarray:
    """images [b, H, W, C] float -> logits [b, num_classes] (fp32)."""
    dt = cfg.dtype
    x = _patchify(images.astype(dt), cfg)
    x = x @ params["patch_embed"].astype(dt) + params["patch_bias"].astype(dt)
    cls = jnp.broadcast_to(params["cls_token"].astype(dt),
                           (x.shape[0], 1, cfg.hidden_size))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(dt)[None]

    hd = cfg.hidden_size // cfg.num_heads

    def layer_fn(x, lp):
        b, s, h = x.shape
        y = rms_norm(x, lp["attn_norm"])
        q = (y @ lp["wq"].astype(dt)).reshape(b, s, cfg.num_heads, hd)
        k = (y @ lp["wk"].astype(dt)).reshape(b, s, cfg.num_heads, hd)
        v = (y @ lp["wv"].astype(dt)).reshape(b, s, cfg.num_heads, hd)
        attn = dot_product_attention(q, k, v, causal=False, impl="ref",
                                     mesh=mesh)
        x = x + attn.reshape(b, s, h) @ lp["wo"].astype(dt)
        y = rms_norm(x, lp["mlp_norm"])
        act = jax.nn.gelu((y @ lp["w_up"].astype(dt)).astype(jnp.float32))
        return x + act.astype(dt) @ lp["w_down"].astype(dt), None

    f = layer_fn
    if cfg.remat:
        f = jax.checkpoint(f)
    if cfg.scan_layers:
        x, _ = jax.lax.scan(lambda c, lp: f(c, lp), x, params["layers"])
    else:
        L = jax.tree.leaves(params["layers"])[0].shape[0]
        for i in range(L):
            x, _ = f(x, jax.tree.map(lambda a: a[i], params["layers"]))
    x = rms_norm(x, params["final_norm"])
    cls_out = x[:, 0]
    return (cls_out @ params["head_w"].astype(dt)
            + params["head_b"].astype(dt)).astype(jnp.float32)


def vit_loss(params: Dict[str, Any], batch: Dict[str, jnp.ndarray],
             cfg: ViTConfig, *, mesh=None) -> jnp.ndarray:
    logits = vit_apply(params, batch["images"], cfg, mesh=mesh)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(
        logp, batch["labels"][:, None], axis=-1).mean()


def make_vit_trainer(cfg: ViTConfig, mesh, *, optimizer=None, rules=None):
    from ray_tpu.models.training import ShardedTrainer, default_optimizer
    from ray_tpu.parallel.pipeline import reject_pp

    rules = reject_pp(mesh, "ViT", rules)
    return ShardedTrainer(
        init_fn=lambda key: vit_init(key, cfg),
        loss_fn=functools.partial(vit_loss, cfg=cfg, mesh=mesh),
        param_specs=vit_param_specs(cfg),
        mesh=mesh,
        optimizer=optimizer or default_optimizer(),
        rules=rules,
    )
