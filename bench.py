"""Headline benchmark: Llama training MFU on the available TPU chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is measured MFU / 35% — the north-star target from BASELINE.md
("Train Llama-2-7B DP on v5e-64 at >=35% MFU").  Here it runs the largest
model that fits the chips present (a single v5e chip under the test driver),
same math, same code path as the multi-chip trainer.

Timing: loss is read back to host each step, which synchronizes the device
stream (plain block_until_ready does not block through the axon tunnel).
"""

import json
import sys
import time

import jax
import jax.numpy as jnp


PEAK_FLOPS = {
    # bf16 peak per chip
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops_per_chip() -> float:
    kind = jax.devices()[0].device_kind
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return 197e12


def train_flops_per_step(cfg, batch, seq) -> float:
    """6*N per token for the dense matmuls (fwd 2N + bwd 4N) plus causal
    attention: 12*b*s^2*h*hd per layer (QK^T+PV fwd=4, bwd=8) * 0.5 causal."""
    n_matmul = cfg.num_params() - cfg.vocab_size * cfg.hidden_size  # embed lookup is not a matmul
    tokens = batch * seq
    dense = 6 * n_matmul * tokens
    hd = cfg.resolved_head_dim
    attn = 12 * cfg.num_layers * batch * seq * seq * cfg.num_heads * hd * 0.5
    return dense + attn


def main() -> None:
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.models.training import make_llama_trainer, default_optimizer
    from ray_tpu.parallel import MeshConfig, create_mesh

    n_dev = len(jax.devices())
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # Largest config the test driver's compile tunnel accepts; head_dim
        # 128 and the 1536x6144 mlp keep the MXU at high occupancy (measured
        # sweep: 40.5% at hs1024/mlp4096 -> 50.9% at b8/s2048 -> 52.8% at
        # b16/s1024, which trades quadratic attention FLOPs for dense ones
        # at the same token count; bigger models, b16/s2048, and the
        # save_dots remat policy are all rejected by the remote compile
        # helper).  Round-5 lever sweep (benchmarks/mfu_sweep.py) measured
        # the remaining candidates: save_attn_mlp remat (+1.1 pts at b8
        # but OOMs above, net below this b16 config), grad accumulation
        # (persistent f32 accumulator +4.5 GB -> OOM at any accum>1 here),
        # int8 embed gather (<=0.1 pts) — the 52.8% plateau is the proven
        # ceiling for this rig (benchmarks/README.md round-5 MFU section).
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1536, num_layers=16, num_heads=12,
            num_kv_heads=12, mlp_dim=6144, max_seq_len=1024,
        )
        batch, seq, steps = 16, 1024, 10
    else:  # CPU fallback so the script runs anywhere
        cfg = LlamaConfig.tiny()
        batch, seq, steps = 8, 64, 3

    mesh = create_mesh(MeshConfig(dp=-1))
    tr = make_llama_trainer(
        cfg, mesh, optimizer=default_optimizer(warmup=1, decay_steps=1000)
    )
    state = tr.init_state(jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size
    )
    b = tr.shard_batch({"tokens": tokens})

    # Warmup (compile + first run).
    for _ in range(2):
        state, m = tr.step(state, b)
        float(m["loss"])

    # Host readback through the test driver's TPU tunnel costs ~160 ms, so
    # per-step sync timing lies badly.  Instead: run N1 and N2 chained steps
    # (state-dependent, so the device must execute each) with a single
    # readback at the end; the slope (t2-t1)/(N2-N1) is the true step time.
    def run_chained(n):
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(n):
            state, m = tr.step(state, b)
        float(m["loss"])
        return time.perf_counter() - t0

    n1, n2 = max(steps // 4, 1), steps
    t1 = run_chained(n1)
    t2 = run_chained(n2)
    dt = (t2 - t1) / (n2 - n1)

    flops = train_flops_per_step(cfg, batch, seq)
    peak = peak_flops_per_chip() * n_dev if on_tpu else 1e12
    mfu = flops / dt / peak
    tokens_s = batch * seq / dt
    result = {
        "metric": "llama_train_mfu" if on_tpu else "llama_train_mfu_cpu",
        "value": round(mfu * 100, 2),
        "unit": "%MFU",
        "vs_baseline": round(mfu / 0.35, 3),
        "detail": {
            "params_m": round(cfg.num_params() / 1e6, 1),
            "tokens_per_s": round(tokens_s),
            "step_ms": round(dt * 1e3, 1),
            "devices": n_dev,
            "device_kind": jax.devices()[0].device_kind,
            # Honest labeling (VERDICT round-1 weak #8): this is a
            # single-chip proxy for the v5e-64 Llama-2-7B north star — the
            # largest model the one available chip fits.  Multi-chip mesh
            # configs are timed in __graft_entry__.dryrun_multichip, and
            # the 7B sharding itself is compile-proven there.
            "scope": "single_chip_proxy",
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
