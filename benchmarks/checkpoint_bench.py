"""Checkpoint A/B: synchronous whole-tree stall vs async tiered save.

PR 17 acceptance gate: a training step that checkpoints through the
async tiered path (:class:`~ray_tpu.train.checkpoint_async.
AsyncCheckpointer`) must stall for at most **25%** of what the
synchronous whole-tree baseline stalls, at equal durability.  Both arms
run the SAME save machinery — snapshot (D2H + serialize) then
write+fsync+rename-commit — the only difference is *when the step
resumes*:

* ``sync``  — ``save(..., wait_persist=True)``: the step blocks until
  the shard is fsynced and the generation's MANIFEST is committed
  (what a plain ``Checkpoint.from_pytree`` loop pays every step);
* ``async`` — ``save(...)``: the step resumes once the snapshot is in
  host RAM; serialize+fsync+commit runs on the persist thread,
  overlapping the next step's compute.

The arms are **interleaved** step-for-step in one run (sync step i,
then async step i), so background load drift hits both equally.  Each
arm drives its own :class:`~ray_tpu.train.session.StepLedger`; the
record carries both ``step_time_breakdown`` blocks, and the gate
requires the split buckets (``checkpoint_snapshot`` /
``checkpoint_persist``) visible in both.  Per-arm **stall** is
``mean(step_wall − compute)`` — everything the checkpoint added to the
step's critical path.

Equal durability is asserted, not assumed: after the loop (and one
``wait()`` to drain the async persist queue) both storage dirs must
hold the same number of rename-committed generations, and the async
arm's newest generation must restore bit-exact against the saved tree.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python benchmarks/checkpoint_bench.py \
        [--mib 32] [--steps 6] [--dir /path/with/real/fsync]
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu._private.bench_emit import emit_final_record

GATE_STALL_RATIO = 0.25


def _make_state(mib: int):
    """A model-shaped pytree totaling ~``mib`` MiB of float32 leaves."""
    import jax
    import numpy as np

    n_leaves = 8
    per = (mib * 1024 * 1024) // (4 * n_leaves)
    rng = np.random.default_rng(0)
    host = {f"layer_{i}": rng.standard_normal(per).astype("float32")
            for i in range(n_leaves)}
    return jax.device_put(host)


def _calibrated_compute(target_s: float):
    """A jitted matmul loop sized so one call takes ~``target_s`` — the
    'next step's compute' the async persist overlaps with."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((768, 768), dtype=jnp.float32)

    @jax.jit
    def mm(a):
        return jnp.tanh(a @ a) * 0.5

    jax.block_until_ready(mm(x))  # compile outside the timing
    t0 = time.perf_counter()
    jax.block_until_ready(mm(x))
    t_one = max(time.perf_counter() - t0, 1e-4)
    reps = max(1, int(target_s / t_one) + 1)

    def compute():
        y = x
        for _ in range(reps):
            y = mm(y)
        jax.block_until_ready(y)

    return compute


def _run_arm_step(ledger, compute, ckptr, state, step, sync):
    # save FIRST, then compute: the async arm's background persist then
    # overlaps THIS step's compute, so the ledger attributes it to the
    # step it actually overlapped (in an interleaved A/B the next step
    # belongs to the other arm, which would hide the persist between
    # this ledger's step boundaries)
    with ledger.step():
        ckptr.save(state, {"step": step}, wait_persist=sync)
        with ledger.bucket("compute"):
            compute()


def _stall_s(bd):
    return max(bd["step_wall_s"] - bd["buckets_s"].get("compute", 0.0), 0.0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mib", type=int, default=32)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--dir", default=None,
                    help="parent dir for the two checkpoint stores "
                         "(default: a tempdir under the cwd, so fsync "
                         "hits the working disk, not a tmpfs)")
    args = ap.parse_args()

    import jax  # noqa: F401  — fail fast before building state
    import numpy as np

    from ray_tpu.train.checkpoint_async import (
        AsyncCheckpointer, restore_tiered)
    from ray_tpu.train.checkpoint_manager import committed_checkpoint_dirs
    from ray_tpu.train.session import StepLedger

    root = args.dir or tempfile.mkdtemp(prefix="ckpt_bench_", dir=os.getcwd())
    dirs = {"sync": os.path.join(root, "sync"),
            "async": os.path.join(root, "async")}
    for d in dirs.values():
        os.makedirs(d, exist_ok=True)

    state = _make_state(args.mib)

    # calibrate the per-step compute to ~3x one full sync persist, so
    # the async arm's background write genuinely overlaps (and finishes
    # inside) the same step's compute — the overlap claim, not a toy
    # sleep.  Two probes, take the slower: fsync cost swings with the
    # page-cache state, and an undersized compute window lets the
    # persist spill past the step boundary (where the ledger correctly
    # refuses to charge it)
    probe = AsyncCheckpointer(dirs["sync"], "ckpt-bench-probe", 0, 1,
                              publish_status=False)
    t_persist = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        probe.save(state, wait_persist=True)
        t_persist = max(t_persist, time.perf_counter() - t0)
    probe.close()
    shutil.rmtree(dirs["sync"])
    os.makedirs(dirs["sync"])
    compute = _calibrated_compute(3.0 * t_persist)

    ledgers = {a: StepLedger(group_name=f"ckpt-bench-{a}", publish=False)
               for a in dirs}
    ckptrs = {a: AsyncCheckpointer(dirs[a], f"ckpt-bench-{a}", 0, 1,
                                   ledger=ledgers[a], publish_status=False)
              for a in dirs}

    # warmup step per arm (first-save index discovery, thread spawn)
    for a in dirs:
        _run_arm_step(ledgers[a], compute, ckptrs[a], state, 0, a == "sync")
    ckptrs["async"].wait(60.0)
    for a in dirs:  # drop the warmup from the measured breakdowns
        ledgers[a].__init__(group_name=f"ckpt-bench-{a}", publish=False)

    # the interleaved measured loop: sync step i, then async step i
    for step in range(1, args.steps + 1):
        for a in ("sync", "async"):
            _run_arm_step(ledgers[a], compute, ckptrs[a], state,
                          step, a == "sync")

    # equal durability: drain the async queue, then both stores must
    # hold the same number of rename-committed generations
    drained = ckptrs["async"].wait(120.0)
    committed = {a: len(committed_checkpoint_dirs(dirs[a])) for a in dirs}
    res = restore_tiered(dirs["async"], "ckpt-bench-async")
    restored_exact = res is not None and all(
        np.array_equal(np.asarray(res.tree[k]), np.asarray(v))
        for k, v in jax.device_get(state).items())

    bds = {a: ledgers[a].breakdown() for a in dirs}
    stall = {a: _stall_s(bds[a]) for a in dirs}
    ratio = stall["async"] / stall["sync"] if stall["sync"] > 0 else 1.0
    buckets_ok = all(
        b in bds[a]["buckets_s"]
        for a in dirs for b in ("checkpoint_snapshot", "checkpoint_persist"))
    ok = (ratio <= GATE_STALL_RATIO and drained and restored_exact
          and buckets_ok and committed["sync"] == committed["async"]
          and committed["async"] >= args.steps)

    for a in dirs:
        ckptrs[a].close()
    if args.dir is None:
        shutil.rmtree(root, ignore_errors=True)

    emit_final_record({
        "metric": "checkpoint_async_stall_ratio",
        "value": round(ratio, 4),
        "unit": "x_of_sync_stall",
        "ok": bool(ok),
        "detail": {
            "scope": "checkpoint_ab",
            "mib": args.mib,
            "steps": args.steps,
            "gate_stall_ratio": GATE_STALL_RATIO,
            "stall_sync_ms": round(stall["sync"] * 1e3, 2),
            "stall_async_ms": round(stall["async"] * 1e3, 2),
            "persist_probe_ms": round(t_persist * 1e3, 2),
            "committed_generations": committed,
            "async_restore_bit_exact": bool(restored_exact),
            "step_time_breakdown": {a: bds[a] for a in dirs},
        },
    })

    assert buckets_ok, (
        f"split buckets missing from a breakdown: "
        f"{ {a: sorted(bds[a]['buckets_s']) for a in dirs} }")
    assert drained and committed["sync"] == committed["async"] \
        and committed["async"] >= args.steps, (
        f"durability mismatch: committed={committed} (need >= {args.steps} "
        f"in both), drained={drained}")
    assert restored_exact, "async arm's newest generation not bit-exact"
    assert ratio <= GATE_STALL_RATIO, (
        f"async step stall is {ratio:.2%} of the sync baseline "
        f"(gate: <= {GATE_STALL_RATIO:.0%}; "
        f"sync {stall['sync']*1e3:.1f}ms vs async {stall['async']*1e3:.1f}ms)")


if __name__ == "__main__":
    main()
