"""Attention: reference, Pallas flash (TPU), and ring attention (sp axis).

Ring attention (context parallelism) is absent from the reference
(SURVEY.md §2.4 — "EP/SP/CP/ring attention: Absent") and is a headline
TPU-native feature here: K/V blocks rotate around the ``sp`` mesh axis via
``lax.ppermute`` (ICI neighbor exchanges) while each device computes
blockwise online-softmax attention for its local Q shard — memory per device
is O(seq/sp), enabling contexts sp× longer than a single chip's HBM allows.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_WARNED_WINDOW_NO_FLASH = False
_NEG_INF = -1e30


def sliding_window_mask(q_pos, k_pos, window):
    """Sliding-window visibility clause: query at ``q_pos`` sees keys in
    ``(q_pos - window, q_pos]`` — the SINGLE home of the off-by-one
    convention, shared by the attention ops and every model cache path
    (dense + paged).  Args broadcast."""
    return q_pos - k_pos < window


def _repeat_kv(k: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[b, s, kv_heads, d] -> [b, s, kv_heads * n_rep, d] (GQA expansion)."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d
    )


def reference_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    positions_q: Optional[jnp.ndarray] = None,
    positions_k: Optional[jnp.ndarray] = None,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Plain softmax attention, fp32 accumulation.

    q: [b, sq, h, d]; k, v: [b, sk, kv_h, d] with h % kv_h == 0.
    ``window``: sliding-window (Mistral-style) — query p attends keys in
    (p - window, p].  Requires causal.
    """
    b, sq, h, d = q.shape
    kv_h = k.shape[2]
    k = _repeat_kv(k, h // kv_h)
    v = _repeat_kv(v, h // kv_h)
    scale = d ** -0.5
    if window is not None and not causal:
        raise ValueError("sliding window requires causal attention")
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        if positions_q is None:
            positions_q = jnp.arange(sq)
        if positions_k is None:
            positions_k = jnp.arange(k.shape[1])
        mask = positions_q[:, None] >= positions_k[None, :]
        if window is not None:
            mask &= sliding_window_mask(positions_q[:, None],
                                        positions_k[None, :], window)
        logits = jnp.where(mask[None, None, :, :], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def _blockwise_step(q, k, v, m, l, o, *, qpos, kpos, scale, window=None):
    """One online-softmax accumulation step against a K/V block.

    q: [b, sq, h, d]; k, v: [b, sk, h, d] (kv already GQA-expanded);
    m, l: [b, h, sq] running max / normalizer; o: [b, sq, h, d] fp32 accum.
    """
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    mask = qpos[:, None] >= kpos[None, :]
    if window is not None:
        mask &= sliding_window_mask(qpos[:, None], kpos[None, :], window)
    logits = jnp.where(mask[None, None, :, :], logits, _NEG_INF)
    m_blk = jnp.max(logits, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # exp of fully-masked rows underflows to 0 — no NaNs since m_new finite.
    p = jnp.exp(logits - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, o_new


def _shard_map(fn, *, mesh, in_specs, out_specs, check_vma=True):
    """shard_map across jax versions (jax.shard_map vs experimental).

    check_vma=False is needed when the body contains ops opaque to the
    varying-axis type system (e.g. pallas_call).
    """
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check_vma,
            )
        except TypeError:
            return jax.shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs
            )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mesh: Mesh,
    sp_axis: str = "sp",
    causal: bool = True,
    batch_axes=("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Ring attention over the ``sp`` mesh axis (global-view inputs).

    Inputs are global arrays [b, S, h, d] (sharded or not); shard_map splits
    S over ``sp``, and K/V shards rotate around the ring with ppermute while
    each device accumulates blockwise output for its local Q shard.
    """
    if window is not None and not causal:
        raise ValueError("sliding window requires causal attention")
    sp = mesh.shape[sp_axis]
    if sp == 1:
        return reference_attention(q, k, v, causal=causal, window=window)
    h, kv_h = q.shape[2], k.shape[2]
    batch_axes = tuple(a for a in batch_axes if a in mesh.axis_names)
    if head_axis is not None and head_axis not in mesh.axis_names:
        head_axis = None
    qspec = P(batch_axes if batch_axes else None, sp_axis, head_axis, None)

    def local_fn(q_loc, k_loc, v_loc):
        b, sq, h_loc, d = q_loc.shape
        idx = jax.lax.axis_index(sp_axis)
        scale = d ** -0.5
        qpos = idx * sq + jnp.arange(sq)
        perm = [(i, (i + 1) % sp) for i in range(sp)]

        def compute(t, k_cur, v_cur, m, l, o):
            src_block = (idx - t) % sp
            if causal:
                kpos = src_block * sq + jnp.arange(k_cur.shape[1])
                qp = qpos
            else:
                kpos = jnp.zeros((k_cur.shape[1],), jnp.int32)
                qp = jnp.zeros((sq,), jnp.int32)
            return _blockwise_step(
                q_loc, k_cur, v_cur, m, l, o, qpos=qp, kpos=kpos,
                scale=scale, window=window
            )

        def body(t, carry):
            k_cur, v_cur, m, l, o = carry
            m, l, o = compute(t, k_cur, v_cur, m, l, o)
            k_nxt = jax.lax.ppermute(k_cur, sp_axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, sp_axis, perm)
            return k_nxt, v_nxt, m, l, o

        m0 = jnp.full((b, h_loc, sq), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h_loc, sq), jnp.float32)
        o0 = jnp.zeros((b, sq, h_loc, d), jnp.float32)
        # Mark the accumulators device-varying so the loop carry typechecks
        # under shard_map's varying-axis tracking (jax>=0.9).
        if hasattr(jax.lax, "pcast"):
            m0, l0, o0 = jax.lax.pcast(
                (m0, l0, o0), tuple(mesh.axis_names), to="varying"
            )
        elif hasattr(jax.lax, "pvary"):
            m0, l0, o0 = jax.lax.pvary((m0, l0, o0), tuple(mesh.axis_names))
        # Last block: compute only — its rotated K/V would be discarded, so
        # running the final ppermute pair would waste two ICI collectives.
        k_l, v_l, m, l, o = jax.lax.fori_loop(
            0, sp - 1, body, (k_loc, v_loc, m0, l0, o0)
        )
        m, l, o = compute(sp - 1, k_l, v_l, m, l, o)
        l = jnp.maximum(l, 1e-30)
        out = o / l.transpose(0, 2, 1)[..., None]
        return out.astype(q_loc.dtype)

    # GQA-expand before shard_map so head counts line up under tp sharding.
    k = _repeat_kv(k, h // kv_h)
    v = _repeat_kv(v, h // kv_h)
    # check_vma=False: outputs are trivially replicated over mesh axes the
    # specs never mention (e.g. a size-1 "pp"), which the static VMA check
    # cannot infer through the ppermute ring.
    return _shard_map(
        local_fn, mesh=mesh, in_specs=(qspec, qspec, qspec), out_specs=qspec,
        check_vma=False,
    )(q, k, v)


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    impl: str = "auto",
    mesh: Optional[Mesh] = None,
    sp_axis: str = "sp",
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Dispatching attention entry point used by the model layer.

    impl: 'auto' | 'ref' | 'flash' | 'ring'.  'auto' picks ring when the
    mesh shards sequence (sp>1), Pallas flash on TPU otherwise, and the
    reference path on CPU test meshes.  ``window`` (sliding-window /
    Mistral-style) is supported by ref and ring; 'auto' avoids the flash
    kernel when a window is set (the pallas kernel has no window mask
    yet — a skipped-block windowed variant is the natural follow-up).
    """
    if impl == "auto":
        if (
            mesh is not None
            and sp_axis in mesh.axis_names
            and mesh.shape[sp_axis] > 1
        ):
            impl = "ring"
        elif jax.default_backend() == "tpu" and q.shape[1] >= 256:
            if window is None:
                impl = "flash"
            else:
                global _WARNED_WINDOW_NO_FLASH
                if not _WARNED_WINDOW_NO_FLASH:
                    _WARNED_WINDOW_NO_FLASH = True
                    import warnings

                    warnings.warn(
                        "sliding_window forces reference attention on "
                        "TPU (the pallas flash kernel has no window "
                        "mask yet): full [b,h,S,S] logits materialize "
                        "per layer — expect higher HBM use at long "
                        "sequence lengths", stacklevel=2)
                impl = "ref"
        else:
            impl = "ref"
    if impl == "flash" and window is not None:
        raise ValueError(
            "impl='flash' does not support sliding windows; use 'ref', "
            "'ring', or 'auto'")
    if impl == "ring":
        assert mesh is not None, "ring attention needs a mesh"
        return ring_attention(
            q, k, v, mesh=mesh, sp_axis=sp_axis, causal=causal,
            window=window
        )
    if impl == "flash":
        from ray_tpu.ops.pallas.flash_attention import flash_attention

        if mesh is None:
            return flash_attention(q, k, v, causal=causal)
        # The pallas_call is opaque to GSPMD: run it per-shard under
        # shard_map, with batch sharded over dp/fsdp and heads over tp
        # (sequence is whole per device since sp==1 on this path).
        batch_axes = tuple(
            a for a in ("dp", "fsdp") if a in mesh.axis_names
        )
        head_axis = "tp" if "tp" in mesh.axis_names else None
        qspec = P(batch_axes if batch_axes else None, None, head_axis, None)
        kvspec = qspec
        return _shard_map(
            lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=causal),
            mesh=mesh,
            in_specs=(qspec, kvspec, kvspec),
            out_specs=qspec,
            check_vma=False,
        )(q, k, v)
    return reference_attention(q, k, v, causal=causal, window=window)
