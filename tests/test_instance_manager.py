"""Instance-manager lifecycle + TPU pod-slice provider (VERDICT item #8).

Reference: ``python/ray/autoscaler/v2/instance_manager/`` state machine
and the TPU slice model (``_private/accelerators/tpu.py:326-372``).
"""

from typing import Dict, List, Optional

import pytest

from ray_tpu.autoscaler.instance_manager import (
    Instance,
    InstanceManager,
    InstanceState,
)
from ray_tpu.autoscaler.tpu_slice_provider import parse_pod_type


class FakeProvider:
    """In-memory provider: instances 'join' when the test says so."""

    def __init__(self, nodes_per_instance: int = 1):
        self._n = nodes_per_instance
        self._alive: Dict[str, List[str]] = {}
        self._counter = 0
        self.fail_next = False

    def create_node(self, node_type, resources, labels):
        if self.fail_next:
            self.fail_next = False
            raise RuntimeError("cloud quota exceeded")
        self._counter += 1
        pid = f"{node_type}-{self._counter}"
        self._alive[pid] = [f"{pid}-n{i}" for i in range(self._n)]
        return pid

    def terminate_node(self, pid):
        self._alive.pop(pid, None)

    def non_terminated_nodes(self):
        return list(self._alive)

    def node_id_of(self, pid):
        ids = self._alive.get(pid)
        return ids[0] if ids else None

    def node_ids_of(self, pid):
        return list(self._alive.get(pid, []))


def test_lifecycle_requested_to_running():
    prov = FakeProvider()
    im = InstanceManager(prov)
    inst = im.request("cpu", {"CPU": 4}, {})
    assert inst.state is InstanceState.REQUESTED
    im.reconcile(alive_node_ids=set())
    assert inst.state is InstanceState.LAUNCHING
    assert inst.provider_id in prov.non_terminated_nodes()
    # node registers with the GCS -> RUNNING
    im.reconcile(alive_node_ids=set(prov.node_ids_of(inst.provider_id)))
    assert inst.state is InstanceState.RUNNING
    assert inst.node_ids == prov.node_ids_of(inst.provider_id)


def test_drain_terminates():
    prov = FakeProvider()
    im = InstanceManager(prov)
    inst = im.request("cpu", {"CPU": 4}, {})
    im.reconcile(set())
    im.reconcile(set(prov.node_ids_of(inst.provider_id)))
    im.drain(inst)
    assert inst.state is InstanceState.DRAINING
    im.reconcile(set())
    assert inst.state is InstanceState.TERMINATED
    assert not prov.non_terminated_nodes()


def test_launch_failure_marks_failed():
    prov = FakeProvider()
    prov.fail_next = True
    im = InstanceManager(prov)
    inst = im.request("cpu", {"CPU": 4}, {})
    im.reconcile(set())
    assert inst.state is InstanceState.FAILED
    assert "quota" in inst.failure


def test_launch_timeout_fails_and_cleans_up():
    prov = FakeProvider()
    im = InstanceManager(prov, launch_timeout_s=0.0)
    inst = im.request("cpu", {"CPU": 4}, {})
    im.reconcile(set())
    assert inst.state is InstanceState.LAUNCHING
    im.reconcile(set())  # node never joins; timeout elapsed (0s)
    assert inst.state is InstanceState.FAILED
    assert inst.failure == "launch timeout"
    assert not prov.non_terminated_nodes()  # provider node reclaimed


def test_running_node_death_fails_instance():
    prov = FakeProvider()
    im = InstanceManager(prov)
    inst = im.request("cpu", {"CPU": 4}, {})
    im.reconcile(set())
    alive = set(prov.node_ids_of(inst.provider_id))
    im.reconcile(alive)
    assert inst.state is InstanceState.RUNNING
    prov.terminate_node(inst.provider_id)  # cloud killed it
    im.reconcile(alive)
    assert inst.state is InstanceState.FAILED


def test_transient_heartbeat_blip_survives_grace():
    """A member missing from GCS-alive briefly (heartbeat blip) must not
    fail the instance; a persistent absence past the grace does."""
    prov = FakeProvider()
    im = InstanceManager(prov, dead_grace_s=3600.0)
    inst = im.request("cpu", {"CPU": 4}, {})
    im.reconcile(set())
    alive = set(prov.node_ids_of(inst.provider_id))
    im.reconcile(alive)
    assert inst.state is InstanceState.RUNNING
    im.reconcile(set())  # GCS says dead, provider says alive: blip
    assert inst.state is InstanceState.RUNNING
    im.reconcile(alive)  # resurrected
    assert inst.state is InstanceState.RUNNING and inst.dead_since is None
    im2 = InstanceManager(prov, dead_grace_s=0.0)
    inst2 = im2.request("cpu", {"CPU": 4}, {})
    im2.reconcile(set())
    alive2 = set(prov.node_ids_of(inst2.provider_id))
    im2.reconcile(alive2)
    im2.reconcile(set())   # first observation starts the clock
    im2.reconcile(set())   # grace (0s) elapsed -> FAILED + reclaimed
    assert inst2.state is InstanceState.FAILED
    assert inst2.provider_id not in prov.non_terminated_nodes()


def test_terminal_records_pruned():
    prov = FakeProvider()
    im = InstanceManager(prov, keep_terminal=3)
    for _ in range(6):
        inst = im.request("cpu", {"CPU": 1}, {})
        im.reconcile(set())
        im.reconcile(set(prov.node_ids_of(inst.provider_id)))
        im.drain(inst)
        im.reconcile(set())
    terminal = im.by_state(InstanceState.TERMINATED, InstanceState.FAILED)
    assert len(terminal) == 3  # oldest evicted


def test_multi_host_instance_runs_only_when_all_join():
    """A pod slice is RUNNING only once EVERY host raylet registered."""
    prov = FakeProvider(nodes_per_instance=4)
    im = InstanceManager(prov)
    inst = im.request("v5e-16", {"TPU": 4}, {})
    im.reconcile(set())
    all_ids = prov.node_ids_of(inst.provider_id)
    im.reconcile(set(all_ids[:2]))  # half the hosts joined
    assert inst.state is InstanceState.LAUNCHING
    im.reconcile(set(all_ids))
    assert inst.state is InstanceState.RUNNING
    assert len(inst.node_ids) == 4


def test_parse_pod_type():
    spec = parse_pod_type("v5e-16")
    assert (spec.num_hosts, spec.chips_per_host, spec.total_chips) == (4, 4, 16)
    spec = parse_pod_type("v4-8")
    assert spec.num_hosts == 2
    assert parse_pod_type("v5e-4").num_hosts == 1


def test_tpu_slice_provider_end_to_end(ray_isolated):
    """Provision a real (subprocess) 2-host slice: both hosts register
    with slice labels, the head host carries the slice-head resource, and
    termination tears down the whole slice atomically."""
    import time

    import ray_tpu
    from ray_tpu._private.worker import get_global_worker
    from ray_tpu.autoscaler.tpu_slice_provider import TPUPodSliceProvider

    w = get_global_worker()
    # v4-8 = 2 hosts x 4 chips
    prov = TPUPodSliceProvider(w.session_dir, w.gcs.addr, host_cpus=1)
    sid = prov.create_node("v4-8", {}, {})
    try:
        node_ids = prov.node_ids_of(sid)
        assert len(node_ids) == 2
        deadline = time.time() + 30
        while time.time() < deadline:
            nodes = {n["node_id"]: n for n in ray_tpu.nodes()
                     if n["alive"]}
            if all(nid in nodes for nid in node_ids):
                break
            time.sleep(0.5)
        members = [nodes[nid] for nid in node_ids]
        assert all(m["Resources"].get("TPU") == 4.0 for m in members)
        heads = [m for m in members
                 if any(k.startswith("TPU-v4-8-head")
                        for k in m["Resources"])]
        assert len(heads) == 1  # exactly one slice-head
        labels = [m["labels"] for m in members]
        assert {l["tpu-worker-index"] for l in labels} == {"0", "1"}
        assert len({l["tpu-slice"] for l in labels}) == 1
    finally:
        prov.terminate_node(sid)
    deadline = time.time() + 30
    while time.time() < deadline:
        alive = [n for n in ray_tpu.nodes()
                 if n["alive"] and n["node_id"] in node_ids]
        if not alive:
            break
        time.sleep(0.5)
    assert not alive
