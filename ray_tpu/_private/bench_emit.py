"""Bench-record emission: the FINAL-bare-JSON-line contract, centralized.

The bench harness parses the **last line of captured output** as the
round's record; stdout and stderr are captured *merged*.  Every round in
which the multichip dryrun's record failed to parse traced back to one
of two leaks in hand-rolled ``print(json.dumps(...))`` endings:

- **interleave**: stderr (XLA sharding warnings, absl teardown chatter)
  is unbuffered while piped stdout is block-buffered, so bytes written
  to stderr *before* the record routinely landed *after* it in the
  merged capture — the harness then parsed a warning fragment;
- **failure skips emission**: any assert/raise before the final print
  exits with a traceback as the last output and no record at all;
- **post-record teardown chatter**: asyncio "Task was destroyed"
  warnings and other interpreter-exit output print after the last
  user statement, stealing the final line from the record.

:func:`emit_final_record` fixes the first (flush stderr, then write the
record flushed, as one atomic line); :func:`final_record_guard` fixes
the second (whatever happens inside the guard, a record — the real one
or a structured error record — is the last thing on stdout).
``raylint``'s ``bench-emission`` rule keeps every benchmark entrypoint
on these helpers so the contract can't silently regress again.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import tempfile
import traceback
from typing import Any, Dict, Iterator, Optional


def emit_record_line(record: Dict[str, Any]) -> None:
    """Print one intermediate bare-JSON record line, flushed — for
    benches that stream per-scenario/per-section records before the
    final headline record.

    The record is written with a LEADING newline: in a merged capture an
    unterminated stderr fragment (absl and XLA both write warnings in
    pieces) would otherwise glue onto the front of the record line and
    break the harness's ``json.loads(last_line)``.  A blank line in the
    stream is harmless; a half-warning prefix is not."""
    sys.stderr.flush()
    sys.stdout.write("\n" + json.dumps(record) + "\n")
    sys.stdout.flush()


def emit_final_record(record: Dict[str, Any]) -> None:
    """Emit the bench's FINAL record so it is the last parseable line of
    the merged (stdout+stderr) capture: everything buffered on either
    stream is flushed *first*, then the record is written as one line
    and flushed — and then both std streams are redirected to devnull,
    so teardown chatter (asyncio "Task was destroyed" warnings, logging
    shutdown, atexit hooks) cannot print after the record and steal the
    harness's last line.  Nothing may be printed after this call — the
    raylint ``bench-emission`` rule enforces that statically for the
    bench's own code, and the redirect enforces it for everyone else's
    interpreter-exit output.

    Post-record output is not discarded blind: it lands in a side-
    channel tail log (``RAY_TPU_BENCH_TAIL_LOG``, default
    ``<tmpdir>/ray_tpu_bench_tail_<pid>.log``) so a teardown crash after
    a success-shaped record still leaves its traceback somewhere a
    human can find it."""
    emit_record_line(record)
    tail_path = os.environ.get("RAY_TPU_BENCH_TAIL_LOG") or os.path.join(
        tempfile.gettempdir(), f"ray_tpu_bench_tail_{os.getpid()}.log")
    try:
        sink = open(tail_path, "w", buffering=1)
    except OSError:
        sink = open(os.devnull, "w")
    sys.stdout = sink
    sys.stderr = sink


@contextlib.contextmanager
def final_record_guard(metric: str, *,
                       detail: Optional[Dict[str, Any]] = None,
                       unit: str = "") -> Iterator[Dict[str, Any]]:
    """Guarantee a final bare-JSON record even when the bench body dies.

    Usage::

        with final_record_guard("llama_train_mfu_multichip") as out:
            ...  # bench body
            out["record"] = record        # the real record

    On clean exit the guard emits ``out["record"]``.  On an exception it
    prints the traceback to stderr, emits a structured zero-value error
    record (same ``metric``, ``value: 0.0``, the error in ``detail``) as
    the final line, and exits rc 1 via ``SystemExit`` — the harness
    still parses a record, and the nonzero rc still marks the failure.
    """
    out: Dict[str, Any] = {}
    try:
        yield out
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 — the record IS the report
        traceback.print_exc()
        err_detail = dict(detail or {})
        err_detail["error"] = f"{type(e).__name__}: {e}"
        emit_final_record({
            "metric": metric, "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "detail": err_detail,
        })
        raise SystemExit(1) from e
    record = out.get("record")
    if record is None:
        err_detail = dict(detail or {})
        err_detail["error"] = "bench body set no record"
        record = {"metric": metric, "value": 0.0, "unit": unit,
                  "vs_baseline": 0.0, "detail": err_detail}
    emit_final_record(record)
