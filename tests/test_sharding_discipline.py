"""Layout discipline: the sharded train step compiles with ZERO XLA
SPMD resharding warnings on every mesh the trainer path can form.

Three layers:

1. **Golden-sharding gate** (the satellite the multichip warning tails
   demanded): a subprocess with fd-captured stderr lowers + compiles
   the sharded Llama train step on the 8-device CPU mesh for every
   ``MESH_PRESETS`` entry AND the dryrun's multi-axis / hybrid meshes,
   asserting no "involuntary full rematerialization" / last-resort
   replicate line.  The same subprocess compiles the LEGACY constraint
   set (``RAY_TPU_LEGACY_SHARDING=1``) on the hybrid mesh and must see
   warnings there — proof the capture isn't vacuously quiet.
2. **Warning-capture units** — marker counting and the fd-level
   capture actually seeing C-level fd-2 writes.
3. **Donation** — the train step really donates the state buffers
   (update-in-place in HBM), and ``donate_batch=True`` extends that to
   the input buffers; the default keeps reusable batches alive.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_GOLDEN_WORKER = r'''
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ.pop("RAY_TPU_LEGACY_SHARDING", None)

import jax

from ray_tpu.models.llama import LlamaConfig
from ray_tpu.models.training import default_optimizer, make_llama_trainer
from ray_tpu.parallel import (
    MESH_PRESETS,
    MeshConfig,
    create_hybrid_mesh,
    create_mesh,
    resolve_mesh_config,
)
from ray_tpu.parallel.sharding import ENV_LEGACY_SHARDING
from ray_tpu.parallel.xla_warnings import sharding_warning_capture


def compile_count(mesh):
    """Compile (AOT, no execution) init + train step; count warnings."""
    cfg = LlamaConfig.tiny(num_heads=4, num_kv_heads=4, num_layers=2)
    with sharding_warning_capture(replay=False) as w:
        tr = make_llama_trainer(
            cfg, mesh, optimizer=default_optimizer(warmup=1, decay_steps=10))
        state = tr.init_state(jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 9), 0, cfg.vocab_size)
        batch = tr.shard_batch({"tokens": tokens})
        tr.compile(state, batch)
    return w["count"], w["lines"]


meshes = {name: create_mesh(resolve_mesh_config(name).clamp_to(8))
          for name in sorted(MESH_PRESETS)}
# the two dryrun layouts whose gathers produced the historical warning
# tails: every axis at once, and the 2-slice hybrid
meshes["dp_fsdp_tp_sp"] = create_mesh(MeshConfig(dp=1, fsdp=2, tp=2, sp=2))
meshes["hybrid_2slice"] = create_hybrid_mesh(
    ici_config=MeshConfig(dp=1, fsdp=2, tp=2), num_slices=2)

out = {"presets": {}, "lines": {}}
for name, mesh in meshes.items():
    count, lines = compile_count(mesh)
    out["presets"][name] = count
    if lines:
        out["lines"][name] = lines[:2]

# legacy arm on the hybrid mesh: the capture must SEE the resharding
# the old constraint set provokes, or the zeros above prove nothing
os.environ[ENV_LEGACY_SHARDING] = "1"
out["legacy_hybrid"], _ = compile_count(meshes["hybrid_2slice"])
os.environ.pop(ENV_LEGACY_SHARDING, None)

print("GOLDEN " + json.dumps(out))
'''


@pytest.fixture(scope="module")
def golden_result():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", _GOLDEN_WORKER],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO, timeout=900)
    assert proc.returncode == 0, proc.stdout[-4000:]
    line = next(ln for ln in proc.stdout.splitlines()
                if ln.startswith("GOLDEN "))
    return json.loads(line[len("GOLDEN "):])


class TestGoldenShardingGate:
    def test_every_preset_compiles_clean(self, golden_result):
        dirty = {name: n for name, n in golden_result["presets"].items()
                 if n != 0}
        assert not dirty, (
            f"SPMD resharding warnings on meshes {dirty}; first lines: "
            f"{golden_result['lines']}")

    def test_gate_covers_every_preset_and_the_dryrun_meshes(
            self, golden_result):
        from ray_tpu.parallel import MESH_PRESETS

        covered = set(golden_result["presets"])
        assert covered >= set(MESH_PRESETS) | {"dp_fsdp_tp_sp",
                                               "hybrid_2slice"}

    def test_legacy_constraints_still_warn(self, golden_result):
        """The capture is not vacuous: the pre-discipline constraint
        set reshards on the hybrid mesh and the counter sees it."""
        assert golden_result["legacy_hybrid"] >= 1


class TestWarningCaptureUnits:
    def test_marker_counting(self):
        from ray_tpu.parallel.xla_warnings import count_sharding_warnings

        text = (
            "2026-01-01: E spmd_partitioner.cc:613] [spmd] Involuntary "
            "full rematerialization. The compiler was not able ...\n"
            "some unrelated line\n"
            "... As the last resort, SPMD will replicate the tensor and "
            "then partition it to obtain the target sharding, which is "
            "inefficient ...\n")
        assert count_sharding_warnings(text) == 2
        assert count_sharding_warnings("all clean") == 0

    def test_fd_level_writes_are_captured_and_replayed(self, capfd):
        from ray_tpu.parallel.xla_warnings import capture_stderr_fd

        with capture_stderr_fd() as cap:
            os.write(2, b"raw fd2 write: Involuntary full "
                        b"rematerialization\n")
        assert "Involuntary full rematerialization" in cap["text"]
        # replayed: the bytes still reach the real stderr afterwards
        assert "raw fd2 write" in capfd.readouterr().err

    def test_capture_nests(self):
        from ray_tpu.parallel.xla_warnings import capture_stderr_fd

        with capture_stderr_fd(replay=False) as outer:
            os.write(2, b"outer-a\n")
            with capture_stderr_fd(replay=False) as inner:
                os.write(2, b"inner\n")
            os.write(2, b"outer-b\n")
        assert inner["text"] == "inner\n"
        assert "outer-a" in outer["text"] and "outer-b" in outer["text"]
        assert "inner" not in outer["text"]

    def test_legacy_env_gate_parsing(self, monkeypatch):
        from ray_tpu.parallel.sharding import (
            ENV_LEGACY_SHARDING,
            legacy_sharding_enabled,
        )

        monkeypatch.delenv(ENV_LEGACY_SHARDING, raising=False)
        assert not legacy_sharding_enabled()
        for val, want in (("1", True), ("true", True), ("YES", True),
                          ("0", False), ("", False), ("no", False)):
            monkeypatch.setenv(ENV_LEGACY_SHARDING, val)
            assert legacy_sharding_enabled() is want, val


class TestDonation:
    def _trainer(self, **kw):
        import jax

        from ray_tpu.models.llama import (
            LlamaConfig, llama_init, llama_loss, llama_param_specs,
        )
        from ray_tpu.models.training import ShardedTrainer, default_optimizer
        from ray_tpu.parallel import MeshConfig, create_mesh
        import functools

        mesh = create_mesh(MeshConfig(dp=1, fsdp=-1))
        cfg = LlamaConfig.tiny()
        tr = ShardedTrainer(
            functools.partial(llama_init, cfg=cfg),
            functools.partial(llama_loss, cfg=cfg, mesh=mesh),
            llama_param_specs(cfg),
            mesh=mesh,
            optimizer=default_optimizer(warmup=1, decay_steps=10),
            **kw)
        state = tr.init_state(jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 9), 0, cfg.vocab_size)
        batch = tr.shard_batch({"tokens": tokens})
        return tr, state, batch

    def test_step_donates_state_buffers(self):
        tr, state, batch = self._trainer()
        old_embed = state["params"]["embed"]
        new_state, _ = tr.step(state, batch)
        # the old tree's buffers were donated into the update — the
        # params copy can never serialize the step tail
        assert old_embed.is_deleted()
        assert not new_state["params"]["embed"].is_deleted()
        # the batch is NOT donated by default: benches and the H2D
        # stager legitimately feed the same buffers every step
        assert not batch["tokens"].is_deleted()
        tr.step(new_state, batch)  # reusable

    def test_donate_batch_opt_in(self):
        """The opt-in batch donation reaches XLA.  On the CPU test
        backend an int32 tokens buffer can alias no output, so the
        donation surfaces as jax's "not usable" warning — which is
        exactly the proof the donate_argnums plumbing carried it (the
        default trainer's step raises no such warning; see
        test_step_donates_state_buffers)."""
        import warnings

        tr, state, batch = self._trainer(donate_batch=True)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            tr.step(state, batch)
        assert any("donated buffers were not usable" in str(x.message)
                   for x in w), [str(x.message) for x in w]


class TestLayoutParity:
    def test_fixed_and_legacy_losses_match(self, monkeypatch):
        """The discipline changes layouts, never numerics: same mesh,
        same params, same batch -> bit-for-bit equal loss."""
        import jax

        from ray_tpu.models.llama import LlamaConfig, llama_init, llama_loss
        from ray_tpu.parallel import MeshConfig, create_mesh
        from ray_tpu.parallel.sharding import ENV_LEGACY_SHARDING

        mesh = create_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
        cfg = LlamaConfig.tiny(num_heads=4, num_kv_heads=4, num_layers=2)
        params = llama_init(jax.random.PRNGKey(0), cfg)
        batch = {"tokens": jax.random.randint(
            jax.random.PRNGKey(1), (8, 9), 0, cfg.vocab_size)}

        def loss():
            with mesh:
                return float(jax.jit(
                    lambda p, b: llama_loss(p, b, cfg, mesh=mesh))(
                        params, batch))

        monkeypatch.delenv(ENV_LEGACY_SHARDING, raising=False)
        fixed = loss()
        monkeypatch.setenv(ENV_LEGACY_SHARDING, "1")
        legacy = loss()
        assert fixed == legacy
        assert np.isfinite(fixed)
