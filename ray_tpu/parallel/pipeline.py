"""In-graph pipeline parallelism over the ``pp`` mesh axis.

The reference delegates pipeline parallelism to vLLM
(``python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_models.py:127``
``pipeline_parallel_size`` → placement-group bundles) and provides only the
channel substrate for inter-actor pipelining
(``python/ray/dag/dag_node_operation.py``).  Here PP is a first-class mesh
axis like dp/fsdp/tp/sp, implemented the TPU way:

- layer-stacked params are sharded over ``pp`` (each stage holds
  ``L / pp_size`` contiguous layers);
- the microbatch schedule is a ``lax.scan`` of compute+``ppermute`` ticks
  inside a *partial-manual* ``shard_map`` — only ``pp`` is manual, the
  other axes stay auto so GSPMD keeps inserting the dp/fsdp/tp collectives
  from sharding annotations;
- reverse-mode AD transposes the ``ppermute`` ring, so the backward pass is
  the mirrored pipeline schedule for free.  With per-layer remat the live
  state per stage is one microbatch activation + the output buffer, which
  is the 1F1B memory profile (activations for at most the in-flight
  microbatches, not all of them).

Bubble fraction is ``(S-1) / (M + S - 1)`` for S stages and M microbatches;
raise ``num_microbatches`` to amortize.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def pp_size(mesh: Optional[Mesh], axis: str = "pp") -> int:
    """Number of pipeline stages in the mesh (1 when no pp axis)."""
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return mesh.shape[axis]


def pipeline_apply(
    layer_fn: Callable[[jnp.ndarray, Any], jnp.ndarray],
    stacked_params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    num_microbatches: Optional[int] = None,
    axis: str = "pp",
) -> jnp.ndarray:
    """Run ``x`` through L stacked layers pipelined over the ``axis`` stages.

    ``layer_fn(x, layer_params) -> x`` is the per-layer body (already
    remat-wrapped by the caller if desired).  ``stacked_params`` is a pytree
    whose leaves have a leading layer dimension L, sharded over ``axis``
    (each stage owns a contiguous block of L/S layers).  ``x`` is
    ``[batch, ...]`` and must be divisible into ``num_microbatches``.

    Returns the activations after all L layers, same shape as ``x``.
    """
    S = pp_size(mesh, axis)
    if S == 1:
        def body(carry, lp):
            return layer_fn(carry, lp), None
        out, _ = jax.lax.scan(body, x, stacked_params)
        return out

    M = num_microbatches or S
    b = x.shape[0]
    if b % M != 0:
        raise ValueError(f"batch {b} not divisible by {M} microbatches")
    n_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_layers % S != 0:
        raise ValueError(f"{n_layers} layers not divisible by {S} stages")

    micro = x.reshape((M, b // M) + x.shape[1:])

    def stage_body(state, layers_shard):
        def body(carry, lp):
            return layer_fn(carry, lp), None
        out, _ = jax.lax.scan(body, state, layers_shard)
        return out

    def pipelined(layers_shard, micro):
        stage = jax.lax.axis_index(axis)
        state = jnp.zeros_like(micro[0])
        outputs = jnp.zeros_like(micro)

        def tick(carry, t):
            state, outputs = carry
            # Stage 0 ingests microbatch t (clamped; masked off past M).
            inp = jax.lax.dynamic_index_in_dim(
                micro, jnp.minimum(t, M - 1), axis=0, keepdims=False
            )
            state = jnp.where(stage == 0, inp, state)
            state = stage_body(state, layers_shard)
            # Last stage emits microbatch t-(S-1) once the fill completes.
            out_idx = t - (S - 1)
            emit = (stage == S - 1) & (out_idx >= 0)
            emitted = jax.lax.dynamic_update_index_in_dim(
                outputs, state, jnp.maximum(out_idx, 0), axis=0
            )
            outputs = jnp.where(emit, emitted, outputs)
            # Rotate activations one stage down the ring.
            state = jax.lax.ppermute(
                state, axis, [(i, (i + 1) % S) for i in range(S)]
            )
            return (state, outputs), None

        (state, outputs), _ = jax.lax.scan(
            tick, (state, outputs), jnp.arange(M + S - 1)
        )
        # Only the last stage holds real outputs; psum replicates them
        # across the pp ring (zeros elsewhere) so out_specs can be P().
        outputs = jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs))
        return jax.lax.psum(outputs, axis)

    shard_spec = jax.tree.map(lambda _: P(axis), stacked_params)
    # Partial-manual shard_map: only `axis` manual, rest auto.  Modern
    # jax spells that `jax.shard_map(..., axis_names={axis},
    # check_vma=False)`; on older jax (< 0.6) the same program is the
    # legacy `jax.experimental.shard_map.shard_map(..., auto=<the other
    # mesh axes>, check_rep=False)`.  Try modern first, fall back, and
    # only fail — with a clear version message — when neither spelling
    # exists.
    try:
        mapped = jax.shard_map(
            pipelined,
            mesh=mesh,
            in_specs=(shard_spec, P()),
            out_specs=P(),
            axis_names={axis},
            check_vma=False,
        )
    except (AttributeError, TypeError):
        try:
            from jax.experimental.shard_map import shard_map as _legacy

            mapped = _legacy(
                pipelined,
                mesh=mesh,
                in_specs=(shard_spec, P()),
                out_specs=P(),
                check_rep=False,
                auto=frozenset(n for n in mesh.axis_names if n != axis),
            )
        except (ImportError, AttributeError, TypeError) as e:
            raise RuntimeError(
                "pipeline parallelism needs a shard_map with "
                "partial-manual axis support (jax.shard_map axis_names= "
                "on jax >= 0.6, or jax.experimental.shard_map auto= on "
                "0.4.x); this jax has neither"
            ) from e
    out = mapped(stacked_params, micro)
    return out.reshape(x.shape)


def pipeline_microbatches(cfg_microbatches: Optional[int], mesh: Mesh,
                          axis: str = "pp") -> int:
    """Default microbatch count: 2*stages (25%→~14% bubble vs M=S)."""
    return cfg_microbatches or 2 * pp_size(mesh, axis)


def reject_pp(mesh: Optional[Mesh], family: str, rules=None):
    """Guard for model families without a pipeline apply path.

    Raises on pp>1 meshes, and — only when the caller supplied no rule
    table of their own — replicates stacked layers over pp instead of
    stage-sharding them (a stage-sharded stack under a plain lax.scan
    would all-gather every layer, every step).  Returns the rule table to
    use.
    """
    if pp_size(mesh) > 1:
        raise ValueError(
            f"{family} has no pipeline (pp) apply path; use dp/fsdp/tp/sp "
            "axes (pp is llama-only for now)"
        )
    if rules is None:
        from ray_tpu.parallel.sharding import DEFAULT_RULES

        return {**DEFAULT_RULES, "layers": None}
    return rules
