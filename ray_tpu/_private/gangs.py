"""Gang scheduling primitives: states, victim selection, claims.

A *gang* is the GCS-side identity of a placement group: an atomic
all-or-nothing reservation moving through a persisted state machine

    PENDING -> RESERVING -> PLACED -> (PREEMPTING | FAILED) -> REMOVED

(``FAILED`` re-enters ``PENDING`` for ``restartable=True`` gangs — the
train controller's mode).  Every transition is written through the
GCS's persisted gang table by ``GcsServer._gang_transition`` (enforced
by the ``gang-table-discipline`` raylint checker): a crash between any
two transitions restores to a consistent state, and the audit contract
holds — outside the RESERVING window a gang's raylet-side reservations
are either complete or empty, never partial.

This module keeps the *pure* pieces (state vocabulary, deterministic
victim selection) import-light so the scheduler tests exercise them
without a GCS.

Victim selection (priority preemption)
--------------------------------------

When a priority-P gang is infeasible but would fit by evicting
strictly-lower-priority PLACED gangs, :func:`select_victims` picks the
victim set deterministically:

1. **fewest gangs disturbed** — every single-victim solution is tried
   before any multi-victim one;
2. **lowest priority first** — candidates are ordered by ascending
   priority so the cheapest tenants are disturbed first;
3. **seeded tiebreak** — equal-priority candidates are ordered by a
   ``random.Random(seed)`` shuffle keyed on the preemptor's id (the
   ``chaos.py`` determinism contract: same spec + same seed => same
   victims, unit-tested).
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu._private import scheduling
from ray_tpu._private.scheduling import NodeView, ResourceSet

# the persisted gang state machine (docs/fault_tolerance.md)
GANG_STATES = ("PENDING", "RESERVING", "PLACED", "PREEMPTING", "FAILED",
               "REMOVED")
#: states whose gangs still own (or may own) capacity / claims
ACTIVE_STATES = ("PENDING", "RESERVING", "PLACED", "PREEMPTING")
#: terminal states: all reservations provably released
TERMINAL_STATES = ("FAILED", "REMOVED")


def tiebreak_rng(seed: int, preemptor_id: bytes) -> random.Random:
    """One seeded rng per (cluster seed, preemptor): victim choice is a
    pure function of the spec, never of arrival jitter."""
    return random.Random(f"{seed}|{bytes(preemptor_id).hex()}")


def _views_with_released(views: Sequence[NodeView],
                         victims: Sequence[Dict[str, Any]]) -> List[NodeView]:
    """Simulated cluster view with every victim's reserved bundles
    returned to availability."""
    out = [NodeView(v.node_id, v.total.to_dict(), v.available.to_dict(),
                    dict(v.labels), v.alive) for v in views]
    by_id = {v.node_id: v for v in out}
    for victim in victims:
        placement = victim.get("placement") or []
        bundles = victim.get("bundles") or []
        for node_id, bundle in zip(placement, bundles):
            node = by_id.get(node_id)
            if node is not None:
                node.available.add(ResourceSet(bundle))
    return out


def select_victims(
    bundles: List[Dict[str, float]],
    strategy: str,
    priority: int,
    preemptor_id: bytes,
    views: Sequence[NodeView],
    placed_gangs: Sequence[Dict[str, Any]],
    seed: int = 0,
    exclude_node_ids: Optional[set] = None,
) -> Optional[List[bytes]]:
    """Pick the gangs to evict so ``bundles`` becomes placeable.

    ``placed_gangs`` entries carry ``gang_id``, ``priority``,
    ``placement`` (node per bundle) and ``bundles``.  Only strictly
    lower-priority gangs are candidates.  Returns the victim gang ids
    (deterministic for equal inputs + seed) or None when no eviction of
    lower-priority gangs makes the gang fit.
    """
    candidates = [g for g in placed_gangs
                  if g.get("priority", 0) < priority
                  and g.get("placement")]
    if not candidates:
        return None
    rng = tiebreak_rng(seed, preemptor_id)
    tiebreak = {id(g): rng.random() for g in sorted(
        candidates, key=lambda g: bytes(g["gang_id"]))}
    candidates.sort(key=lambda g: (g.get("priority", 0), tiebreak[id(g)]))

    def fits(victims: Sequence[Dict[str, Any]]) -> bool:
        trial = _views_with_released(views, victims)
        return scheduling.pack_bundles(
            trial, bundles, strategy,
            exclude_node_ids=exclude_node_ids) is not None

    # fewest-gangs-disturbed: any single victim beats every pair
    for g in candidates:
        if fits([g]):
            return [g["gang_id"]]
    # greedy accumulation in (priority, tiebreak) order
    acc: List[Dict[str, Any]] = []
    for g in candidates:
        acc.append(g)
        if fits(acc):
            return [v["gang_id"] for v in acc]
    return None
