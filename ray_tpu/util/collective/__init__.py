"""Out-of-band collectives between actors/tasks.

Parity: ``ray.util.collective`` (``python/ray/util/collective/collective.py``
— init_collective_group :123, create_collective_group :160, allreduce :268,
barrier :308, reduce :321, broadcast :383, allgather :433, reducescatter
:482, send :541, recv :604).  Backends are TCP (GLOO role) and XLA (NCCL
role, over ICI) — no CUDA anywhere.
"""

from ray_tpu.util.collective.collective import (  # noqa: F401
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    flight_recorder_dump,
    get_group_state,
    get_rank,
    get_collective_group_size,
    init_collective_group,
    is_group_initialized,
    recv,
    reduce,
    reducescatter,
    send,
)
from ray_tpu.util.collective.types import (  # noqa: F401
    Backend,
    GroupState,
    ReduceOp,
)
