"""Memory monitor + OOM worker-killing policies (reference:
``src/ray/common/memory_monitor.h:52``, ``worker_killing_policy*.h``)."""

import time

from ray_tpu._private.memory_monitor import (
    MemoryMonitor,
    pick_victim,
    process_rss_bytes,
    system_memory_usage,
)


class FakeWorker:
    """Mirrors WorkerHandle + the raylet's lease dict shape
    (``raylet.py`` ``worker.lease = {"owner": ..., "granted_at": ...}``)."""

    def __init__(self, pid, started_at, owner=None, granted_at=None,
                 dedicated=False):
        self.pid = pid
        self.started_at = started_at
        self.lease = (
            None if owner is None
            else {"owner": owner,
                  "granted_at": granted_at if granted_at is not None
                  else started_at}
        )
        self.dedicated = dedicated


def test_system_memory_usage_sane():
    used, total = system_memory_usage()
    assert 0 < used <= total


def test_process_rss_self():
    import os

    assert process_rss_bytes(os.getpid()) > 1024 * 1024


def test_idle_workers_die_first():
    idle_old = FakeWorker(1, 10.0)
    idle_new = FakeWorker(2, 20.0)
    busy = FakeWorker(3, 5.0, owner="a")
    assert pick_victim([busy, idle_old, idle_new]) is idle_new


def test_retriable_fifo_kills_newest_lease():
    old = FakeWorker(1, 10.0, owner="a")
    new = FakeWorker(2, 20.0, owner="b")
    actor = FakeWorker(3, 30.0, owner="c", dedicated=True)
    # Newest non-actor lease dies; actors are last resorts.
    assert pick_victim([old, new, actor], "retriable_fifo") is new
    assert pick_victim([actor], "retriable_fifo") is actor


def test_retriable_fifo_orders_by_lease_grant_not_spawn_time():
    # Old prestarted worker that JUST got a task vs a young worker whose
    # task has been running for a while: the just-granted lease dies.
    old_worker_new_lease = FakeWorker(1, started_at=10.0, owner="a",
                                      granted_at=100.0)
    new_worker_old_lease = FakeWorker(2, started_at=50.0, owner="b",
                                      granted_at=60.0)
    assert pick_victim(
        [old_worker_new_lease, new_worker_old_lease], "retriable_fifo"
    ) is old_worker_new_lease


def test_group_by_owner_targets_biggest_group():
    a1 = FakeWorker(1, 10.0, owner="a")
    a2 = FakeWorker(2, 20.0, owner="a")
    b1 = FakeWorker(3, 30.0, owner="b")
    assert pick_victim([a1, a2, b1], "group_by_owner") is a2


def test_group_by_owner_prefers_retriable_over_actor():
    task = FakeWorker(1, 10.0, owner="a")
    actor = FakeWorker(2, 20.0, owner="a", dedicated=True)
    b1 = FakeWorker(3, 30.0, owner="b")
    assert pick_victim([task, actor, b1], "group_by_owner") is task


def test_no_workers_no_victim():
    assert pick_victim([]) is None


def test_monitor_threshold_and_rate_limit():
    usage = {"v": (50, 100)}
    mon = MemoryMonitor(usage_fn=lambda: usage["v"], threshold=0.9,
                        min_kill_interval_s=60.0,
                        rss_fn=lambda pid: 50)  # workers own the usage
    w = FakeWorker(1, 10.0, owner="a")
    assert mon.maybe_pick_victim([w]) is None  # below threshold
    usage["v"] = (95, 100)
    assert mon.maybe_pick_victim([w]) is w
    # Rate limited: second pressure reading doesn't immediately kill again.
    assert mon.maybe_pick_victim([w]) is None


def test_monitor_skips_external_pressure():
    """Shared-host tenant pushes node memory over the threshold while our
    workers are tiny: killing them frees nothing, so the monitor abstains."""
    mon = MemoryMonitor(usage_fn=lambda: (95, 100), threshold=0.9,
                        min_kill_interval_s=0.0,
                        rss_fn=lambda pid: 1)  # 1B of 95B used: external
    w = FakeWorker(1, 10.0, owner="a")
    assert mon.maybe_pick_victim([w]) is None
    # Same pressure but the workers own it: kill proceeds.
    mon2 = MemoryMonitor(usage_fn=lambda: (95, 100), threshold=0.9,
                         min_kill_interval_s=0.0,
                         rss_fn=lambda pid: 90)
    assert mon2.maybe_pick_victim([w]) is w
