"""End-to-end: ASHA hyperparameter sweep over a toy objective.

Run: python examples/tune_asha.py
"""

import ray_tpu
from ray_tpu import tune


def objective(config):
    acc = 0.0
    for _ in range(20):
        acc += config["lr"] * (1.0 - acc)
        tune.report({"acc": acc})


def main():
    ray_tpu.init()
    grid = tune.Tuner(
        objective,
        param_space={"lr": tune.loguniform(1e-3, 1.0)},
        tune_config=tune.TuneConfig(
            metric="acc", mode="max", num_samples=8,
            scheduler=tune.ASHAScheduler(grace_period=2, max_t=20),
            max_concurrent_trials=4, seed=0),
    ).fit()
    best = grid.get_best_result()
    print(f"best acc={best.metrics['acc']:.3f} lr={best.config['lr']:.4f}")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
