"""ray_tpu.rl: reinforcement learning (reference: ``rllib/``).

PPO with jitted in-graph rollouts for jax envs (TPU fast path) or
EnvRunner actors for python/gym envs (the reference's architecture).
"""

from ray_tpu.rl.algorithm import PPO, Algorithm, AlgorithmConfig
from ray_tpu.rl.bc import BC, MARWIL, MARWILParams
from ray_tpu.rl.cql import CQL, CQLParams
from ray_tpu.rl.dqn import DQN, DQNConfig, DQNParams, ReplayBuffer
from ray_tpu.rl.dreamer import DreamerParams, DreamerV3
from ray_tpu.rl.impala import APPO, IMPALA, ImpalaLearner, ImpalaParams, vtrace
from ray_tpu.rl.sac import SAC, SACConfig, SACParams
from ray_tpu.rl.env import (
    CartPoleEnv,
    EnvSpec,
    GymVectorEnv,
    JaxVectorEnv,
    make_env,
    register_env,
)
from ray_tpu.rl.env_runner import EnvRunner, EnvRunnerGroup
from ray_tpu.rl.models import ActorCriticModule
from ray_tpu.rl.multi_agent_env import JaxMultiAgentEnv, PursuitTagEnv
from ray_tpu.rl.multi_agent_ppo import (
    MultiAgentPPO,
    make_multi_agent_rollout_fn,
)
from ray_tpu.rl.ppo import PPOConfig, PPOLearner, compute_gae
from ray_tpu.rl.rlhf import (
    RLHFConfig,
    RLHFLoop,
    RolloutActor,
    RolloutGroup,
    TrajectoryLedger,
)
from ray_tpu.rl.weight_sync import (
    NoWeightsPublishedError,
    WeightPublisher,
    WeightSubscriber,
    WeightSyncError,
    WeightVersion,
    WeightsStaleError,
)

__all__ = [
    "APPO", "BC", "CQL", "CQLParams", "DQN", "DQNConfig", "DQNParams",
    "DreamerParams", "DreamerV3", "IMPALA",
    "ImpalaLearner", "ImpalaParams", "MARWIL", "MARWILParams",
    "ReplayBuffer", "PPO", "SAC", "SACConfig", "SACParams",
    "Algorithm", "AlgorithmConfig", "ActorCriticModule",
    "CartPoleEnv", "EnvRunner", "EnvRunnerGroup", "EnvSpec", "GymVectorEnv",
    "JaxMultiAgentEnv", "JaxVectorEnv", "MultiAgentPPO",
    "NoWeightsPublishedError", "PPOConfig",
    "PPOLearner", "PursuitTagEnv", "RLHFConfig", "RLHFLoop",
    "RolloutActor", "RolloutGroup", "TrajectoryLedger",
    "WeightPublisher", "WeightSubscriber", "WeightSyncError",
    "WeightVersion", "WeightsStaleError", "compute_gae",
    "make_multi_agent_rollout_fn", "make_env", "register_env", "vtrace",
]
