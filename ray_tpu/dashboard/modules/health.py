"""Health module: node health ladder + straggler/SDC verdicts.

The health plane (:mod:`ray_tpu._private.health_plane`) publishes one
verdict record per suspect into the GCS KV under namespace "health"
(key ``verdict/<kind>/<subject>``) and moves nodes along the
HEALTHY -> SUSPECT -> QUARANTINED ladder in the GCS node table.  This
module serves both through the same ``aggregate_health_records`` helper
the state API and ``raytpu health`` use, so all three surfaces agree on
ordering and on the staleness sweep (a verdict from a monitor that died
mid-run must not pin a SUSPECT forever).
"""

from __future__ import annotations

import json


def routes(gcs, helpers):
    jresp = helpers["jresp"]

    async def api_health(_req):
        from ray_tpu.util.health import aggregate_health_records

        nodes = []
        for nid, n in gcs.nodes.items():
            nodes.append({
                "node_id": nid,
                "state": n.get("state",
                               "ALIVE" if n.get("alive") else "DEAD"),
                "health": n.get("health", "HEALTHY"),
                "health_reason": n.get("health_reason", ""),
                "hw_confirmed": bool(n.get("health_hw_confirmed")),
                # per-device HBM occupancy rides the heartbeat stats
                "devices": (n.get("stats") or {}).get("devices", []),
            })
        records = []
        for (ns, key), raw in list(gcs.kv.items()):
            if ns != "health" or not key.startswith("verdict/"):
                continue
            try:
                records.append(json.loads(raw))
            except (ValueError, TypeError):
                continue
        return jresp({"nodes": nodes,
                      "verdicts": aggregate_health_records(records)})

    return [("GET", "/api/health", api_health)]
