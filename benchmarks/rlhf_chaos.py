"""RLHF chaos crucible: the standing integration scenario under faults.

Drives the end-to-end rollout → reward → update loop
(``ray_tpu/rl/rlhf.py``) with one deterministic fault per scenario and
asserts the loop's invariants survived:

- the loop completes every configured iteration;
- no trajectory batch is ever double-counted
  (``duplicates_rejected == 0`` and ``consumed + dropped == expected``);
- consumed weight versions are monotonically non-decreasing;
- (where armed) the fault actually fired.

Scenarios (``--scenario``; default runs the fast set):

==================  =======================================================
name                fault
==================  =======================================================
``baseline``        none — the loop itself
``publish_fault``   retryable fault at ``rl.weight_sync.publish`` (the
                    torn-publish seam: version commits only after payload)
``reward_fault``    retryable fault at ``rl.reward.score``
``rollout_kill``    SIGKILL one rollout actor with its sample in flight
                    (drop accounting + bounded respawn)
``rollout_hang``    ``delay`` kind at ``rl.rollout.sample`` — a hung
                    generator is cancelled at the sample deadline
``rollout_sigkill`` ``sigkill`` kind at ``rl.rollout.sample`` — a real
                    mid-sample process death in every rollout actor
``gcs_flake``       retryable faults at the existing ``gcs_store.call``
                    site while the loop runs (control-plane chaos)
``serve_reward``    reward model hosted behind serve; a fault at the
                    existing ``serve.router.assign`` site is absorbed by
                    the serving layer's own retry
``drain``           drain the node hosting the train worker mid-epoch:
                    checkpoint → elastic restart → publication resumes
                    above the committed version (multi-node; slow)
``collective``      2 train workers; ``delay`` at the existing
                    ``collective.op`` site aborts the supervised group →
                    controller restarts from the checkpoint (slow)
==================  =======================================================

Usage::

    python benchmarks/rlhf_chaos.py                 # fast set
    python benchmarks/rlhf_chaos.py --scenario drain
    python benchmarks/rlhf_chaos.py --all           # everything (slow)

Each scenario emits one structured JSON record; the driver exits nonzero
if any invariant failed.  The slow-marked tests in ``tests/test_rlhf.py``
call :func:`run_scenario` directly.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu._private.bench_emit import emit_final_record, emit_record_line

FAST_SCENARIOS = ["baseline", "publish_fault", "reward_fault",
                  "rollout_kill", "rollout_hang", "gcs_flake"]
SLOW_SCENARIOS = ["rollout_sigkill", "serve_reward", "drain", "collective"]


def _base_config(name: str, **overrides) -> "Any":
    from ray_tpu.rl.rlhf import RLHFConfig

    kw: Dict[str, Any] = dict(
        iterations=4, num_rollout_actors=2, rollout_batch=32,
        learner_batch_size=32, name=name, mesh="dp",
        sample_timeout_s=20.0, stale_timeout_s=20.0,
        verify_weights_on_read=True,
    )
    kw.update(overrides)
    return RLHFConfig(**kw)


def _check_invariants(result, *, expect_drops: bool = False,
                      expect_fired: Optional[str] = None,
                      min_iterations: Optional[int] = None) -> List[str]:
    """The crucible's acceptance gates; returns human-readable failures."""
    problems: List[str] = []
    if result.error is not None:
        return [f"loop failed: {result.error}"]
    m = result.metrics or {}
    want_iters = min_iterations or 0
    if m.get("training_iteration", 0) < want_iters:
        problems.append(
            f"only {m.get('training_iteration')} iterations completed "
            f"(wanted {want_iters})")
    if m.get("duplicates_rejected", 0) != 0:
        problems.append(
            f"trajectories double-counted: duplicates_rejected="
            f"{m['duplicates_rejected']}")
    cv = m.get("consumed_versions", [])
    if any(a > b for a, b in zip(cv, cv[1:])):
        problems.append(f"consumed weight versions regressed: {cv}")
    if m.get("trajectories_consumed", 0) > m.get("trajectories_produced", 0):
        problems.append("consumed more trajectories than produced")
    if expect_drops and m.get("trajectories_dropped", 0) < 1:
        problems.append("expected dropped trajectories, saw none")
    if expect_fired and m.get(expect_fired, 0) < 1:
        problems.append(f"fault never fired ({expect_fired}=0)")
    return problems


def _run_loop(cfg, *, max_failures: int = 0):
    from ray_tpu.rl.rlhf import RLHFLoop

    return RLHFLoop(cfg).run()


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def _scenario_baseline() -> Dict[str, Any]:
    cfg = _base_config("chaos-baseline")
    result = _run_loop(cfg)
    return {"result": result,
            "problems": _check_invariants(result, min_iterations=4)}


def _scenario_publish_fault() -> Dict[str, Any]:
    cfg = _base_config("chaos-publish", chaos={"publish_fault_at": 2})
    result = _run_loop(cfg)
    return {"result": result, "problems": _check_invariants(
        result, expect_fired="publish_faults_fired", min_iterations=4)}


def _scenario_reward_fault() -> Dict[str, Any]:
    cfg = _base_config("chaos-reward", chaos={"reward_fault_at": 2})
    result = _run_loop(cfg)
    return {"result": result, "problems": _check_invariants(
        result, expect_fired="reward_faults_fired", min_iterations=4)}


def _scenario_rollout_kill() -> Dict[str, Any]:
    cfg = _base_config("chaos-kill", chaos={"kill_rollout_at_iter": 2})
    result = _run_loop(cfg)
    return {"result": result, "problems": _check_invariants(
        result, expect_drops=True, min_iterations=4)}


def _env_armed(spec: str):
    """Context manager: arm the registry via the env for every process
    the cluster spawns while the scenario runs."""
    import contextlib

    from ray_tpu.util import fault_injection as fi

    @contextlib.contextmanager
    def armed():
        old = os.environ.get(fi.ENV_VAR)
        os.environ[fi.ENV_VAR] = spec
        try:
            yield
        finally:
            if old is None:
                os.environ.pop(fi.ENV_VAR, None)
            else:
                os.environ[fi.ENV_VAR] = old

    return armed()


def _run_loop_with_armed_cluster(spec: str, cfg):
    """Env-armed scenarios need the spec in the environment BEFORE the
    cluster starts: raylet-spawned worker processes inherit the
    raylet's env, not the driver's, so arming after init never reaches
    the rollout actors."""
    import ray_tpu

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    with _env_armed(spec):
        ray_tpu.init(num_cpus=8, num_tpus=0)
        try:
            return _run_loop(cfg)
        finally:
            ray_tpu.shutdown()


def _scenario_rollout_hang() -> Dict[str, Any]:
    # every rollout actor's 2nd sample hangs for 60s; the 5s sample
    # deadline cancels it and the iteration proceeds on drop accounting
    cfg = _base_config("chaos-hang", sample_timeout_s=5.0,
                      respawn_budget=0, iterations=3)
    result = _run_loop_with_armed_cluster(
        "rl.rollout.sample:2:1:delay:60", cfg)
    return {"result": result, "problems": _check_invariants(
        result, expect_drops=True, min_iterations=3)}


def _scenario_rollout_sigkill() -> Dict[str, Any]:
    # a REAL mid-sample crash in each actor's 2nd sample
    cfg = _base_config("chaos-sigkill", iterations=3,
                      respawn_budget=4)
    result = _run_loop_with_armed_cluster(
        "rl.rollout.sample:2:1:sigkill", cfg)
    return {"result": result, "problems": _check_invariants(
        result, expect_drops=True, min_iterations=3)}


def _scenario_gcs_flake() -> Dict[str, Any]:
    # control-plane chaos at the existing gcs_store.call site while the
    # loop runs; the resilience layer's retries absorb it
    cfg = _base_config("chaos-gcs", iterations=3)
    result = _run_loop_with_armed_cluster(
        "gcs_store.call:10:2:connection", cfg)
    return {"result": result,
            "problems": _check_invariants(result, min_iterations=3)}


def _serve_reward_fn(obs, actions, cfg):
    """Reward routed through a serve deployment (picklable module-level
    fn; the handle is resolved inside the train worker)."""
    from ray_tpu import serve

    handle = serve.get_deployment_handle("rlhf-reward")
    return handle.remote(obs.tolist(), actions.tolist()).result(timeout=30)


def _scenario_serve_reward() -> Dict[str, Any]:
    import numpy as np

    from ray_tpu import serve
    from ray_tpu.rl.rlhf import _gold_matrix

    base = _base_config("chaos-serve")

    @serve.deployment(name="rlhf-reward", num_replicas=1)
    class RewardModel:
        def __init__(self, gold):
            self.gold = np.asarray(gold, np.float32)

        def __call__(self, obs, actions):
            obs = np.asarray(obs, np.float32)
            actions = np.asarray(actions)
            gold = np.argmax(obs @ self.gold, axis=-1)
            return (actions == gold).astype(np.float32)

    serve.run(RewardModel.bind(_gold_matrix(base).tolist()))
    try:
        cfg = _base_config("chaos-serve", iterations=3,
                          reward_fn=_serve_reward_fn)
        with _env_armed("serve.router.assign:2:1:connection"):
            result = _run_loop(cfg)
        return {"result": result,
                "problems": _check_invariants(result, min_iterations=3)}
    finally:
        serve.shutdown()


def _scenario_drain(tmp_dir: Optional[str] = None) -> Dict[str, Any]:
    """Multi-node: drain the node hosting the train worker mid-epoch.
    The controller checkpoints, restarts the worker off the draining
    node, and weight publication resumes above the committed version."""
    import tempfile

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.state import drain_node, list_actors

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        cluster.connect()
        cluster.add_node(num_cpus=4, resources={"trainer_slot": 2})
        cluster.add_node(num_cpus=4, resources={"trainer_slot": 2})
        cluster.wait_for_nodes()

        drained: Dict[str, Any] = {}

        def drainer():
            deadline = time.time() + 120
            while time.time() < deadline:
                try:
                    actors = list_actors()
                except Exception:  # noqa: BLE001 — control plane busy
                    time.sleep(0.3)
                    continue
                for a in actors:
                    if a.get("state") == "ALIVE" and \
                            "TrainWorker" in (a.get("class_name") or "") \
                            and a.get("node_id"):
                        # let it get through iteration ~1 first
                        time.sleep(3.0)
                        drained["ack"] = drain_node(
                            a["node_id"], reason="chaos: spot reclaim",
                            deadline_s=15.0)
                        drained["node"] = a["node_id"]
                        return
                time.sleep(0.3)

        t = threading.Thread(target=drainer, daemon=True)
        t.start()
        storage = tmp_dir or tempfile.mkdtemp(prefix="rlhf-chaos-drain-")
        from ray_tpu import train

        cfg = _base_config(
            "chaos-drain", iterations=6, use_channel=False,
            storage_path=storage, max_failures=2)
        from ray_tpu.rl.rlhf import RLHFLoop

        run_config = train.RunConfig(
            name="rlhf-chaos-drain", storage_path=storage,
            failure_config=train.FailureConfig(max_failures=2))
        # pin the worker off the head so the drained node never hosts
        # the driver
        trainer = train.JaxTrainer(
            _drain_loop_entry,
            train_loop_config={"rlhf": _cfg_dict(cfg)},
            scaling_config=train.ScalingConfig(
                num_workers=1, mesh=cfg.mesh,
                resources_per_worker={"CPU": 1, "trainer_slot": 1}),
            run_config=run_config,
        )
        result = trainer.fit()
        t.join(timeout=5)
        problems = _check_invariants(result, min_iterations=6)
        if "node" not in drained:
            problems.append("drainer never found the train worker")
        elif not drained["ack"].get("accepted"):
            problems.append(f"drain not accepted: {drained['ack']}")
        m = result.metrics or {}
        if not problems and m.get("publisher_epoch", 0) < 1:
            problems.append(
                "loop never restarted (publisher epoch still 0) — the "
                "drain did not exercise the elastic-restart path")
        return {"result": result, "problems": problems, "drained": drained}
    finally:
        cluster.shutdown()


def _cfg_dict(cfg) -> Dict[str, Any]:
    import dataclasses

    return dataclasses.asdict(cfg)


def _drain_loop_entry(config):
    from ray_tpu.rl.rlhf import _rlhf_train_loop

    return _rlhf_train_loop(config)


def _scenario_collective() -> Dict[str, Any]:
    """2 train workers form the supervised collective group; an injected
    ``delay`` at the existing ``collective.op`` site hangs one allreduce
    past the watchdog timeout → CollectiveAbortError → controller
    restart from the checkpoint.  Armed in-process by the last rank's
    FIRST incarnation only (see RLHFConfig.chaos), so the sequence
    terminates instead of re-injecting every generation."""
    cfg = _base_config(
        "chaos-collective", iterations=4, num_workers=2,
        num_rollout_actors=1, use_channel=False, max_failures=2,
        sample_timeout_s=15.0,
        # op ~20 lands inside iteration 2's allreduce round, after
        # iteration 1's checkpoint committed
        chaos={"collective_fault_op": 20})
    result = _run_loop(cfg)
    problems = _check_invariants(result, min_iterations=4)
    m = result.metrics or {}
    if not problems and m.get("publisher_epoch", 0) < 1:
        problems.append(
            "collective abort never restarted the loop (epoch still 0)")
    return {"result": result, "problems": problems}


SCENARIOS = {
    "baseline": _scenario_baseline,
    "publish_fault": _scenario_publish_fault,
    "reward_fault": _scenario_reward_fault,
    "rollout_kill": _scenario_rollout_kill,
    "rollout_hang": _scenario_rollout_hang,
    "rollout_sigkill": _scenario_rollout_sigkill,
    "gcs_flake": _scenario_gcs_flake,
    "serve_reward": _scenario_serve_reward,
    "drain": _scenario_drain,
    "collective": _scenario_collective,
}


def run_scenario(name: str) -> Dict[str, Any]:
    """Run one scenario; returns ``{"scenario", "ok", "problems",
    "metrics", "seconds"}``.  Importable by the slow chaos tests."""
    import ray_tpu

    t0 = time.perf_counter()
    # these scenarios manage their own cluster (env-armed specs must be
    # in the environment before any raylet spawns; drain is multi-node)
    needs_own_cluster = name in (
        "drain", "rollout_hang", "rollout_sigkill", "gcs_flake")
    started_here = False
    if not needs_own_cluster and not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=8, num_tpus=0)
        started_here = True
    try:
        out = SCENARIOS[name]()
    finally:
        if started_here and ray_tpu.is_initialized():
            ray_tpu.shutdown()
    result = out["result"]
    metrics = {k: v for k, v in (result.metrics or {}).items()
               if isinstance(v, (int, float, str))}
    return {
        "scenario": name,
        "ok": not out["problems"],
        "problems": out["problems"],
        "metrics": metrics,
        "seconds": round(time.perf_counter() - t0, 2),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--scenario", action="append",
                    choices=sorted(SCENARIOS), default=None)
    ap.add_argument("--all", action="store_true",
                    help="run fast + slow scenarios")
    args = ap.parse_args()
    names = (args.scenario or
             (FAST_SCENARIOS + SLOW_SCENARIOS if args.all
              else FAST_SCENARIOS))
    records = []
    failed = False
    for name in names:
        rec = run_scenario(name)
        records.append(rec)
        failed = failed or not rec["ok"]
        emit_record_line(rec)
    emit_final_record({
        "suite": "rlhf_chaos",
        "scenarios": len(records),
        "passed": sum(1 for r in records if r["ok"]),
        "failed": sum(1 for r in records if not r["ok"]),
    })
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
