"""ObjectRef — a typed future naming an object in the cluster.

Equivalent of the reference's ``ObjectRef`` (``python/ray/_raylet.pyx`` /
``src/ray/common/id.h`` ObjectID + ownership metadata from
``src/ray/core_worker/reference_count.h:72``).  Each ref carries its owner's
address so any holder can resolve the value directly from the owner (the
ownership model: the worker that created an object serves and refcounts it).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner_addr", "_in_band")

    def __init__(self, object_id: ObjectID, owner_addr: Optional[str] = None):
        self.id = object_id
        self.owner_addr = owner_addr
        self._in_band = None  # local-mode fast path: value carried inline

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        # Track refs crossing serialization boundaries (borrower registration,
        # reference: reference_count.h borrow protocol).
        from ray_tpu._private import serialization

        serialization.note_serialized_ref(self)
        return (_rebuild_ref, (self.id, self.owner_addr))

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures

        import ray_tpu

        fut: concurrent.futures.Future = concurrent.futures.Future()
        try:
            fut.set_result(ray_tpu.get(self))
        except Exception as e:  # noqa: BLE001
            fut.set_exception(e)
        return fut

    def __await__(self):
        # Awaitable inside async actors/drivers.
        from ray_tpu._private.worker import global_worker

        return global_worker.get_async(self).__await__()


def _rebuild_ref(object_id, owner_addr):
    from ray_tpu._private import serialization

    ref = ObjectRef(object_id, owner_addr)
    serialization.note_deserialized_ref(ref)
    return ref
