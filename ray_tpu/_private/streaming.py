"""Streaming generator returns: ``num_returns="streaming"``.

TPU-native equivalent of the reference's streaming generators
(``python/ray/_raylet.pyx:279`` ``ObjectRefGenerator``,
``src/ray/core_worker/task_manager.h`` HandleReportGeneratorItemReturns):
a task whose function is a generator streams each yielded value to its
owner as a separate object the moment it is produced, instead of
materializing all outputs before any can be consumed.

Protocol:

- The executing worker runs the generator on its executor thread; each
  item is serialized like a task return (inline payload or shm location)
  and shipped to the owner with a ``streaming_item`` RPC.  A bounded
  in-flight window pipelines items; the owner additionally delays the
  reply of item ``i`` until the consumer is within
  ``_generator_backpressure_num_objects`` items — the reference's
  consumer-driven backpressure.
- ``streaming_end`` carries the final count (or the raised error); the
  normal ``push_task`` reply then releases the lease.
- Item ObjectIDs derive from (task_id, index) like fixed returns, so
  ``ray_tpu.get`` on yielded refs flows through the ordinary owner
  resolution path.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional

from ray_tpu import exceptions as exc
from ray_tpu._private.ids import ObjectID, TaskID

# TaskSpec.num_returns sentinel for streaming tasks
STREAMING_RETURNS = -1


class StreamState:
    """Owner-side bookkeeping for one in-flight generator task."""

    __slots__ = ("task_id", "produced", "consumed", "finished", "count",
                 "error", "waiters", "backpressure", "consume_waiters")

    def __init__(self, task_id: TaskID, backpressure: int = 0):
        self.task_id = task_id
        self.produced = 0          # items whose location has been recorded
        self.consumed = 0          # items handed out by the generator
        self.finished = False
        self.count: Optional[int] = None
        self.error: Optional[Exception] = None
        self.waiters: List[asyncio.Future] = []   # consumers awaiting items
        self.consume_waiters: List[asyncio.Future] = []  # producer backpressure
        self.backpressure = backpressure

    def wake_consumers(self):
        for w in self.waiters:
            if not w.done():
                w.set_result(None)
        self.waiters.clear()

    def wake_producer(self):
        for w in self.consume_waiters:
            if not w.done():
                w.set_result(None)
        self.consume_waiters.clear()


class ObjectRefGenerator:
    """Iterator over a streaming task's yielded object refs.

    Yields ``ObjectRef``s in production order, blocking until the next
    item lands (or the stream finishes → ``StopIteration`` / raises the
    task's error).  Supports both sync and async iteration.  The handle is
    bound to the owner process (the submitter) and is not serializable.
    """

    def __init__(self, task_id: TaskID, worker):
        self._task_id = task_id
        self._worker = worker

    @property
    def task_id(self) -> TaskID:
        return self._task_id

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return self._worker.run_coro(
                self._worker.stream_next(self._task_id))
        except StopAsyncIteration:
            raise StopIteration from None

    def __aiter__(self):
        return self

    async def __anext__(self):
        return await self._worker.stream_next(self._task_id)

    def completed_count(self) -> int:
        st = self._worker._streams.get(self._task_id)
        return st.produced if st else 0

    def close(self):
        """Deterministically abandon the stream: cancel the producer
        task, unblock a backpressured producer, and release buffered
        items — the same teardown ``__del__`` schedules, but without
        waiting on GC timing (a disconnected streaming client must stop
        the replica NOW, not whenever the wrapper is collected).
        Idempotent; safe to call from any thread."""
        try:
            w = self._worker
            if (w is not None and not w._shutdown
                    and self._task_id in w._streams):
                w.loop.call_soon_threadsafe(w._abandon_stream,
                                            self._task_id)
        except Exception:  # noqa: BLE001 — already torn down
            pass

    def __del__(self):
        # dropping an undrained generator must not leak the stream state
        # or wedge a backpressured producer: cancel + clean up
        try:
            w = self._worker
            if (w is not None and not w._shutdown
                    and self._task_id in w._streams):
                w.loop.call_soon_threadsafe(w._abandon_stream, self._task_id)
        except Exception:  # interpreter teardown
            pass

    def __reduce__(self):
        raise TypeError(
            "ObjectRefGenerator is bound to its owner process and cannot "
            "be serialized; pass the individual ObjectRefs instead")

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id.hex()[:12]})"
