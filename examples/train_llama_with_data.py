"""End-to-end: DataParallelTrainer + ray_tpu.data feeding a sharded Llama.

The mesh is declared on the ScalingConfig (a preset name or a
``parallel.MeshConfig``); the worker loop gets it back — resolved
against whatever devices the generation actually has — via
``train.get_context().get_mesh()``.

Run: python examples/train_llama_with_data.py
(CPU-mesh friendly; on a TPU host the same code uses the chips.)
"""

import numpy as np

import ray_tpu
import ray_tpu.data as rd
from ray_tpu import train
from ray_tpu.train import DataParallelTrainer, ScalingConfig


def train_loop(config):
    import jax

    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.models.training import default_optimizer, make_llama_trainer

    ctx = train.get_context()
    # the ScalingConfig's requested mesh, resolved over this worker's
    # device view (clamped if an elastic restart shrank the hardware)
    mesh = ctx.get_mesh()
    cfg = LlamaConfig.tiny()
    tr = make_llama_trainer(cfg, mesh, optimizer=default_optimizer(
        lr=1e-3, warmup=2, decay_steps=100))
    state = tr.init_state(jax.random.PRNGKey(0))  # born sharded on the mesh

    shard = train.get_dataset_shard("train")
    step = 0
    for batch in shard.iter_batches(batch_size=8, prefetch_batches=1):
        tokens = batch["tokens"].astype("int32")
        state, metrics = tr.step(state, tr.shard_batch({"tokens": tokens}))
        step += 1
        train.report({"loss": float(metrics["loss"]), "step": step,
                      "mesh": {a: int(s) for a, s in mesh.shape.items()
                               if int(s) > 1}})


def main():
    ray_tpu.init()
    rng = np.random.default_rng(0)
    # tensor column: each row is a fixed-length token window
    ds = rd.from_numpy(
        rng.integers(0, 256, (64, 33)).astype(np.int32), column="tokens")
    trainer = DataParallelTrainer(
        train_loop,
        scaling_config=ScalingConfig(num_workers=2, mesh="fsdp"),
        datasets={"train": ds})
    result = trainer.fit()
    print("final:", result.metrics)
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
