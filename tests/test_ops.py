"""Tests for ray_tpu.ops: attention kernels, norms, rope."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# JAX-compile-heavy tier: deselect with -m 'not slow' for fast runs
pytestmark = pytest.mark.slow

from ray_tpu.ops.attention import (
    dot_product_attention,
    reference_attention,
    ring_attention,
)
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies, swiglu
from ray_tpu.ops.pallas.flash_attention import flash_attention
from ray_tpu.parallel import MeshConfig, create_mesh


def _qkv(b=2, s=128, h=4, kvh=2, d=64, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), dtype)
    k = jax.random.normal(ks[1], (b, s, kvh, d), dtype)
    v = jax.random.normal(ks[2], (b, s, kvh, d), dtype)
    return q, k, v


class TestReferenceAttention:
    def test_causal_masks_future(self):
        q, k, v = _qkv(s=16)
        out = reference_attention(q, k, v, causal=True)
        # Row 0 attends only to position 0 → equals v[:, 0] (GQA-expanded).
        expected = jnp.repeat(v[:, 0], 2, axis=1)
        np.testing.assert_allclose(out[:, 0], expected, rtol=1e-5)

    def test_matches_jax_builtin(self):
        q, k, v = _qkv(h=4, kvh=4)
        ours = reference_attention(q, k, v, causal=True)
        jaxs = jax.nn.dot_product_attention(q, k, v, is_causal=True)
        np.testing.assert_allclose(ours, jaxs, atol=1e-5)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        ref = reference_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        # Interpret mode emulates MXU bf16 matmul precision.
        np.testing.assert_allclose(out, ref, atol=2e-2)

    # s=48 exercises the backward padding path (not a block multiple).
    @pytest.mark.parametrize("s", [64, 48])
    def test_grad_matches_reference(self, s):
        q, k, v = _qkv(s=s)
        g = jax.grad(
            lambda *a: flash_attention(*a, block_q=32, block_k=32).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        gr = jax.grad(
            lambda *a: reference_attention(*a).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(a, b, atol=2e-2)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference_sp4(self, causal):
        mesh = create_mesh(MeshConfig(dp=2, fsdp=1, tp=1, sp=4))
        q, k, v = _qkv(b=2, s=64, h=4, kvh=2, d=32)
        ref = reference_attention(q, k, v, causal=causal)
        out = ring_attention(q, k, v, mesh=mesh, causal=causal)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_under_jit_with_tp(self):
        mesh = create_mesh(MeshConfig(dp=1, fsdp=1, tp=2, sp=4))
        q, k, v = _qkv(b=2, s=64, h=4, kvh=4, d=32)
        ref = reference_attention(q, k, v, causal=True)
        out = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh=mesh, causal=True)
        )(q, k, v)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_grad_flows(self):
        mesh = create_mesh(MeshConfig(dp=1, fsdp=1, tp=1, sp=8))
        q, k, v = _qkv(b=1, s=64, h=2, kvh=2, d=16)
        def f(q, k, v):
            return ring_attention(q, k, v, mesh=mesh, causal=True).sum()
        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(
            lambda *a: reference_attention(*a, causal=True).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(a, b, atol=1e-4)


class TestSlidingWindow:
    def test_window_matches_manual_mask(self):
        q, k, v = _qkv(s=32, h=4, kvh=4)
        W = 8
        out = reference_attention(q, k, v, causal=True, window=W)
        # manual: causal AND within-window softmax
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (q.shape[-1] ** -0.5)
        i = jnp.arange(32)
        m = (i[:, None] >= i[None, :]) & (i[:, None] - i[None, :] < W)
        logits = jnp.where(m[None, None], logits.astype(jnp.float32), -1e30)
        expect = jnp.einsum(
            "bhqk,bkhd->bqhd", jax.nn.softmax(logits, -1).astype(v.dtype), v)
        np.testing.assert_allclose(out, expect, atol=1e-5)

    def test_window_geq_seq_equals_full(self):
        q, k, v = _qkv(s=16)
        full = reference_attention(q, k, v, causal=True)
        win = reference_attention(q, k, v, causal=True, window=64)
        np.testing.assert_allclose(win, full, atol=1e-6)

    def test_ring_window_matches_reference(self):
        mesh = create_mesh(MeshConfig(dp=2, fsdp=1, tp=1, sp=4))
        q, k, v = _qkv(b=2, s=64, h=4, kvh=2, d=32)
        ref = reference_attention(q, k, v, causal=True, window=10)
        out = ring_attention(q, k, v, mesh=mesh, causal=True, window=10)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_flash_rejects_window(self):
        from ray_tpu.ops.attention import dot_product_attention

        q, k, v = _qkv(s=16)
        with pytest.raises(ValueError, match="flash"):
            dot_product_attention(q, k, v, impl="flash", window=4)


class TestDispatch:
    def test_auto_picks_ring_on_sp_mesh(self):
        mesh = create_mesh(MeshConfig(dp=1, fsdp=1, tp=1, sp=8))
        q, k, v = _qkv(b=1, s=64, h=2, kvh=2, d=16)
        out = dot_product_attention(q, k, v, mesh=mesh)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(out, ref, atol=1e-5)


class TestLayers:
    def test_rms_norm(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 8))
        out = rms_norm(x, jnp.ones(8))
        rms = jnp.sqrt(jnp.mean(out**2, axis=-1))
        np.testing.assert_allclose(rms, jnp.ones(4), rtol=1e-3)

    def test_rope_preserves_norm_and_relative(self):
        cos, sin = rope_frequencies(16, 32)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 2, 16))
        out = apply_rope(x, cos, sin)
        np.testing.assert_allclose(
            jnp.linalg.norm(out, axis=-1), jnp.linalg.norm(x, axis=-1),
            rtol=1e-5,
        )
        # Position 0 is the identity rotation.
        np.testing.assert_allclose(out[:, 0], x[:, 0], atol=1e-6)

    def test_rope_positions_arg(self):
        cos, sin = rope_frequencies(8, 64)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 4, 1, 8))
        pos = jnp.array([[5, 6, 7, 8]])
        shifted = apply_rope(x, cos, sin, positions=pos)
        full = apply_rope(
            jnp.pad(x, ((0, 0), (5, 0), (0, 0), (0, 0))), cos, sin
        )[:, 5:]
        np.testing.assert_allclose(shifted, full, atol=1e-5)

    def test_swiglu(self):
        g = jnp.array([0.0, 1.0, -1.0])
        u = jnp.array([2.0, 2.0, 2.0])
        out = swiglu(g, u)
        np.testing.assert_allclose(out[0], 0.0, atol=1e-6)
        assert out[1] > 0 and out[2] < 0


class TestFlashPadding:
    def test_non_divisible_seq(self):
        """Seq lengths not divisible by block size are padded and masked."""
        q, k, v = _qkv(s=95)
        ref = reference_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
        np.testing.assert_allclose(out, ref, atol=2e-2)

    def test_odd_seq_4095_style(self):
        q, k, v = _qkv(b=1, s=63, h=2, kvh=1, d=32)
        ref = reference_attention(q, k, v, causal=False)
        out = flash_attention(q, k, v, causal=False, block_q=32, block_k=32)
        np.testing.assert_allclose(out, ref, atol=2e-2)


class TestShardedFlash:
    def test_flash_under_mesh_shard_map(self):
        """impl='flash' with a mesh runs per-shard under shard_map."""
        mesh = create_mesh(MeshConfig(dp=4, fsdp=1, tp=2, sp=1))
        q, k, v = _qkv(b=4, s=64, h=4, kvh=2, d=32)
        ref = reference_attention(q, k, v, causal=True)
        out = jax.jit(
            lambda q, k, v: dot_product_attention(
                q, k, v, causal=True, impl="flash", mesh=mesh
            )
        )(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-2)


class TestHybridMesh:
    def test_shape_and_axis_layout(self):
        from ray_tpu.parallel import create_hybrid_mesh

        mesh = create_hybrid_mesh(
            ici_config=MeshConfig(dp=1, fsdp=2, tp=2, sp=1), num_slices=2
        )
        assert dict(mesh.shape) == {
            "dp": 2, "fsdp": 2, "pp": 1, "tp": 2, "sp": 1
        }
