"""`raytpu` command-line interface.

Equivalent of the reference's ``ray`` CLI
(``python/ray/scripts/scripts.py``; ``start`` at ``scripts.py:706``):
start/stop a head node, inspect cluster status, list entities.
Uses argparse instead of click (no extra deps).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _connect(address: str):
    import ray_tpu

    ray_tpu.init(address=address)
    return ray_tpu


def cmd_start(args):
    from ray_tpu._private.node import NodeServices, default_resources

    resources = default_resources(num_cpus=args.num_cpus, num_tpus=args.num_tpus)
    if args.resources:
        resources.update(json.loads(args.resources))
    services = NodeServices()
    addr = services.start_head(resources, json.loads(args.labels or "{}"))
    # Detach: the head runs as its own process group; record for `stop`.
    state = {"gcs_addr": addr, "head_pid": services.head_proc.pid,
             "session_dir": services.session_dir}
    os.makedirs(os.path.expanduser("~/.ray_tpu"), exist_ok=True)
    with open(os.path.expanduser("~/.ray_tpu/head.json"), "w") as f:
        json.dump(state, f)
    import atexit

    atexit.unregister(services.stop)
    services._owns_cluster = False  # keep running after this CLI exits
    print(f"Head started. Address: {addr}")
    print(f"Connect with: ray_tpu.init(address='{addr}')")


def cmd_stop(args):
    path = os.path.expanduser("~/.ray_tpu/head.json")
    if not os.path.exists(path):
        print("No running head found.")
        return
    with open(path) as f:
        state = json.load(f)
    from ray_tpu._private.rpc import RpcClient, run_sync

    async def _down():
        c = RpcClient(state["gcs_addr"])
        try:
            await c.call("shutdown_cluster")
        finally:
            await c.close()

    try:
        run_sync(_down())
        print("Cluster shut down.")
    except Exception as e:  # noqa: BLE001
        print(f"Graceful shutdown failed ({e}); killing pid {state['head_pid']}")
        try:
            os.kill(state["head_pid"], 9)
        except ProcessLookupError:
            pass
    os.unlink(path)


def cmd_status(args):
    ray_tpu = _connect(args.address or _default_address())
    from ray_tpu.util.state import list_nodes

    nodes = list_nodes()
    print("Nodes:")
    fenced = zombies = 0
    for n in nodes:
        mark = n.get("state", "ALIVE" if n["alive"] else "DEAD")
        extra = ""
        if mark == "DRAINING":
            left = (n.get("drain_deadline") or 0) - time.time()
            extra = (f" draining: {n.get('drain_reason') or '<no reason>'}"
                     f" ({max(0.0, left):.0f}s to deadline)")
        elif mark == "DEAD" and n.get("death_reason"):
            extra = f" ({n['death_reason']})"
        health = n.get("health", "HEALTHY")
        if health != "HEALTHY":
            extra += f" health={health}"
        if n.get("fenced"):
            fenced += 1
            extra += " fenced"
        if n.get("zombie"):
            zombies += 1
            extra += " ZOMBIE"
        print(f"  {n['node_id'][:12]} [{mark}] {n['addr']} "
              f"inc={n.get('incarnation', 0)} "
              f"total={n['total']}{extra}")
    if fenced or zombies:
        # a zombie is a dead-declared incarnation still contacting the
        # GCS — fenced off, but worth a human look (split-brain debris)
        print(f"Fencing: {fenced} fenced, {zombies} zombie")
    print("Cluster resources:", ray_tpu.cluster_resources())
    print("Available:", ray_tpu.available_resources())
    try:
        from ray_tpu.util.state import list_collective_groups

        groups = list_collective_groups()
    except Exception:  # noqa: BLE001 — status must render without KV
        groups = []
    if groups:
        print("Collective groups:")
        for g in groups:
            line = (f"  {g['group_name']} [{g['state']}] "
                    f"backend={g['backend']} epoch={g['epoch']} "
                    f"members={g['joined']}/{g['world_size']}")
            if g.get("abort_reason"):
                line += f" abort: {g['abort_reason']}"
            print(line)
            for m in g["members"]:
                inflight = m.get("inflight")
                prog = (f"in-flight {inflight['op']} seq={inflight['seq']}"
                        if inflight else
                        f"idle after seq={m.get('last_done_seq', 0)}")
                print(f"    rank {m['rank']} [{m.get('state')}] "
                      f"node={str(m.get('node_id', ''))[:12]} "
                      f"pid={m.get('pid')} {prog}")
    try:
        from ray_tpu.util.state import list_serve_deployments

        deployments = list_serve_deployments()
    except Exception:  # noqa: BLE001 — status must render without KV
        deployments = []
    if deployments:
        print("Serve deployments:")
        for d in deployments:
            line = (f"  {d['name']} replicas={d.get('num_replicas')}"
                    f"/{d.get('goal')} "
                    f"max_ongoing={d.get('max_ongoing_requests')} "
                    f"max_queued={d.get('max_queued_requests')}")
            if d.get("route"):
                line += f" route={d['route']}"
            ov = d.get("overload") or {}
            if any(ov.values()):
                line += (f" overload: shed={ov.get('shed', 0)} "
                         f"expired={ov.get('expired', 0)} "
                         f"cancelled={ov.get('cancelled', 0)} "
                         f"queued={ov.get('queued', 0)}")
            print(line)
    try:
        from ray_tpu.util.state import list_gangs

        gangs = list_gangs()
    except Exception:  # noqa: BLE001 — status must render without gangs
        gangs = []
    if gangs:
        print("Gangs:")
        for g in gangs:
            line = (f"  {g['gang_id'][:12]}"
                    f"{' ' + g['name'] if g.get('name') else ''}"
                    f" [{g['state']}] priority={g.get('priority', 0)}"
                    f" bundles={g.get('bundle_count')}")
            if g.get("placement"):
                line += f" nodes={sorted({n[:8] for n in g['placement']})}"
            if g.get("claim_nodes"):
                line += (f" claiming={len(g['claim_nodes'])} node(s)"
                         f" (preempting)")
            if g.get("preempted_by"):
                line += f" preempted_by={g['preempted_by'][:8]}"
            if g.get("fate_shared"):
                line += f" fate-shared: {g.get('failure')}"
            print(line)
    try:
        from ray_tpu.util.state import list_slo_verdicts

        verdicts = list_slo_verdicts()
    except Exception:  # noqa: BLE001 — status must render without KV
        verdicts = []
    if verdicts:
        print("SLO verdicts:")
        for v in verdicts:
            tag = f"{v.get('plane')}/{v.get('name')}"
            if v.get("phase"):
                tag += f"/{v['phase']}"
            line = f"  {tag} [{v.get('status')}]"
            for viol in v.get("violations") or []:
                line += (f" {viol.get('metric')}={viol.get('value')} "
                         f"(limit {viol.get('limit')})")
            if v.get("status") == "DEGRADED" and v.get("degraded_reason"):
                line += f" ({v['degraded_reason']})"
            print(line)
    ray_tpu.shutdown()


def cmd_health(args):
    """Node health ladder + straggler/SDC verdicts (the health plane's
    operator view — what ``/api/health`` serves on the dashboard)."""
    ray_tpu = _connect(args.address or _default_address())
    from ray_tpu.util.state import list_node_health

    report = list_node_health()
    if args.json:
        print(json.dumps(report, default=str))
        ray_tpu.shutdown()
        return
    print("Node health:")
    for n in report["nodes"]:
        line = (f"  {n['node_id'][:12]} [{n['state']}] "
                f"health={n['health']}")
        if n.get("health_reason"):
            line += f" ({n['health_reason']})"
        if n.get("hw_confirmed"):
            line += " hw-confirmed"
        print(line)
    verdicts = report.get("verdicts") or []
    if verdicts:
        print("Verdicts:")
        for v in verdicts:
            line = (f"  {v.get('kind')}/{v.get('subject')} "
                    f"[{v.get('health')}]")
            if v.get("reason"):
                line += f" {v['reason']}"
            sig = v.get("signals") or {}
            if sig.get("own_time_z") is not None:
                line += f" z={sig['own_time_z']:.1f}"
            if sig.get("probe_ratio") is not None:
                line += f" probe={sig['probe_ratio']:.1f}x"
            if v.get("hw_confirmed"):
                line += " hw-confirmed"
            print(line)
    else:
        print("Verdicts: none (no straggler or SDC reports)")
    ray_tpu.shutdown()


def cmd_drain(args):
    """Operator-initiated node drain (reference ``ray drain-node``)."""
    ray_tpu = _connect(args.address or _default_address())
    from ray_tpu.util.state import drain_node

    # accept a node-id prefix, like the listings print
    target = args.node_id
    matches = [n["node_id"] for n in ray_tpu.nodes()
               if n["node_id"].startswith(target)]
    if len(matches) == 1:
        target = matches[0]
    elif len(matches) > 1:
        print(f"ambiguous node id prefix {target!r} "
              f"({len(matches)} matches)")
        ray_tpu.shutdown()
        sys.exit(1)
    ack = drain_node(target, reason=args.reason,
                     deadline_s=args.deadline_s)
    if ack.get("accepted"):
        left = ack["deadline"] - time.time()
        print(f"draining {target[:12]} (deadline in {left:.0f}s, "
              f"{len(ack.get('lease_holders', []))} lease holder(s))")
    else:
        print(f"drain rejected: {ack.get('rejection_reason')}")
    ray_tpu.shutdown()
    sys.exit(0 if ack.get("accepted") else 1)


def cmd_memory(args):
    """Cluster object-ref debugging view (reference ``ray memory``)."""
    ray_tpu = _connect(args.address or _default_address())
    from ray_tpu.util import state as state_api

    summary = state_api.memory_summary()
    if args.json:
        print(json.dumps(summary, default=str))
    else:
        hdr = (f"{'object_id':<32} {'refs':>4} {'borr':>4} {'pins':>4} "
               f"{'cont':>4} {'lin':>3} {'where':<6} size")

        def row(r, indent):
            print(f"{indent}{r['object_id']:<32} {r['local_refs']:>4} "
                  f"{len(r['borrowers']):>4} {r['transfer_pins']:>4} "
                  f"{r['contained_refs']:>4} "
                  f"{'y' if r['has_lineage'] else '-':>3} "
                  f"{r.get('where', '-'):<6} {r.get('size', '')}")

        for drv in summary["drivers"]:
            print(f"driver pid={drv.get('pid')}")
            print("  " + hdr)
            for r in drv["rows"]:
                row(r, "  ")
        for node in summary["nodes"]:
            print(f"node {node['node_id'][:12]} store={node.get('store')}")
            for wrep in node["workers"]:
                kind = (f"actor {wrep['actor_id'][:12]}"
                        if wrep.get("actor_id") else "worker")
                print(f"  {kind} pid={wrep['pid']}")
                if wrep["rows"]:
                    print("    " + hdr)
                for r in wrep["rows"]:
                    row(r, "    ")
    ray_tpu.shutdown()


def cmd_list(args):
    ray_tpu = _connect(args.address or _default_address())
    from ray_tpu.util import state as state_api

    fn = {
        "actors": state_api.list_actors,
        "nodes": state_api.list_nodes,
        "jobs": state_api.list_jobs,
        "placement-groups": state_api.list_placement_groups,
        "gangs": state_api.list_gangs,
        "slices": state_api.get_slice_topology,
    }[args.entity]
    for row in fn():
        print(json.dumps(row, default=str))
    ray_tpu.shutdown()


def cmd_up(args):
    import logging

    logging.basicConfig(level="INFO")
    from ray_tpu.autoscaler.launcher import cluster_up

    state = cluster_up(args.config, no_monitor=args.no_monitor)
    print(json.dumps({"cluster_name": state["cluster_name"],
                      "address": state["gcs_addr"],
                      "head_pid": state["head_pid"],
                      "workers": len(state.get("workers", []))}))


def cmd_down(args):
    import logging

    logging.basicConfig(level="INFO")
    from ray_tpu.autoscaler.launcher import cluster_down

    ok = cluster_down(args.config)
    print("down" if ok else "no such cluster")


def cmd_job(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.address or _default_address())
    if args.job_command == "submit":
        entry = list(args.entrypoint)
        if entry and entry[0] == "--":  # argparse.REMAINDER keeps the sep
            entry = entry[1:]
        sid = client.submit_job(entrypoint=" ".join(entry),
                                runtime_env=json.loads(args.runtime_env)
                                if args.runtime_env else None)
        print(sid)
        if args.wait:
            status = client.wait_until_finished(sid, timeout=args.timeout)
            print(status.value)
            print(client.get_job_logs(sid), end="")
            if status.value != "SUCCEEDED":
                raise SystemExit(1)
    elif args.job_command == "status":
        print(json.dumps(client.get_job_info(args.submission_id), default=str))
    elif args.job_command == "logs":
        if getattr(args, "follow", False):
            # stream: poll the DELTA (byte offset) until the job
            # terminates (reference: `ray job logs --follow`)
            import time as _time

            seen = 0
            while True:
                delta, seen = client.poll_job_logs(args.submission_id,
                                                   offset=seen)
                if delta:
                    print(delta, end="", flush=True)
                done = client.get_job_status(
                    args.submission_id).is_terminal()
                if done and not delta:
                    break
                if not delta:
                    _time.sleep(0.5)
        else:
            print(client.get_job_logs(args.submission_id), end="")
    elif args.job_command == "stop":
        print(client.stop_job(args.submission_id))
    elif args.job_command == "list":
        for row in client.list_jobs():
            print(json.dumps(row, default=str))


def cmd_dashboard(args):
    path = os.path.expanduser("~/.ray_tpu/head.json")
    if not os.path.exists(path):
        raise SystemExit("No running head found (raytpu start first).")
    with open(path) as f:
        session_dir = json.load(f)["session_dir"]
    addr_file = os.path.join(session_dir, "dashboard_address")
    if not os.path.exists(addr_file):
        raise SystemExit("Dashboard not running (RAY_TPU_DASHBOARD=0?).")
    with open(addr_file) as f:
        print(f.read().strip())


def cmd_timeline(args):
    ray_tpu = _connect(args.address or _default_address())
    from ray_tpu.util import state as state_api

    events = state_api.timeline(args.output)
    # per-trace summary: one causal tree per request/step (the span layer
    # of docs/observability.md) — how many connected trees the export
    # holds and how big each is, so `raytpu timeline` answers "did my
    # request/step form ONE trace" without opening the viewer
    traces = {}
    for e in events:
        tid = (e.get("args") or {}).get("trace_id")
        if tid:
            traces[tid] = traces.get(tid, 0) + 1
    print(f"Wrote {len(events)} events to {args.output}")
    if traces:
        top = sorted(traces.items(), key=lambda kv: -kv[1])[:8]
        print(f"{len(traces)} trace(s); largest: "
              + ", ".join(f"{t[:8]}…×{n}" for t, n in top))
    ray_tpu.shutdown()


def cmd_lint(args):
    """raylint: AST static analysis over the repo (docs/static_analysis.md).

    Exit-code contract: 0 clean, 1 unsuppressed findings, 2 internal
    error (unknown rule, unreadable tree, checker crash).
    """
    try:
        from ray_tpu._private.analysis import run_lint

        root = args.root
        if root is None:
            # default: the tree containing the installed ray_tpu package
            import ray_tpu

            root = os.path.dirname(os.path.dirname(
                os.path.abspath(ray_tpu.__file__)))
        result = run_lint(root, paths=args.paths or None,
                          rules=args.rules.split(",") if args.rules
                          else None)
    except Exception as e:  # noqa: BLE001 — contract: internal error -> 2
        print(f"raylint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        sys.exit(2)
    if args.format == "json":
        print(result.to_json())
    else:
        print(result.render_human())
    sys.exit(0 if result.clean else 1)


def _default_address() -> str:
    if os.environ.get("RAY_TPU_ADDRESS"):
        return os.environ["RAY_TPU_ADDRESS"]
    path = os.path.expanduser("~/.ray_tpu/head.json")
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)["gcs_addr"]
    raise SystemExit("No address given and no running head found (raytpu start first).")


def main(argv=None):
    parser = argparse.ArgumentParser(prog="raytpu",
                                     description="TPU-native distributed runtime CLI")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("start", help="start a head node on this machine")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", default="")
    p.add_argument("--labels", default="")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop the head started on this machine")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("up", help="launch a cluster from a YAML config")
    p.add_argument("config", help="cluster yaml/json")
    p.add_argument("--no-monitor", action="store_true",
                   help="skip the autoscaling monitor process")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="tear down a launched cluster")
    p.add_argument("config", help="cluster yaml/json or cluster name")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("status", help="show cluster nodes and resources")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("health", help="node health ladder and "
                                      "straggler/SDC verdicts")
    p.add_argument("--address", default=None)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_health)

    p = sub.add_parser("drain", help="drain a node (advance-notice "
                                     "preemption: checkpoint/migrate, "
                                     "then terminate at the deadline)")
    p.add_argument("node_id", help="node id (or unique prefix)")
    p.add_argument("--reason", default="operator drain")
    p.add_argument("--deadline-s", dest="deadline_s", type=float,
                   default=None,
                   help="seconds until the node is terminated "
                        "(default: node_drain_deadline_s config)")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_drain)

    p = sub.add_parser("list", help="list cluster entities")
    p.add_argument("entity", choices=["actors", "nodes", "jobs",
                                      "placement-groups", "gangs",
                                      "slices"])
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("memory", help="object-ref debugging view "
                                      "(per-process refcount tables)")
    p.add_argument("--address", default=None)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("job", help="submit and manage jobs")
    jsub = p.add_subparsers(dest="job_command", required=True)
    ps = jsub.add_parser("submit")
    ps.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="-- shell command to run")
    ps.add_argument("--runtime-env", default=None, help="json runtime env")
    ps.add_argument("--wait", action="store_true",
                    help="block until finished, print logs")
    ps.add_argument("--timeout", type=float, default=600.0)
    for name in ("status", "logs", "stop"):
        pj = jsub.add_parser(name)
        pj.add_argument("submission_id")
        if name == "logs":
            pj.add_argument("--follow", action="store_true",
                            help="stream logs until the job terminates")
    jsub.add_parser("list")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser("dashboard", help="print the dashboard URL")
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("lint", help="run the raylint static-analysis "
                                    "suite (0 clean / 1 findings / "
                                    "2 internal error)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan, relative to --root "
                        "(default: ray_tpu tests bench.py)")
    p.add_argument("--root", default=None,
                   help="repo root (default: the tree containing the "
                        "ray_tpu package)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids (default: all)")
    p.add_argument("--format", choices=["human", "json"], default="human")
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser("timeline", help="export chrome://tracing timeline")
    p.add_argument("--output", default="timeline.json")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_timeline)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
