"""Chunked node-to-node object transfer: pull/push managers.

TPU-native equivalent of the reference's object manager transfer plane
(``src/ray/object_manager/object_manager.h:106``, ``pull_manager.h:49``,
``push_manager.h:28``): cross-node object movement in bounded chunks with
windowed pipelining and admission control, replacing the round-1
whole-object-in-one-RPC pull (VERDICT weak #4 — a 10 GiB object became a
single frame through the RPC layer).

Single-host topologies still resolve through shared memory; this is the
DCN path between raylets whose stores don't share visibility (different
sessions / different hosts).

- **Sender (push side)**: ``pull_chunk`` serves ``[offset, offset+len)``
  slices of a sealed object; a process-wide semaphore bounds concurrent
  chunk reads so one greedy puller can't monopolize the raylet
  (reference PushManager's in-flight chunk budget).
- **Receiver (pull side)**: ``ChunkedPuller`` fetches the object size,
  admits the transfer against a global bytes-in-flight budget
  (reference PullManager quota), then pipelines chunk requests under a
  bounded window into a staging buffer, storing the sealed object
  locally on completion.  Concurrent pulls of one object share a single
  in-flight transfer.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, Optional

from ray_tpu._private.config import config
from ray_tpu._private.ids import ObjectID

logger = logging.getLogger(__name__)


class PushLimiter:
    """Sender-side admission: bounds concurrent outbound chunk copies.

    The chunk memcpy runs on the default executor — off the raylet's
    event loop (an 8 MiB copy would otherwise stall every other RPC on
    the node), and the await point is what makes the semaphore a real
    bound on concurrent copies rather than a no-op around sync code.
    """

    def __init__(self, max_concurrent: Optional[int] = None):
        self._sem = asyncio.Semaphore(
            max_concurrent or int(config.transfer_push_concurrency))

    async def read_chunk(self, store, object_id: ObjectID, offset: int,
                         length: int) -> Optional[bytes]:
        async with self._sem:
            buf = store.get_buffer(object_id)
            if buf is None:
                return None
            return await asyncio.get_event_loop().run_in_executor(
                None, lambda: bytes(buf[offset:offset + length]))


class ChunkedPuller:
    """Receiver-side pull manager with windowed chunk pipelining."""

    def __init__(self, store,
                 peer_fn,
                 chunk_bytes: Optional[int] = None,
                 window: Optional[int] = None,
                 max_bytes_in_flight: Optional[int] = None):
        # store: local object store (put_into/get_buffer/contains)
        # peer_fn(addr) -> RpcClient for the source raylet
        self._store = store
        self._peer = peer_fn
        self.chunk_bytes = chunk_bytes or int(config.transfer_chunk_bytes)
        self.window = window or int(config.transfer_window_chunks)
        self._budget = max_bytes_in_flight or int(
            config.transfer_max_bytes_in_flight)
        self._in_flight_bytes = 0
        self._admission = asyncio.Condition()
        self._inflight: Dict[ObjectID, asyncio.Future] = {}
        self.stats: Dict[str, Any] = {
            "pulls": 0, "chunks": 0, "bytes": 0, "dedup_hits": 0,
            "same_host_handoffs": 0,
        }

    async def pull(self, object_id: ObjectID, source_addr: str) -> bool:
        """Pull one object from the raylet at ``source_addr`` into the
        local store.  Returns True when the object is available locally."""
        if self._store.contains(object_id):
            # already visible — possibly a foreign same-host segment a LIVE
            # peer session still owns.  Adopting here would take unlink
            # responsibility for a segment the owner is still serving (our
            # teardown would unlink it under them), so adoption only happens
            # after an explicit export handshake: the source disowns first,
            # then we adopt.  If the handshake fails the object stays
            # readable now; a later loss re-resolves via the chunked pull.
            # Already-owned copies (arena/spill resident, or a previously
            # adopted segment) skip the handshake entirely — at most one
            # RPC per (object, session), not one per repeated get.
            owns = (getattr(self._store, "owns_locally", None)
                    or getattr(self._store, "owns", None))
            if owns is not None and owns(object_id):
                return True
            adopt = (getattr(self._store, "adopt_segment", None)
                     or getattr(self._store, "adopt", None))
            if adopt is not None:
                try:
                    client = self._peer(source_addr)
                    if await client.call(
                            "export_object", oid=object_id.hex(),
                            timeout=config.rpc_connect_timeout_s * 4):
                        adopt(object_id)
                except Exception:  # noqa: BLE001 — visible copy suffices
                    pass
            return True
        existing = self._inflight.get(object_id)
        if existing is not None:
            self.stats["dedup_hits"] += 1
            await asyncio.shield(existing)
            return self._store.contains(object_id)
        fut = asyncio.get_event_loop().create_future()
        self._inflight[object_id] = fut
        try:
            ok = await self._pull_once(object_id, source_addr)
            fut.set_result(ok)
            return ok
        except BaseException as e:
            fut.set_exception(e)
            # consume the exception for waiters that never awaited
            fut.exception()
            raise
        finally:
            self._inflight.pop(object_id, None)

    async def _pull_once(self, object_id: ObjectID,
                         source_addr: str) -> bool:
        client = self._peer(source_addr)
        info = await client.call("object_info", oid=object_id.hex())
        if not info or info.get("size") is None:
            return False
        size = int(info["size"])
        # same-host fast path: when source and destination share /dev/shm
        # (token match), ask the source to publish the object as a
        # machine-global segment — one local memcpy at memory bandwidth,
        # no chunk framing, no admission (nothing crosses the wire)
        from ray_tpu._private.object_store import shm_host_token

        src_token = info.get("host_token")
        if (src_token and src_token != "no-shm"
                and src_token == shm_host_token()):
            try:
                if (await client.call("export_object", oid=object_id.hex(),
                                      timeout=config.rpc_connect_timeout_s * 4)
                        and self._store.contains(object_id)):
                    # adopt the exported segment (take unlink
                    # responsibility): the exporter disowned it, so it now
                    # lives until THIS session tears down — independent-
                    # copy durability without a second payload copy
                    adopt = (getattr(self._store, "adopt_segment", None)
                             or getattr(self._store, "adopt", None))
                    if adopt is not None:
                        adopt(object_id)
                    self.stats["same_host_handoffs"] += 1
                    self.stats["pulls"] += 1
                    return True
            except Exception:  # noqa: BLE001 — fall back to chunked pull
                pass
        # admission: wait until the global in-flight budget has room (an
        # object larger than the whole budget is admitted alone)
        async with self._admission:
            while (self._in_flight_bytes > 0
                   and self._in_flight_bytes + size > self._budget):
                await self._admission.wait()
            self._in_flight_bytes += size
        try:
            if size == 0:
                self._store.put_serialized(object_id, b"")
                self.stats["pulls"] += 1
                return True
            # Write chunks straight into the destination buffer when the
            # store can hand one out pre-seal (arena alloc/seal split, or
            # a fresh segment) — no whole-object staging copy; fall back
            # to a staging bytearray otherwise.
            seal = None
            create = getattr(self._store, "create_writable", None)
            if create is not None:
                try:
                    dest, seal = create(object_id, size)
                except Exception:  # noqa: BLE001 - store full etc.
                    dest, seal = None, None
            else:
                dest = None
            staging = memoryview(bytearray(size)) if dest is None else dest
            offsets = list(range(0, size, self.chunk_bytes))
            sem = asyncio.Semaphore(self.window)
            errors: list = []

            async def fetch(off: int):
                async with sem:
                    if errors:
                        return
                    try:
                        length = min(self.chunk_bytes, size - off)
                        data = await client.call(
                            "pull_chunk", oid=object_id.hex(), offset=off,
                            length=length,
                            timeout=config.rpc_connect_timeout_s * 4)
                        if data is None:
                            raise KeyError(
                                f"source no longer holds {object_id.hex()}")
                        staging[off:off + len(data)] = data
                        self.stats["chunks"] += 1
                    except BaseException as e:  # noqa: BLE001
                        errors.append(e)

            await asyncio.gather(*(fetch(off) for off in offsets))
            if errors:
                if seal is not None:  # reclaim the pre-sealed allocation
                    try:
                        self._store.delete(object_id)
                    except Exception:  # noqa: BLE001
                        pass
                raise errors[0]
            if seal is not None:
                seal()
            else:
                self._store.put_into(
                    object_id, size,
                    lambda view: view.__setitem__(slice(0, size), staging))
            self.stats["pulls"] += 1
            self.stats["bytes"] += size
            return True
        finally:
            async with self._admission:
                self._in_flight_bytes -= size
                self._admission.notify_all()
