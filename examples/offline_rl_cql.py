"""End-to-end: offline RL (CQL) from a Data-tier dataset.

Generates a behavior dataset with a noisy scripted policy, loads it
through ray_tpu.data, and trains a conservative Q-learner that recovers
the good policy without ever touching the environment.

Run: python examples/offline_rl_cql.py
"""

import numpy as np

import ray_tpu
from ray_tpu import data
from ray_tpu.rl import CQL, CQLParams


def make_dataset(n=4096, seed=0):
    rng = np.random.default_rng(seed)
    obs = rng.normal(size=(n, 4)).astype(np.float32)
    best = (obs[:, 0] + obs[:, 2] > 0).astype(np.int32)
    actions = np.where(rng.random(n) < 0.85, best, 1 - best).astype(np.int32)
    rewards = (actions == best).astype(np.float32)
    return [
        {
            "obs": obs[i],
            "actions": int(actions[i]),
            "rewards": float(rewards[i]),
            "next_obs": obs[(i + 1) % n],
            "terminals": 1.0,
        }
        for i in range(n)
    ], obs, best


def main():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    rows, obs, best = make_dataset()
    ds = data.from_items(rows)
    cql = CQL(obs_dim=4, num_actions=2, params=CQLParams(cql_alpha=1.0))
    for epoch in range(10):
        m = cql.train_on(ds, batch_size=512)
        print(f"epoch {epoch}: td={m['td_loss']:.4f} "
              f"cql={m['cql_penalty']:.4f}")
    acc = float((np.asarray(cql.act_greedy(cql.params, obs)) == best).mean())
    print(f"greedy-policy accuracy vs optimal: {acc:.3f}")
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
