"""Worker-side training session: ``report``, ``get_context``.

Parity: ``ray.train.report`` / ``ray.train.get_context``
(``python/ray/train/_internal/session.py``).  The session lives in the
worker actor; ``report()`` enqueues (metrics, checkpoint) rows the
controller polls (Train-v2 poll-based worker group,
``python/ray/train/v2/_internal/execution/worker_group/worker_group.py``).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session_lock = threading.Lock()
_session: Optional["_TrainSession"] = None


class _TrainSession:
    def __init__(
        self,
        rank: int,
        world_size: int,
        group_name: str,
        config: Dict[str, Any],
        checkpoint: Optional[Checkpoint],
        mesh_config: Any = None,
        axis_rules: Optional[Dict[str, Any]] = None,
    ):
        self.rank = rank
        self.world_size = world_size
        self.group_name = group_name
        self.config = config
        self.latest_checkpoint = checkpoint
        self.results: "queue.Queue" = queue.Queue()
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        self.error_tb: Optional[str] = None
        self.dataset_shard: Any = None
        # the REQUESTED mesh (parallel.MeshConfig or None) + rule-table
        # override from ScalingConfig; get_mesh() resolves it against the
        # devices this generation actually sees, so every elastic restart
        # re-forms a mesh that fits the surviving hardware
        self.mesh_config = mesh_config
        self.axis_rules = axis_rules
        self._mesh = None  # resolved jax Mesh, built lazily once
        # set by the controller when the node hosting this worker got a
        # drain (preemption) notice: the loop should checkpoint at its
        # next step boundary; cleared when a checkpoint is reported
        self.checkpoint_requested = threading.Event()


def _start_session(**kw) -> _TrainSession:
    global _session
    with _session_lock:
        _session = _TrainSession(**kw)
        return _session


def _get_session() -> _TrainSession:
    s = _session
    if s is None:
        raise RuntimeError(
            "No training session active — this API must be called inside "
            "a train_loop_per_worker"
        )
    return s


def report(
    metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None
) -> None:
    """Report metrics (and optionally a checkpoint) to the controller."""
    s = _get_session()
    if checkpoint is not None:
        s.checkpoint_requested.clear()
    s.results.put({"metrics": dict(metrics), "checkpoint": checkpoint})


# -- GSPMD mesh + sharding (worker-side face of ScalingConfig.mesh) ----------


def get_mesh():
    """The resolved ``jax.sharding.Mesh`` for this worker generation.

    Joins the multi-process jax runtime first (no-op single-process),
    then resolves the *requested* ``ScalingConfig.mesh`` against the
    devices actually visible — ``MeshConfig.clamp_to`` degrades fixed
    axes that no longer fit, so a restart after a drain shrank the group
    re-forms a valid smaller mesh instead of dying on a divisibility
    error.  No mesh request means pure data parallelism over every
    device.  Built once per session and cached.
    """
    s = _get_session()
    if s._mesh is not None:
        return s._mesh
    from ray_tpu.train.trainer import initialize_jax_distributed

    initialize_jax_distributed()
    import logging

    import jax

    from ray_tpu.parallel.mesh import MeshConfig, create_mesh

    requested = s.mesh_config or MeshConfig(dp=-1)
    n = len(jax.devices())
    concrete = requested.clamp_to(n)
    try:
        fits = requested.resolve(n) == concrete.resolve(n)
    except ValueError:
        fits = False
    if not fits:
        logging.getLogger(__name__).warning(
            "train %s: requested mesh (%s) does not fit %d devices; "
            "clamped to (%s)", s.group_name, requested._named(), n,
            concrete._named())
    s._mesh = create_mesh(concrete)
    return s._mesh


def shard_params(params: Any, spec_tree: Any, rules=None):
    """Place a host-materialized param pytree on the session mesh as
    ``NamedSharding`` arrays, per its logical-axis ``spec_tree`` (e.g.
    ``llama_param_specs(cfg)``) and the session's rule table.

    Works single- and multi-process: every process passes the same full
    host tree and contributes the shards its local devices own.  (For
    models too big to materialize on one host, init inside ``jit`` with
    sharded ``out_shardings`` instead — ``ShardedTrainer.init_state``
    does exactly that.)
    """
    import numpy as np

    import jax

    from ray_tpu.parallel.sharding import spec_tree_to_shardings

    s = _get_session()
    mesh = get_mesh()
    shardings = spec_tree_to_shardings(
        spec_tree, mesh, rules or s.axis_rules)

    def _put(x, sh):
        arr = np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, sh, lambda idx: arr[idx])

    return jax.tree.map(_put, params, shardings)


def shard_inputs(batch: Any, logical_axes=("batch",), rules=None):
    """Shard per-step input arrays over the session mesh's data axes.

    ``logical_axes`` names each array dimension (default: leading
    "batch" dim sharded over dp×fsdp, rest replicated).  Single-process:
    a plain sharded ``device_put``.  Multi-process: each process passes
    its *local* rows and they concatenate, in rank order, into one
    global array — the multi-host batch contract of
    ``jax.distributed`` — without the loop touching
    ``multihost_utils``.
    """
    import jax

    from ray_tpu.parallel.sharding import logical_to_pspec

    s = _get_session()
    mesh = get_mesh()
    spec = logical_to_pspec(logical_axes, rules or s.axis_rules, mesh=mesh)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return jax.tree.map(
            lambda x: multihost_utils.host_local_array_to_global_array(
                x, mesh, spec), batch)
    from jax.sharding import NamedSharding

    sh = NamedSharding(mesh, spec)
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)


class TrainContext:
    def get_world_size(self) -> int:
        return _get_session().world_size

    def get_world_rank(self) -> int:
        return _get_session().rank

    def get_local_rank(self) -> int:
        return _get_session().rank  # single-node local == world for now

    def get_trial_name(self) -> str:
        return _get_session().group_name

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return _get_session().latest_checkpoint

    def get_config(self) -> Dict[str, Any]:
        return _get_session().config

    def get_mesh(self):
        """The resolved GSPMD mesh for this generation (see
        :func:`get_mesh`)."""
        return get_mesh()

    def shard_params(self, params: Any, spec_tree: Any, rules=None):
        """Place params on the mesh per a logical-axis spec tree (see
        :func:`shard_params`)."""
        return shard_params(params, spec_tree, rules=rules)

    def shard_inputs(self, batch: Any, logical_axes=("batch",), rules=None):
        """Shard input arrays over the mesh's data axes (see
        :func:`shard_inputs`)."""
        return shard_inputs(batch, logical_axes=logical_axes, rules=rules)

    def drain_requested(self) -> bool:
        """True when the node hosting this worker received a drain
        (preemption) notice and the controller asked for an immediate
        checkpoint: report one at the next step boundary — steps since
        the last reported checkpoint will be re-run by the replacement
        group.  Loops that checkpoint every step can ignore this."""
        return _get_session().checkpoint_requested.is_set()

    def collective_group(self, backend: str = "tcp",
                         timeout_s: Optional[float] = None) -> str:
        """Join (once) the all-workers collective group; returns its name.

        The DP pattern over DCN-separated hosts: compute grads locally,
        ``col.allreduce(grads, ctx.collective_group())``, apply locally.
        The group name is generation-scoped, so a restarted worker group
        re-forms a FRESH group (new epoch) — a watchdog-aborted
        generation's rendezvous state can never leak into its
        replacement.  ``timeout_s`` bounds every op: a peer that dies or
        hangs mid-allreduce surfaces as ``CollectiveAbortError`` (a
        worker failure the controller restarts from the latest
        checkpoint) instead of wedging this loop forever.
        """
        from ray_tpu.util import collective as col

        s = _get_session()
        name = f"train::{s.group_name}"
        if not col.is_group_initialized(name):
            col.init_collective_group(
                s.world_size, s.rank, backend, name, timeout_s=timeout_s
            )
        return name


def get_context() -> TrainContext:
    return TrainContext()


def get_dataset_shard(name: str = "train"):
    """This rank's dataset shard (parity: ``ray.train.get_dataset_shard``).

    Returns the shard the controller assigned via
    ``DataParallelTrainer(datasets={name: ds})`` — a ``DataIterator`` for
    ``ray_tpu.data`` datasets (``streaming_split`` per rank), or the value
    itself for plain iterables (replicated).
    """
    s = _get_session()
    shards = s.dataset_shard
    if shards is None:
        raise KeyError(
            f"no datasets were passed to the trainer (requested {name!r})")
    if isinstance(shards, dict):
        if name not in shards:
            raise KeyError(f"no dataset shard named {name!r}; have {list(shards)}")
        return shards[name]
    return shards


class _ProfileCapture:
    """Context manager for ``ray_tpu.train.profile`` (device-level
    profiler; complements the task-span chrome trace of
    ``raytpu timeline``).  Reference counterpart: torch-profiler hooks in
    ``ray.train`` callbacks; here it is ``jax.profiler.trace`` capturing
    XLA/TPU execution (xplane + trace-viewer files, loadable in
    TensorBoard or Perfetto)."""

    def __init__(self, logdir: Optional[str] = None):
        import os

        if logdir is None:
            base = os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu")
            rank = _session.rank if _session is not None else 0
            logdir = os.path.join(base, "profiles", f"rank{rank}")
        self.logdir = logdir

    def __enter__(self):
        import os

        import jax

        os.makedirs(self.logdir, exist_ok=True)
        jax.profiler.start_trace(self.logdir)
        return self

    def __exit__(self, *exc):
        import jax

        jax.profiler.stop_trace()
        return False


def profile(logdir: Optional[str] = None) -> _ProfileCapture:
    """Capture a device-level profiler trace around training steps::

        for step in range(10):
            if step == 3:
                prof = train.profile().__enter__()
            state, m = train_step(state, batch)
            if step == 5:
                prof.__exit__()

    or as a context manager around a block of steps.  Writes per-rank
    trace directories under the session dir by default."""
    return _ProfileCapture(logdir)
