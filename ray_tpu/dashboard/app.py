"""Dashboard head: assembles the per-subsystem modules.

Reference: ``python/ray/dashboard/head.py:45`` + per-subsystem modules
(``dashboard/modules/{node,job,actor,serve,train,metrics,log,...}``).
Served from the head process (same event loop as the GCS), so every
endpoint is a direct read of GCS tables — no aggregation RPCs needed on
a single head; node-scoped endpoints proxy through that node's raylet
(the per-node agent role).
"""

from __future__ import annotations

import json


def build_app(gcs) -> "object":
    from aiohttp import web

    from ray_tpu.dashboard.modules import ALL_MODULES
    from ray_tpu.dashboard.ui import INDEX_HTML

    def jresp(data) -> "web.Response":
        return web.Response(text=json.dumps(data, default=str),
                            content_type="application/json")

    async def index(_req):
        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def healthz(_req):
        return jresp({"status": "ok"})

    app = web.Application()
    app.router.add_get("/", index)
    app.router.add_get("/-/healthz", healthz)
    # modules may register background coroutines (e.g. the metrics
    # history sampler); started with the app, cancelled at cleanup
    background: list = []
    helpers = {"jresp": jresp, "web": web, "background_tasks": background}
    for module in ALL_MODULES:
        for method, path, handler in module.routes(gcs, helpers):
            app.router.add_route(method, path, handler)

    async def _run_background(app_):
        import asyncio

        tasks = [asyncio.ensure_future(fn()) for fn in background]
        yield
        for t in tasks:
            t.cancel()
        # deliver the cancellations before teardown completes, or asyncio
        # logs "Task was destroyed but it is pending!"
        await asyncio.gather(*tasks, return_exceptions=True)

    app.cleanup_ctx.append(_run_background)
    return app


async def start_dashboard(gcs, host: str = "127.0.0.1", port: int = 0
                          ) -> str:
    """Start the dashboard on the current loop; returns its http address."""
    from aiohttp import web

    app = build_app(gcs)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    actual_port = site._server.sockets[0].getsockname()[1]
    return f"http://{host}:{actual_port}"
