"""Causal distributed tracing + step-time attribution (ISSUE 9).

Tiers:

1. **Core units** (no cluster): span/trace context-manager parenting,
   task-context minting, the chrome-trace renderer's phase synthesis,
   disabled-mode no-ops, the span-buffer bound.
2. **Exposition** — Prometheus histogram rendering (cumulative
   ``_bucket`` counts, ``le`` ordering, ``+Inf``, ``_sum``/``_count``
   consistency) and label-value escaping, plus the publisher interval
   env and the dashboard aggregator's stale sweep.
3. **E2E** — a driver→actor→nested-task→collective-op chain exports ONE
   connected trace: shared trace_id, every parent link resolves,
   submit/queue/execute phases present, owner-side lease span present.
4. **Bench attribution** — ``bench.measure_step_breakdown`` buckets sum
   to the step wall within 10% and the instrumentation overhead with
   tracing off stays <2%.
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu._private import tracing


@pytest.fixture
def fresh_tracing(monkeypatch):
    """Enabled tracing + a clean span buffer, restored afterwards."""
    monkeypatch.setenv(tracing.ENV_ENABLED, "1")
    tracing.clear_local()
    yield
    tracing.clear_local()


# ---------------------------------------------------------------------------
# 1. core units
# ---------------------------------------------------------------------------


class TestTraceCore:
    def test_span_nesting_parents(self, fresh_tracing):
        with tracing.trace("root") as root:
            assert root.parent_span_id is None
            with tracing.span("outer") as outer:
                assert outer.trace_id == root.trace_id
                assert outer.parent_span_id == root.span_id
                with tracing.span("inner") as inner:
                    assert inner.parent_span_id == outer.span_id
        spans = {s["name"]: s for s in tracing.local_spans()}
        assert spans["inner"]["parent_span_id"] == spans["outer"]["span_id"]
        assert spans["outer"]["parent_span_id"] == spans["root"]["span_id"]
        assert spans["root"]["parent_span_id"] is None
        assert len({s["trace_id"] for s in
                    (spans["root"], spans["outer"], spans["inner"])}) == 1
        # completed spans have sane timestamps
        assert all(s["end"] >= s["start"] for s in spans.values())

    def test_trace_mints_fresh_trace_ids(self, fresh_tracing):
        with tracing.trace("a") as a:
            pass
        with tracing.trace("b") as b:
            pass
        assert a.trace_id != b.trace_id

    def test_mint_task_context_parents_to_current(self, fresh_tracing):
        with tracing.trace("root") as root:
            tc = tracing.mint_task_context("fn")
        assert tc["trace_id"] == root.trace_id
        assert tc["parent_span_id"] == root.span_id
        assert tc["span_id"] != root.span_id
        assert tc["submitted_at"] <= time.time()

    def test_mint_without_scope_uses_process_root(self, fresh_tracing):
        tc = tracing.mint_task_context("fn")
        assert tc["parent_span_id"] is not None
        # the lazy root is exported as an open span so the link resolves
        roots = [s for s in tracing.local_spans()
                 if s["span_id"] == tc["parent_span_id"]]
        assert roots and roots[0].get("open")

    def test_task_scope_installs_context(self, fresh_tracing):
        tc = {"trace_id": "t" * 16, "span_id": "s" * 12,
              "parent_span_id": None}
        with tracing.task_scope(tc):
            cur = tracing.current()
            assert cur.trace_id == tc["trace_id"]
            assert cur.span_id == tc["span_id"]
            child = tracing.mint_task_context("nested")
            assert child["parent_span_id"] == tc["span_id"]
        assert tracing.current() is None

    def test_disabled_mode_records_nothing(self, monkeypatch):
        monkeypatch.setenv(tracing.ENV_ENABLED, "0")
        tracing.clear_local()
        assert tracing.mint_task_context("fn") is None
        assert tracing.start_span("x") is None
        with tracing.span("y") as ctx:
            assert ctx is None
        with tracing.trace("z") as ctx:
            assert ctx is None
        assert tracing.local_spans() == []
        tracing.clear_local()

    def test_manual_span_end(self, fresh_tracing):
        s = tracing.start_span("manual", attrs={"k": 1})
        assert any(sp.get("open") for sp in tracing.local_spans())
        s.end()
        s.end()  # idempotent
        done = [sp for sp in tracing.local_spans() if sp["name"] == "manual"]
        assert len(done) == 1 and not done[0].get("open")

    def test_note_duration_sink_routing(self):
        got = []
        token = tracing.register_duration_sink(
            lambda b, s: got.append((b, s)))
        try:
            tracing.note_duration("compute", 0.5)
        finally:
            tracing.unregister_duration_sink(token)
        tracing.note_duration("compute", 0.25)  # after unregister: dropped
        assert got == [("compute", 0.5)]

    def test_chrome_renderer_synthesizes_phases(self):
        now = time.time()
        ev = {
            "task_id": "ab" * 8, "name": "myfn", "kind": "NORMAL_TASK",
            "start": now - 1.0, "end": now, "ok": True,
            "worker_id": "w1", "node_id": "n1",
            "trace": {"trace_id": "t1", "span_id": "s1",
                      "parent_span_id": "p1",
                      "submitted_at": now - 3.0, "received_at": now - 2.0},
        }
        legacy = {"task_id": "cd" * 8, "name": "oldfn", "start": now - 1.0,
                  "end": now, "ok": True, "worker_id": "w1",
                  "node_id": "n1"}
        out = tracing.chrome_trace_events([ev, legacy])
        by_phase = {e["args"].get("phase"): e for e in out
                    if "phase" in e.get("args", {})}
        assert set(by_phase) == {"task", "submit", "queue", "execute"}
        task = by_phase["task"]
        assert task["ts"] == pytest.approx((now - 3.0) * 1e6)
        assert task["args"]["parent_span_id"] == "p1"
        for phase in ("submit", "queue", "execute"):
            assert by_phase[phase]["args"]["parent_span_id"] == "s1"
            assert by_phase[phase]["args"]["span_id"] == f"s1.{phase}"
        assert by_phase["submit"]["dur"] == pytest.approx(1e6)
        assert by_phase["queue"]["dur"] == pytest.approx(1e6)
        assert by_phase["execute"]["dur"] == pytest.approx(1e6)
        # legacy event renders exactly as the old execution box
        old = [e for e in out if e["name"] == "oldfn"]
        assert len(old) == 1 and "trace_id" not in old[0]["args"]

    def test_span_buffer_bounded(self, fresh_tracing):
        cap = tracing._buffer_cap()
        with tracing.trace("flood"):
            for i in range(cap + 50):
                with tracing.span(f"s{i}"):
                    pass
        assert len(tracing.local_spans()) <= cap + len(tracing._open) + 1


# ---------------------------------------------------------------------------
# 2. exposition + publisher satellites
# ---------------------------------------------------------------------------


class TestPrometheusExposition:
    def test_histogram_exposition_contract(self):
        from ray_tpu.util import metrics

        h = metrics.Histogram("tt_hist_contract", "hist under test",
                              boundaries=[0.1, 1.0, 5.0],
                              tag_keys=("route",))
        for v in (0.05, 0.5, 0.7, 2.0, 50.0):
            h.observe(v, tags={"route": "/a"})
        text = metrics.prometheus_text(metrics.collect_local())
        lines = [ln for ln in text.splitlines()
                 if ln.startswith("tt_hist_contract")]
        bucket_lines = [ln for ln in lines if "_bucket" in ln]
        # le ordering: finite ascending then +Inf
        les = [ln.split('le="')[1].split('"')[0] for ln in bucket_lines]
        assert les == ["0.1", "1.0", "5.0", "+Inf"]
        counts = [float(ln.rsplit(" ", 1)[1]) for ln in bucket_lines]
        # cumulative and monotone: 1 obs <=0.1, 3 <=1.0, 4 <=5.0, 5 total
        assert counts == [1, 3, 4, 5]
        inf_count = counts[-1]
        count_line = next(ln for ln in lines if "_count" in ln)
        sum_line = next(ln for ln in lines if "_sum" in ln)
        assert float(count_line.rsplit(" ", 1)[1]) == inf_count == 5
        assert float(sum_line.rsplit(" ", 1)[1]) == pytest.approx(53.25)
        # TYPE declared before samples
        assert text.index("# TYPE tt_hist_contract histogram") \
            < text.index(bucket_lines[0])

    def test_label_value_escaping(self):
        from ray_tpu.util import metrics

        c = metrics.Counter("tt_escape_counter", "desc", tag_keys=("path",))
        c.inc(1.0, tags={"path": 'a\\b"c\nd'})
        text = metrics.prometheus_text(metrics.collect_local())
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("tt_escape_counter{"))
        assert '\\\\b' in line          # backslash escaped
        assert '\\"c' in line           # quote escaped
        assert "\n" not in line         # newline never raw inside a line
        assert "\\n" in line            # ... it is escaped instead
        # the label block still parses as one balanced {...} token
        assert line.count("{") == 1 and line.count("}") == 1

    def test_histogram_label_escaping(self):
        from ray_tpu.util import metrics

        h = metrics.Histogram("tt_escape_hist", "h", boundaries=[1.0],
                              tag_keys=("q",))
        h.observe(0.5, tags={"q": 'x"y'})
        text = metrics.prometheus_text(metrics.collect_local())
        assert 'q="x\\"y"' in text

    def test_publish_interval_env(self, monkeypatch):
        from ray_tpu.util import metrics

        monkeypatch.delenv(metrics.ENV_PUBLISH_INTERVAL, raising=False)
        assert metrics.publish_interval_s() == 5.0
        monkeypatch.setenv(metrics.ENV_PUBLISH_INTERVAL, "0.7")
        assert metrics.publish_interval_s() == pytest.approx(0.7)
        monkeypatch.setenv(metrics.ENV_PUBLISH_INTERVAL, "0.01")
        assert metrics.publish_interval_s() == 0.2  # floored
        monkeypatch.setenv(metrics.ENV_PUBLISH_INTERVAL, "junk")
        assert metrics.publish_interval_s() == 5.0

    def test_final_publish_lands_in_kv(self, ray_start):
        from ray_tpu.experimental.internal_kv import _internal_kv_get_prefix
        from ray_tpu.util import metrics

        c = metrics.Counter("tt_final_publish", "final-flush proof")
        c.inc(3.0)
        metrics.final_publish()  # no interval wait
        table = _internal_kv_get_prefix("metrics/", namespace="metrics")
        found = [json.loads(raw) for raw in table.values()]
        assert any("tt_final_publish" in rec.get("metrics", {})
                   for rec in found)

    def test_aggregator_sweeps_stale_workers(self):
        import types

        from ray_tpu.dashboard.modules.metrics import (STALE_S,
                                                       aggregate_metrics)

        now = time.time()
        fresh = json.dumps({"ts": now, "metrics": {
            "m": {"kind": "gauge", "series": [{"tags": {}, "value": 1.0}]}}})
        stale = json.dumps({"ts": now - STALE_S - 60, "metrics": {
            "dead": {"kind": "gauge",
                     "series": [{"tags": {}, "value": 9.0}]}}})
        stale_trace = json.dumps({"ts": now - STALE_S - 60, "spans": []})
        gcs = types.SimpleNamespace(kv={
            ("metrics", "metrics/live"): fresh,
            ("metrics", "metrics/dead"): stale,
            ("trace", "spans/dead"): stale_trace,
            ("other", "key"): b"untouched",
        }, _dirty=False)
        merged = aggregate_metrics(gcs)
        assert "m" in merged and "dead" not in merged
        # stale records deleted from the KV itself, fresh ones kept
        assert ("metrics", "metrics/dead") not in gcs.kv
        assert ("trace", "spans/dead") not in gcs.kv
        assert ("metrics", "metrics/live") in gcs.kv
        assert ("other", "key") in gcs.kv
        assert gcs._dirty


# ---------------------------------------------------------------------------
# 3. e2e: one connected trace across driver→actor→nested task→collective
# ---------------------------------------------------------------------------


def _trace_events(events, trace_id):
    return [e for e in events
            if (e.get("args") or {}).get("trace_id") == trace_id]


def _connected(events, trace_id):
    """True when every span of the trace is reachable from its root."""
    mine = _trace_events(events, trace_id)
    ids = {e["args"]["span_id"] for e in mine}
    roots = [e for e in mine if e["args"].get("parent_span_id") is None]
    if not roots:
        return False
    children = {}
    for e in mine:
        p = e["args"].get("parent_span_id")
        if p is not None:
            children.setdefault(p, []).append(e["args"]["span_id"])
    seen = set()
    stack = [r["args"]["span_id"] for r in roots]
    while stack:
        s = stack.pop()
        if s in seen:
            continue
        seen.add(s)
        stack.extend(children.get(s, ()))
    return seen == ids


def test_connected_trace_driver_actor_nested_collective(
        no_cluster, monkeypatch):
    """The acceptance chain: driver→actor→nested-task→collective-op must
    export ONE connected trace — shared trace_id, every parent link
    resolving, submit/queue/execute phases and an owner-side lease span
    present."""
    import uuid

    monkeypatch.setenv("RAY_TPU_METRICS_INTERVAL_S", "0.5")
    monkeypatch.setenv(tracing.ENV_ENABLED, "1")
    tracing.clear_local()
    ray_tpu.init(num_cpus=8, num_tpus=0)
    from ray_tpu.util import state as state_api

    @ray_tpu.remote
    class ChainWorker:
        def __init__(self, rank, world):
            self.rank = rank
            self.world = world

        def setup(self, name):
            from ray_tpu.util import collective as col

            col.init_collective_group(self.world, self.rank, "tcp", name)
            return self.rank

        def run_chain(self, name):
            import numpy as np

            import ray_tpu as rt
            from ray_tpu.util import collective as col

            @rt.remote
            def nested(x):
                return x + 1

            val = rt.get(nested.remote(self.rank), timeout=60)
            out = col.allreduce(np.ones(4), name)
            return val + float(out[0])

    group = f"trace-{uuid.uuid4().hex[:8]}"
    workers = [ChainWorker.remote(i, 2) for i in range(2)]
    ray_tpu.get([w.setup.remote(group) for w in workers], timeout=120)

    with tracing.trace("e2e-chain") as root:
        outs = ray_tpu.get([w.run_chain.remote(group) for w in workers],
                           timeout=120)
    assert sorted(outs) == [3.0, 4.0]
    trace_id = root.trace_id

    deadline = time.time() + 45
    last = []
    while time.time() < deadline:
        last = state_api.timeline()
        mine = _trace_events(last, trace_id)
        names = [e["name"] for e in mine]
        phases = {e["args"].get("phase") for e in mine}
        if (names.count("run_chain") >= 2
                and any(n.endswith("nested") for n in names)
                and any(n.startswith("collective.") for n in names)
                and "lease" in names
                and {"submit", "queue", "execute"} <= phases
                and _connected(last, trace_id)):
            break
        time.sleep(0.5)

    mine = _trace_events(last, trace_id)
    names = [e["name"] for e in mine]
    assert names.count("run_chain") >= 2, names
    assert any(n.endswith("nested") for n in names), names
    assert any(n.startswith("collective.") for n in names), names
    assert "lease" in names, names
    phases = {e["args"].get("phase") for e in mine}
    assert {"submit", "queue", "execute"} <= phases, phases
    # ONE connected tree: every parent link resolves from the root
    assert _connected(last, trace_id), \
        [(e["name"], e["args"].get("span_id"),
          e["args"].get("parent_span_id")) for e in mine]
    # the nested task's parent is one of the actor-task spans
    chain_ids = {e["args"]["span_id"] for e in mine
                 if e["name"] == "run_chain"
                 and e["args"].get("phase") == "task"}
    nested_parents = {e["args"]["parent_span_id"] for e in mine
                      if e["name"].endswith("nested")
                      and e["args"].get("phase") == "task"}
    assert nested_parents and nested_parents <= chain_ids
    # the collective spans hang off the actor-task spans too
    coll_parents = {e["args"]["parent_span_id"] for e in mine
                    if e["name"].startswith("collective.")}
    assert coll_parents <= chain_ids, (coll_parents, chain_ids)
    ray_tpu.shutdown()


def test_timeline_file_is_valid_chrome_trace(ray_start, tmp_path):
    """timeline(filename) writes loadable chrome-trace JSON whose traced
    tasks carry the new phase spans."""
    from ray_tpu.util import state as state_api

    @ray_tpu.remote
    def traced_for_phases():
        return 1

    assert ray_tpu.get([traced_for_phases.remote() for _ in range(2)],
                       timeout=120) == [1, 1]
    out = str(tmp_path / "timeline.json")
    deadline = time.time() + 30
    phases = set()
    while time.time() < deadline:  # task events flush every ~2s
        events = state_api.timeline(out)
        phases = {e["args"].get("phase") for e in events
                  if isinstance(e.get("args"), dict)
                  and str(e["args"].get("task", "")).endswith(
                      "traced_for_phases")}
        if {"submit", "queue", "execute"} <= phases:
            break
        time.sleep(0.5)
    assert {"submit", "queue", "execute"} <= phases, phases
    loaded = json.load(open(out))
    assert isinstance(loaded, list) and loaded
    for e in loaded:
        assert "ph" in e and "ts" in e and "name" in e
        if e["ph"] == "X":
            assert e["dur"] > 0


def test_serve_request_context_carries_trace(fresh_tracing):
    """The serving plane: a request scope installs the request's trace
    root, so handle calls made inside parent to it."""
    from ray_tpu.serve import context as serve_ctx

    with serve_ctx.request_scope(timeout_s=5.0) as rc:
        assert rc.trace_ctx is not None
        cur = tracing.current()
        assert cur is not None
        assert cur.trace_id == rc.trace_ctx["trace_id"]
        minted = tracing.mint_task_context("replica_call")
        assert minted["trace_id"] == rc.trace_ctx["trace_id"]
        assert minted["parent_span_id"] == rc.trace_ctx["span_id"]
    # the request root span was recorded, so the parent link resolves
    roots = [s for s in tracing.local_spans()
             if s["name"] == "serve.request"
             and s["span_id"] == rc.trace_ctx["span_id"]]
    assert roots
    # round-trips through the wire dict (proxy→router→replica hop)
    again = serve_ctx.RequestContext.from_dict(rc.to_dict())
    assert again.trace_ctx == rc.trace_ctx


# ---------------------------------------------------------------------------
# 4. step-time attribution: ledger units + the bench contract
# ---------------------------------------------------------------------------


class TestStepLedger:
    def test_buckets_and_other(self, fresh_tracing):
        from ray_tpu.train.session import StepLedger

        led = StepLedger(group_name="t", publish=False)
        with led.step():
            with led.bucket("compute"):
                time.sleep(0.05)
            t0 = time.perf_counter()
            time.sleep(0.02)
            # the sink route every auto-attributed subsystem uses
            tracing.note_duration("collective_wait",
                                  time.perf_counter() - t0)
        bd = led.last_breakdown()
        assert bd["step"] == 1
        b = bd["buckets"]
        assert b["compute"] >= 0.05
        assert b["collective_wait"] >= 0.02
        assert b["other"] >= 0.0
        # every second of the step is accounted: buckets (incl. other)
        # reconstruct the measured wall
        assert sum(b.values()) == pytest.approx(bd["wall_s"], rel=0.05)

    def test_no_charge_between_steps(self, fresh_tracing):
        from ray_tpu.train.session import StepLedger

        led = StepLedger(publish=False)
        tracing.note_duration("collective_wait", 5.0)  # no step: dropped
        with led.step():
            pass
        assert led.last_breakdown()["buckets"].get(
            "collective_wait", 0.0) == 0.0

    def test_step_does_not_nest(self, fresh_tracing):
        from ray_tpu.train.session import StepLedger

        led = StepLedger(publish=False)
        with led.step():
            with pytest.raises(RuntimeError):
                with led.step():
                    pass

    def test_step_emits_span_and_histogram(self, fresh_tracing):
        from ray_tpu.train.session import StepLedger
        from ray_tpu.util import metrics

        led = StepLedger(group_name="span-check", publish=False)
        with led.step():
            with led.bucket("compute"):
                pass
        spans = [s for s in tracing.local_spans()
                 if s["name"] == "train.step"]
        assert spans and spans[-1]["attrs"]["group"] == "span-check"
        snap = metrics.collect_local()
        hist = snap["train_step_bucket_s"]["histogram"]
        assert any(h["tags"].get("group") == "span-check" for h in hist)


def test_bench_step_time_breakdown_contract():
    """Acceptance: the bench record's step_time_breakdown bucket sum is
    within 10% of the measured step wall, and the instrumentation
    overhead with tracing off is <2% of the bench step."""
    import jax

    import bench
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.models.training import (default_optimizer,
                                         make_llama_trainer)
    from ray_tpu.parallel import MeshConfig, create_mesh

    cfg = LlamaConfig.tiny()
    mesh = create_mesh(MeshConfig(dp=-1))
    tr = make_llama_trainer(
        cfg, mesh, optimizer=default_optimizer(warmup=1, decay_steps=100))
    state = tr.init_state(jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 129), 0, cfg.vocab_size)
    b = tr.shard_batch({"tokens": tokens})
    for _ in range(2):  # compile + settle
        state, m = tr.step(state, b)
        float(m["loss"])

    # overhead is a minimum-statistic: retry a couple of times so a
    # background-load spike cannot fail a genuinely-<2% instrumentation
    best = None
    for _ in range(3):
        state, bd = bench.measure_step_breakdown(tr, state, b,
                                                 steps=5, runs=3)
        if best is None or bd["tracing_off_overhead_pct"] \
                < best["tracing_off_overhead_pct"]:
            best = bd
        if best["tracing_off_overhead_pct"] < 2.0:
            break
    assert best["steps"] >= 5
    assert set(best["buckets_s"]) >= {"compute", "other"}
    # bucket sum within 10% of measured step wall
    assert best["bucket_sum_s"] == pytest.approx(
        best["step_wall_s"], rel=0.10), best
    assert 0.9 <= best["coverage"] <= 1.1, best
    # tracing-off overhead <2% on the bench step
    assert best["tracing_off_overhead_pct"] < 2.0, best
    # fractions sum to ~1 (the dashboard panel contract)
    assert sum(best["fractions"].values()) == pytest.approx(1.0, rel=0.10)
