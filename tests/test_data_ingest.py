"""Ingest-pipeline tests: block-prefetch lookahead, locality-aware
streaming split, double-buffered H2D staging, and teardown hygiene.

Covers the pipelined data plane end to end (reference model:
``python/ray/data/tests/test_iterator.py`` + the output-splitter
locality tests): lookahead preserves block order and propagates
mid-stream errors in position; abandoning an iterator leaks no producer
threads; ``streaming_split(locality_hints=...)`` routes bundles to their
co-located consumer on a real two-node cluster; a node death mid-stream
falls back to lineage reconstruction; and the CPU smoke bench proves the
overlap (pipelined >= 1.5x forced-serial, consumer-blocked strictly
below total block-fetch time).
"""

import gc
import importlib.util
import os
import signal
import threading
import time

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rd
from ray_tpu.data.block import BlockMetadata, batch_to_block
from ray_tpu.data.context import DataContext
from ray_tpu.data.iterator import DataIterator, _ShuffleBuffer
from ray_tpu.data.operators import OutputSplitter, PhysicalOperator, RefBundle

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bundles_from_blocks(n_blocks: int, rows: int, pad_cols: int = 0):
    """n_blocks put-blocks of ``rows`` rows with globally increasing ids."""
    bundles = []
    for i in range(n_blocks):
        batch = {"id": np.arange(i * rows, (i + 1) * rows)}
        if pad_cols:
            batch["payload"] = np.ones((rows, pad_cols), np.float64)
        block = batch_to_block(batch)
        meta = BlockMetadata.for_block(block)
        bundles.append(RefBundle([(ray_tpu.put(block), meta)]))
    return bundles


def _source_of(bundles, delay_s: float = 0.0, fail_after: int = None,
               exc: BaseException = None):
    def source():
        for i, b in enumerate(bundles):
            if fail_after is not None and i == fail_after:
                raise exc
            if delay_s:
                time.sleep(delay_s)
            yield b
    return source


def _ingest_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("rtpu-data")]


def _wait_ingest_threads_gone(baseline: int, timeout: float = 15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        gc.collect()
        if len(_ingest_threads()) <= baseline:
            return True
        time.sleep(0.1)
    return False


# -- lookahead ordering + error propagation -----------------------------------


def test_lookahead_preserves_block_order(ray_start):
    bundles = _bundles_from_blocks(20, 32)
    it = DataIterator(_source_of(bundles))
    ids = []
    for b in it.iter_batches(batch_size=32, prefetch_batches=2):
        ids.extend(b["id"].tolist())
    assert ids == list(range(20 * 32)), "lookahead reordered blocks"
    d = it.ingest_stats.to_dict()
    assert d["blocks"] == 20 and d["batches"] == 20
    assert d["bytes_fetched"] > 0
    # the human-readable report renders without error
    assert "Ingest pipeline stats" in it.stats()


def test_lookahead_propagates_midstream_source_error(ray_start):
    """A source failure surfaces at its stream position: every earlier
    batch is delivered first, then the original exception raises."""
    bundles = _bundles_from_blocks(6, 16)
    boom = RuntimeError("upstream exploded")
    it = DataIterator(_source_of(bundles, fail_after=4, exc=boom))
    got = []
    with pytest.raises(RuntimeError, match="upstream exploded"):
        for b in it.iter_batches(batch_size=16, prefetch_batches=2):
            got.extend(b["id"].tolist())
    assert got == list(range(4 * 16)), "batches before the error were lost"


def test_lookahead_propagates_block_task_error(ray_start):
    """An errored block ref (failed producing task) raises from the
    consumer at that block's position, not from the lookahead thread."""

    @ray_tpu.remote
    def bad_block():
        raise ValueError("bad block payload")

    bundles = _bundles_from_blocks(3, 8)
    bad_meta = BlockMetadata(num_rows=8, size_bytes=256)
    bundles.insert(2, RefBundle([(bad_block.remote(), bad_meta)]))
    it = DataIterator(_source_of(bundles))
    got = []
    with pytest.raises(Exception, match="bad block payload"):
        for b in it.iter_batches(batch_size=8, prefetch_batches=2):
            got.extend(b["id"].tolist())
    assert got == list(range(2 * 8))


def test_forced_serial_fallback_still_works(ray_start):
    """lookahead_bytes=0 is the A/B baseline: same results, no threads."""
    ctx = DataContext.get_current()
    saved = ctx.iterator_lookahead_bytes
    ctx.iterator_lookahead_bytes = 0
    try:
        bundles = _bundles_from_blocks(5, 16)
        it = DataIterator(_source_of(bundles))
        ids = [v for b in it.iter_batches(batch_size=16, prefetch_batches=0)
               for v in b["id"].tolist()]
        assert ids == list(range(5 * 16))
        d = it.ingest_stats.to_dict()
        # serial: every stall is on the consumer, so blocked == fetch total
        assert d["consumer_blocked_s"] >= d["block_fetch_s"]
    finally:
        ctx.iterator_lookahead_bytes = saved


# -- abandonment hygiene ------------------------------------------------------


def test_early_abandon_leaves_no_threads(ray_start):
    """A consumer that breaks after one batch must not leave lookahead or
    prefetch producer threads alive (the pre-PR leak: blocked in q.put)."""
    baseline = len(_ingest_threads())
    bundles = _bundles_from_blocks(30, 64)
    it = DataIterator(_source_of(bundles, delay_s=0.005))
    for b in it.iter_batches(batch_size=64, prefetch_batches=2):
        break  # abandon with the pipeline full and the source mid-stream
    del it, b
    assert _wait_ingest_threads_gone(baseline), (
        f"leaked ingest threads: {_ingest_threads()}")


def test_early_abandon_dataset_iterator_stops_executor(ray_start):
    """Abandoning a Dataset-backed iterator must also wind down the
    streaming executor's control thread — its end-of-stream sentinel put
    must not block forever on the full, never-drained output queue."""
    baseline = len(_ingest_threads())
    it = rd.range(5000, parallelism=50).iterator()
    for b in it.iter_batches(batch_size=10, prefetch_batches=2):
        break
    del it, b
    assert _wait_ingest_threads_gone(baseline, timeout=20), (
        f"leaked ingest/executor threads: {_ingest_threads()}")


def test_early_abandon_jax_iterator_leaves_no_threads(ray_start):
    baseline = len(_ingest_threads())
    bundles = _bundles_from_blocks(30, 64)
    it = DataIterator(_source_of(bundles, delay_s=0.005))
    gen = it.iter_jax_batches(batch_size=64, prefetch_batches=2,
                              drop_last=False)
    next(gen)
    gen.close()  # train-failure path: the generator is closed explicitly
    del gen, it
    assert _wait_ingest_threads_gone(baseline), (
        f"leaked ingest threads: {_ingest_threads()}")


# -- device staging -----------------------------------------------------------


def test_iter_jax_batches_device_buffer_depth(ray_start):
    """The device-side buffer holds exactly prefetch_batches staged
    batches while the consumer is the slow stage (acceptance criterion:
    asserted via the stats report)."""
    import jax.numpy as jnp

    # the high-water mark needs the producer to outpace the consumer;
    # under suite load the producer threads can be starved, so escalate
    # the consumer's slowness until the buffer demonstrably fills
    d = None
    for step_s in (0.03, 0.1, 0.3):
        bundles = _bundles_from_blocks(10, 32)
        it = DataIterator(_source_of(bundles))
        total = 0.0
        for b in it.iter_jax_batches(batch_size=32, prefetch_batches=2,
                                     dtypes={"id": np.float32},
                                     drop_last=False):
            assert b["id"].dtype == jnp.float32
            total += float(b["id"].sum())
            time.sleep(step_s)  # slow consumer: buffer fills behind us
        assert total == float(np.arange(10 * 32).sum())
        d = it.ingest_stats.to_dict()
        assert d["device_buffer_capacity"] == 2
        assert d["h2d_s"] > 0.0
        if d["device_prefetch_depth"] == 2:
            break
    assert d["device_prefetch_depth"] == 2, (
        f"device buffer never reached its depth: {d}")


# -- local shuffle buffer -----------------------------------------------------


def test_shuffle_buffer_stays_topped_up():
    """Chunked sampling: the buffer never drains below min_rows while the
    stream is live (no full-drain latency spike), and every row comes out
    exactly once."""
    buf = _ShuffleBuffer(min_rows=64, seed=7, chunk_rows=16)
    out = []
    for i in range(12):
        block = batch_to_block({"id": np.arange(i * 16, (i + 1) * 16)})
        for sampled in buf.add(block):
            assert sampled.num_rows <= 16, "drained more than one chunk"
            assert buf._rows >= 64, "buffer drained below min_rows mid-stream"
            out.extend(sampled.column("id").to_pylist())
    for sampled in buf.flush():
        out.extend(sampled.column("id").to_pylist())
    assert sorted(out) == list(range(12 * 16))
    assert out != sorted(out), "buffer produced no shuffling"


def test_local_shuffle_through_iterator_complete_and_shuffled(ray_start):
    ds = rd.range(200, parallelism=4)
    ids = [v for b in ds.iter_batches(batch_size=20,
                                      local_shuffle_buffer_size=80,
                                      local_shuffle_seed=11)
           for v in b["id"].tolist()]
    assert sorted(ids) == list(range(200))
    assert ids != list(range(200))


def test_get_local_object_locations(ray_start):
    """The experimental no-RPC location probe backing the ingest ledger's
    cross-node accounting: sealed shm objects map to their node, inline
    objects to None."""
    from ray_tpu.experimental import get_local_object_locations

    big = ray_tpu.put(np.ones(512 * 1024, np.uint8))  # shm-resident
    small = ray_tpu.put(7)                            # inline
    locs = get_local_object_locations([big, small])
    me = ray_tpu.get_runtime_context().get_node_id()
    assert locs[big] == me
    assert locs[small] is None


# -- locality-aware split routing (unit) --------------------------------------


def _forged_bundle(node: str, rows: int = 64, size: int = 4096):
    meta = BlockMetadata(num_rows=rows, size_bytes=size, exec_node_id=node)
    return RefBundle([(None, meta)])


def test_output_splitter_prefers_colocated_consumer():
    src = PhysicalOperator("src", [])
    sp = OutputSplitter(src, 2, locality_hints=["nodeA", "nodeB"])
    for node, want in (("nodeA", 0), ("nodeB", 1), ("nodeA", 0),
                       ("nodeB", 1)):
        b = _forged_bundle(node)
        sp.add_input(b)
        assert sp.queues[want][-1] is b, f"{node} misrouted"
    stats = sp.split_stats()
    assert stats["locality_hits"] == 4 and stats["locality_misses"] == 0
    # unknown producer falls back to fewest-rows, counted as a miss
    sp.add_input(_forged_bundle(None))
    assert sp.split_stats()["locality_misses"] == 1


def test_output_splitter_bounds_skew():
    """The co-located consumer is skipped once it runs ahead of the
    least-loaded one by more than the configured skew budget."""
    ctx = DataContext.get_current()
    saved = ctx.locality_split_max_skew_rows
    ctx.locality_split_max_skew_rows = 100
    try:
        src = PhysicalOperator("src", [])
        sp = OutputSplitter(src, 2, locality_hints=["nodeA", "nodeB"])
        for _ in range(4):  # all prefer rank 0; 64 rows each
            sp.add_input(_forged_bundle("nodeA"))
        stats = sp.split_stats()
        assert stats["rows_per_output"][1] > 0, (
            "skew bound never forced a spill to the other consumer")
        assert stats["locality_misses"] > 0
        assert max(stats["rows_per_output"]) - \
            min(stats["rows_per_output"]) <= 100 + 64
    finally:
        ctx.locality_split_max_skew_rows = saved


def test_ingest_telemetry_retires_on_final_publish(ray_start):
    """Per-iterator telemetry must not accumulate forever: the final
    publish drops the iterator's gauge tag series and sweeps KV records
    past the panel's stale window (incl. iterators that died silently)."""
    import json as json_mod

    from ray_tpu.data.iterator import IngestStats, _gauges
    from ray_tpu.experimental.internal_kv import (_internal_kv_get_prefix,
                                                  _internal_kv_put)

    stale = {"ts": time.time() - 3600, "iterator": "it-dead", "done": False}
    _internal_kv_put(b"iter/it-dead", json_mod.dumps(stale).encode(),
                     namespace="data")

    s = IngestStats()
    s._t_start -= 5.0  # old enough that the final publish isn't throttled
    s._publish_metrics(s.to_dict())
    g = _gauges["data_ingest_block_wait_s"]
    assert any(t.get("iterator") == s.iterator_id for t, _ in g.snapshot())

    s.maybe_publish(final=True)
    recs = _internal_kv_get_prefix("iter/", namespace="data")
    assert "iter/it-dead" not in recs, "stale record survived the sweep"
    assert f"iter/{s.iterator_id}" in recs, "final record must stay visible"
    assert not any(t.get("iterator") == s.iterator_id
                   for t, _ in g.snapshot()), "gauge series not retired"


def test_split_stats_merge_is_idempotent():
    """The coordinator's counters are cumulative totals — folding them in
    repeatedly (stats() per epoch, the periodic publish) must not
    multiply the reported hit rate."""
    from ray_tpu.data.iterator import IngestStats

    s = IngestStats()
    for _ in range(3):
        s.merge_split_stats({"locality_hits": 10, "locality_misses": 2})
    d = s.to_dict()
    assert d["locality_hits"] == 10 and d["locality_misses"] == 2


def test_streaming_split_rejects_bad_hints(ray_start):
    with pytest.raises(ValueError, match="locality_hints"):
        rd.range(10).streaming_split(2, locality_hints=["only-one"])


# -- locality-aware split (two real nodes) ------------------------------------


def test_streaming_split_locality_two_nodes(no_cluster):
    """With locality_hints on a two-node cluster, the majority of bundles
    route to their co-located consumer and the consumers pull measurably
    fewer cross-node bytes than the locality-free baseline (acceptance
    criterion)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    # defined inside the test so cloudpickle ships them by value — the
    # cluster's workers cannot import the pytest-loaded test module
    @ray_tpu.remote
    class ShardConsumer:
        def consume(self, it):
            rows = 0
            for b in it.iter_batches(batch_size=64, prefetch_batches=2):
                rows += len(b["id"])
            return rows, it.ingest_stats.to_dict()

    def pad_payload(b):
        # ~256KB blocks: above the inline threshold, so cross-node pulls
        # are real transfers the ingest ledger can account
        return {"id": b["id"], "payload": np.ones((len(b["id"]), 512),
                                                  np.float64)}

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        cluster.connect()
        # separate session dir -> own shm arena: cross-node gets travel
        # the real chunked transfer plane
        worker = cluster.add_node(num_cpus=2, separate_session=True)
        cluster.wait_for_nodes()
        alive = [n["node_id"] for n in ray_tpu.nodes() if n["alive"]]
        worker_id = worker.node_id
        head_id = next(n for n in alive if n != worker_id)

        def run(hints):
            ds = rd.range(1024, parallelism=16).map_batches(pad_payload)
            its = ds.streaming_split(2, locality_hints=hints)
            actors = [
                ShardConsumer.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=nid, soft=False)).remote()
                for nid in (head_id, worker_id)]
            out = ray_tpu.get(
                [a.consume.remote(its[i]) for i, a in enumerate(actors)],
                timeout=180)
            for a in actors:
                ray_tpu.kill(a)
            rows = sum(r for r, _ in out)
            xnode = sum(s["bytes_cross_node"] for _, s in out)
            # splitter counters ride the terminal next_bundle reply into
            # each consumer's ingest stats (coordinator-global totals) —
            # a post-drain split_stats RPC would race the coordinator's
            # self-retirement timer, the old suite-load flake
            split = out[0][1]
            return rows, xnode, split

        rows, xnode_loc, split = run([head_id, worker_id])
        assert rows == 1024
        total = split["locality_hits"] + split["locality_misses"]
        assert total >= 16
        assert split["locality_hits"] > total / 2, (
            f"locality routing below majority: {split}")

        rows, xnode_base, _ = run(None)
        assert rows == 1024
        assert xnode_base > 0, (
            "locality-free baseline pulled nothing cross-node — "
            "the comparison is vacuous")
        assert xnode_loc < xnode_base, (
            f"locality hints did not reduce cross-node bytes "
            f"({xnode_loc} vs {xnode_base})")
    finally:
        cluster.shutdown()


# -- chaos: node death mid-lookahead ------------------------------------------


@pytest.mark.slow
def test_node_death_mid_lookahead_recovers_via_lineage(no_cluster):
    """The lookahead window holds refs whose only sealed copies live on a
    node that dies mid-iteration; the in-order get inside the prefetcher
    must fall back to lineage reconstruction on a replacement node and
    deliver every block's correct contents."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        cluster.connect()
        side = cluster.add_node(num_cpus=4, resources={"side": 4})
        cluster.wait_for_nodes()

        rows = 256

        @ray_tpu.remote(resources={"side": 1})
        def produce(i):
            return batch_to_block({
                "id": np.arange(i * rows, (i + 1) * rows),
                "payload": np.ones((rows, 512), np.float64)})

        n_blocks = 8
        refs = [produce.remote(i) for i in range(n_blocks)]
        # completion only — the sole sealed copies stay on the side node
        ready, _ = ray_tpu.wait(refs, num_returns=n_blocks, timeout=120,
                                fetch_local=False)
        assert len(ready) == n_blocks
        bundles = [
            RefBundle([(r, BlockMetadata(num_rows=rows,
                                         size_bytes=rows * 512 * 8))])
            for r in refs]

        ctx = DataContext.get_current()
        saved = (ctx.iterator_lookahead_bytes,
                 ctx.iterator_lookahead_max_blocks)
        # narrow window: only ~2 blocks are pulled ahead, so the node
        # dies while most of the stream is still remote-only
        ctx.iterator_lookahead_bytes = 1
        ctx.iterator_lookahead_max_blocks = 2
        try:
            it = DataIterator(_source_of(bundles))
            got = []
            for k, b in enumerate(it.iter_batches(batch_size=rows,
                                                  prefetch_batches=0)):
                got.extend(b["id"].tolist())
                if k == 0:
                    os.kill(side.proc.pid, signal.SIGKILL)
                    side.proc.wait(timeout=10)
                    # replacement capacity for the lineage re-execution
                    cluster.add_node(num_cpus=4, resources={"side": 4})
            assert got == list(range(n_blocks * rows))
        finally:
            (ctx.iterator_lookahead_bytes,
             ctx.iterator_lookahead_max_blocks) = saved
    finally:
        cluster.shutdown()


# -- overlap smoke bench (CI gate) --------------------------------------------


def _load_ingest_bench():
    spec = importlib.util.spec_from_file_location(
        "ingest_bench", os.path.join(_REPO, "benchmarks", "ingest_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_pipelined_ingest_beats_forced_serial(ray_start):
    """Acceptance criterion: on a synthetic slow source the pipelined
    iterator sustains >= 1.5x the forced-serial throughput, and the stats
    ledger proves the overlap (consumer-blocked strictly below total
    block-fetch time)."""
    bench = _load_ingest_bench()
    result = None
    for attempt in range(3):  # pipelining is timing-sensitive under load
        result = bench.run_compare(blocks=12, rows=256,
                                   block_delay_s=0.04, step_delay_s=0.04)
        if result["speedup"] >= 1.5:
            break
    assert result["speedup"] >= 1.5, result
    pipe = result["pipelined_ingest"]
    assert pipe["consumer_blocked_s"] < pipe["block_fetch_total_s"], (
        f"no overlap: consumer blocked {pipe['consumer_blocked_s']:.3f}s "
        f"vs fetch total {pipe['block_fetch_total_s']:.3f}s")
    # the serial baseline shows NO overlap (blocked >= source wait), so
    # the comparison above is meaningful
    serial = result["serial_ingest"]
    assert serial["consumer_blocked_s"] >= serial["source_wait_s"] * 0.9
