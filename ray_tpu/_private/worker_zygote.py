"""Worker zygote: fork-based fast worker spawning.

TPU-native equivalent of the reference WorkerPool's prestart capability
(``src/ray/raylet/worker_pool.h`` — PrestartWorkers / PopWorker hide
process-start latency).  The reference prestarts whole idle python
processes; here ONE zygote process pays the interpreter + heavy-import
cost (jax alone is most of it), then every worker is an ``os.fork()``
away — milliseconds instead of seconds, which is the difference between
1,000 actors in minutes vs an hour (round-3 envelope: 2.4 s/worker,
58 min for 1k actors).

Fork safety: the zygote imports modules but never initializes a jax
backend, starts an event loop, or spawns threads — children initialize
everything post-fork.  Children call ``os.setsid()`` (own session, like
the Popen path's ``start_new_session``) and are reaped by the zygote's
accept loop (they are the zygote's children, not the raylet's; the
raylet probes liveness by pid as it already does for re-adopted
workers).

Protocol (length-prefixed JSON over the zygote's unix socket):
  request:  {"env": {...}, "log_path": "..."}  -> fork a worker
  reply:    {"pid": <child pid>}
A connection error or malformed request is answered with best effort and
never kills the zygote; the raylet falls back to the Popen spawn path if
the zygote is unavailable.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import struct
import sys


def _preload() -> None:
    """Import the heavy modules once, pre-fork.  Anything imported here
    is shared COW by every worker.  Backend-initializing calls (e.g.
    ``jax.devices()``) are deliberately absent: they create threads and
    claim accelerators, both fork-hostile."""
    import ray_tpu  # noqa: F401
    import ray_tpu._private.worker  # noqa: F401
    import ray_tpu._private.worker_proc  # noqa: F401

    try:
        import jax  # noqa: F401  (the ~1s+ import is the whole point)
        import jax.numpy  # noqa: F401
    except Exception:  # noqa: BLE001 - jax-less environments still work
        pass
    try:
        import numpy  # noqa: F401
    except Exception:  # noqa: BLE001
        pass


def _recv_msg(conn: socket.socket) -> dict:
    hdr = b""
    while len(hdr) < 4:
        chunk = conn.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("zygote request truncated")
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    if n > 1 << 20:
        raise ValueError(f"zygote request too large: {n}")
    data = b""
    while len(data) < n:
        chunk = conn.recv(n - len(data))
        if not chunk:
            raise ConnectionError("zygote request truncated")
        data += chunk
    return json.loads(data)


def _send_msg(conn: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    conn.sendall(struct.pack("<I", len(data)) + data)


def _reap() -> None:
    try:
        while True:
            pid, _ = os.waitpid(-1, os.WNOHANG)
            if pid == 0:
                break
    except ChildProcessError:
        pass


def proc_starttime(pid: int):
    """Kernel start time (clock ticks since boot) from /proc/<pid>/stat —
    a (pid, starttime) pair uniquely identifies a process incarnation, so
    liveness probes and kills can't hit a recycled pid.  None if gone."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read()
        # field 2 (comm) may contain spaces/parens; fields after the LAST
        # ')' are well-formed — starttime is the 20th of those
        return int(data.rsplit(b")", 1)[1].split()[19])
    except (OSError, IndexError, ValueError):
        return None


def _spawn(req: dict) -> int:
    env = req.get("env", {})
    log_path = req.get("log_path")
    pid = os.fork()
    if pid != 0:
        return pid
    # ---- child: becomes a worker process ----
    try:
        os.setsid()
        if log_path:
            fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                         0o644)
            os.dup2(fd, 1)
            os.dup2(fd, 2)
            if fd > 2:
                os.close(fd)
        os.environ.update({str(k): str(v) for k, v in env.items()})
        # default signal dispositions (the zygote ignores SIGINT)
        signal.signal(signal.SIGINT, signal.SIG_DFL)
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        from ray_tpu._private import worker_proc

        worker_proc.main()
    except BaseException:  # noqa: BLE001 - never return into the accept loop
        import traceback

        traceback.print_exc()
    finally:
        os._exit(0)
    return 0  # unreachable


def main() -> None:
    sock_path = os.environ["RAY_TPU_ZYGOTE_SOCK"]
    _preload()
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    srv.bind(sock_path + ".tmp")
    srv.listen(64)
    # atomic publish: the raylet treats the socket's existence as "ready"
    os.rename(sock_path + ".tmp", sock_path)
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    srv.settimeout(1.0)
    while True:
        _reap()
        try:
            conn, _ = srv.accept()
        except socket.timeout:
            continue
        except OSError:
            break
        try:
            req = _recv_msg(conn)
            if req.get("cmd") == "stop":
                _send_msg(conn, {"ok": True})
                break
            pid = _spawn(req)
            _send_msg(conn, {"pid": pid,
                             "starttime": proc_starttime(pid)})
        except Exception as e:  # noqa: BLE001 - one bad request, not fatal
            try:
                _send_msg(conn, {"error": str(e)})
            except Exception:  # noqa: BLE001
                pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
    try:
        srv.close()
        os.unlink(sock_path)
    except OSError:
        pass


if __name__ == "__main__":
    main()
