"""Actor API: ActorClass / ActorHandle / ActorMethod.

Equivalent of the reference's ``python/ray/actor.py``
(``ActorClass._remote`` at ``actor.py:324``, ``ActorMethod._remote`` at
``actor.py:909``).  Creation registers the actor with the GCS, which leases a
dedicated worker and pushes the creation task (reference
``gcs_actor_manager.cc:396`` / ``gcs_actor_scheduler.h:115``); method calls
push directly to the actor's worker with per-caller sequence numbers.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, Optional

from ray_tpu._private import api_utils, rpc, serialization
from ray_tpu._private.ids import ActorID
from ray_tpu._private.task_spec import FunctionDescriptor, TaskSpec, TaskType
from ray_tpu.exceptions import ActorDiedError
from ray_tpu.remote_function import _validated_runtime_env


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 options: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._method_name = method_name
        self._options = options or {}

    def options(self, **opts) -> "ActorMethod":
        merged = dict(self._options)
        merged.update(opts)
        return ActorMethod(self._handle, self._method_name, merged)

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._method_name, args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        """Build a DAG node for this method call (reference
        ``python/ray/dag/``; compiled via ``experimental_compile``)."""
        from ray_tpu.dag import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs,
                               options=self._options)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name!r} cannot be called directly; "
            f"use .remote()."
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str, is_async: bool,
                 max_concurrency: int, method_names: tuple,
                 method_options: Optional[Dict[str, Dict[str, Any]]] = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._is_async = is_async
        self._max_concurrency = max_concurrency
        self._method_names = method_names
        self._method_options = method_options or {}

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        if self._method_names and name not in self._method_names:
            raise AttributeError(
                f"Actor class {self._class_name!r} has no method {name!r}")
        return ActorMethod(self, name, dict(self._method_options.get(name, {})))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._class_name, self._is_async,
             self._max_concurrency, self._method_names, self._method_options),
        )

    @property
    def _ray_actor_id(self) -> ActorID:
        return self._actor_id

    def _invoke(self, method_name: str, args, kwargs, options: Dict[str, Any]):
        from ray_tpu._private import tracing
        from ray_tpu._private.worker import get_global_worker

        worker = get_global_worker()
        task_args, kw_keys, nested_refs = api_utils.build_args(
            worker, args, kwargs)
        seq = worker._actor_seq_out = getattr(worker, "_actor_seq_out", {})
        seq_no = seq.get(self._actor_id, 0)
        seq[self._actor_id] = seq_no + 1
        spec = TaskSpec(
            task_id=api_utils.next_task_id(worker),
            job_id=worker.job_id,
            task_type=TaskType.ACTOR_TASK,
            function=FunctionDescriptor(
                module="", qualname=self._class_name, payload=b"",
                method_name=method_name,
            ),
            args=task_args,
            kwargs_keys=kw_keys,
            num_returns=api_utils.coerce_num_returns(
                options.get("num_returns", 1)),
            resources={},
            owner_addr=worker.serve_addr,
            parent_task_id=worker.current_ctx().task_id,
            actor_id=self._actor_id,
            actor_seq_no=seq_no,
            max_concurrency=self._max_concurrency,
            is_async_actor=self._is_async,
            concurrency_group=options.get("concurrency_group", ""),
            trace_ctx=tracing.mint_task_context(
                f"{self._class_name}.{method_name}"),
        )
        refs = worker.submit_actor_task(spec, nested_arg_refs=nested_refs)
        if spec.num_returns == 1:
            return refs[0]
        return refs

    @property
    def __ray_terminate__(self) -> ActorMethod:
        """Graceful in-band termination (parity: ray ActorHandle.__ray_terminate__)."""
        return ActorMethod(self, "__ray_terminate__")

    @property
    def _remote_call(self) -> ActorMethod:
        """Generic in-actor execution: ``h._remote_call.remote(fn, *args)``
        runs ``fn(actor_instance, *args)`` in the actor's process (parity:
        ray's ``__ray_call__``)."""
        return ActorMethod(self, "__rtpu_call__", {})


class ActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = api_utils.validate_options(dict(options or {}), for_actor=True)
        self._payload = serialization.dumps(cls)
        self.__name__ = cls.__name__
        self.__qualname__ = cls.__qualname__

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__!r} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()."
        )

    def options(self, **options) -> "ActorClass":
        merged = dict(self._options)
        merged.update(options)
        ac = ActorClass.__new__(ActorClass)
        ac._cls = self._cls
        ac._options = api_utils.validate_options(merged, for_actor=True)
        ac._payload = self._payload
        ac.__name__ = self._cls.__name__
        ac.__qualname__ = self._cls.__qualname__
        return ac

    def _is_async_class(self) -> bool:
        return any(
            asyncio_iscoroutinefunction(m)
            for _n, m in inspect.getmembers(self._cls, predicate=inspect.isfunction)
        )

    def _method_names(self) -> tuple:
        names = [
            n for n, _m in inspect.getmembers(
                self._cls, predicate=lambda m: inspect.isfunction(m) or inspect.ismethod(m))
            if not n.startswith("__")
        ]
        return tuple(names)

    def _packaged_runtime_env(self, worker):
        """Env snapshot at first creation (see RemoteFunction twin)."""
        from ray_tpu.remote_function import _UNSET

        cached = getattr(self, "_runtime_env_snapshot", _UNSET)
        if cached is _UNSET:
            cached = _validated_runtime_env(self._options, worker)
            self._runtime_env_snapshot = cached
        return cached

    def _method_options(self) -> Dict[str, Dict[str, Any]]:
        """Collect per-method defaults set via @ray_tpu.method(...)."""
        out: Dict[str, Dict[str, Any]] = {}
        for n, m in inspect.getmembers(
                self._cls, predicate=lambda m: inspect.isfunction(m) or inspect.ismethod(m)):
            opts = getattr(m, "__ray_tpu_method_options__", None)
            if opts:
                out[n] = dict(opts)
        return out

    def remote(self, *args, **kwargs):
        from ray_tpu._private import tracing
        from ray_tpu._private.config import config
        from ray_tpu._private.worker import get_global_worker

        worker = get_global_worker()
        opts = self._options
        name = opts.get("name") or ""
        namespace = opts.get("namespace") or worker.namespace

        if opts.get("get_if_exists") and name:
            existing = get_actor_or_none(name, namespace)
            if existing is not None:
                return existing

        ctx = worker.current_ctx()
        ctx.submit_index += 1
        actor_id = ActorID.of(worker.job_id, ctx.task_id, ctx.submit_index)
        task_args, kw_keys, nested_refs = api_utils.build_args(
            worker, args, kwargs)
        is_async = self._is_async_class()
        max_concurrency = opts.get("max_concurrency") or (1000 if is_async else 1)
        groups = opts.get("concurrency_groups")
        if groups is not None:
            if not isinstance(groups, dict) or not groups or not all(
                    isinstance(g, str) and g
                    and isinstance(lim, int) and lim >= 1
                    for g, lim in groups.items()):
                raise ValueError(
                    "concurrency_groups must be a non-empty "
                    "{name: max_concurrency >= 1} dict")
            if "default" in groups:
                # the default group's cap IS max_concurrency (reference:
                # unannotated methods run in the default group)
                raise ValueError(
                    "'default' is implicit: set max_concurrency for "
                    "methods without a concurrency_group")
            groups = dict(groups)
        spec = TaskSpec(
            task_id=api_utils.next_task_id(worker),
            job_id=worker.job_id,
            task_type=TaskType.ACTOR_CREATION_TASK,
            function=FunctionDescriptor(
                module=getattr(self._cls, "__module__", "") or "",
                qualname=self._cls.__qualname__,
                payload=self._payload,
            ),
            args=task_args,
            kwargs_keys=kw_keys,
            num_returns=1,
            resources=api_utils.build_resources(opts, default_num_cpus=0),
            owner_addr=worker.serve_addr,
            parent_task_id=ctx.task_id,
            scheduling_strategy=api_utils.resolve_strategy(
                opts.get("scheduling_strategy"), worker),
            priority=int(opts.get("priority", 0) or 0),
            actor_id=actor_id,
            max_restarts=opts.get("max_restarts", config.actor_max_restarts_default),
            max_concurrency=max_concurrency,
            concurrency_groups=groups,
            # only the class-reflection results ride the meta dict —
            # is_async/max_concurrency already live on the spec itself
            # (one source of truth; the GCS composes the full handle meta)
            actor_handle_meta={
                "method_names": (method_names := self._method_names()),
                "method_options": (method_options := self._method_options()),
            },
            runtime_env=self._packaged_runtime_env(worker),
            is_async_actor=is_async,
            actor_name=name,
            namespace=namespace,
            trace_ctx=tracing.mint_task_context(
                f"{self._cls.__qualname__}.__init__"),
        )
        worker.run_coro(
            # deduped verb: the _mid makes a transport retry of a lost
            # reply replay the registration instead of double-scheduling
            worker.gcs.call("create_actor",
                            spec_bytes=serialization.dumps(spec),
                            _mid=rpc.mint_mid())
        )
        creation_refs = ([a.payload for a in task_args if a.is_ref]
                         + list(nested_refs))
        worker.hold_actor_creation_refs(
            actor_id, creation_refs, until_dead=spec.max_restarts != 0)
        return ActorHandle(actor_id, self._cls.__qualname__, is_async,
                           max_concurrency, method_names, method_options)


def asyncio_iscoroutinefunction(fn) -> bool:
    import asyncio

    return asyncio.iscoroutinefunction(fn)


def get_actor_or_none(name: str, namespace: Optional[str] = None) -> Optional[ActorHandle]:
    from ray_tpu._private.worker import get_global_worker

    worker = get_global_worker()
    if namespace is None:
        namespace = worker.namespace
    actor_id_bytes = worker.run_coro(
        worker.gcs.call("get_named_actor", name=name, namespace=namespace)
    )
    if actor_id_bytes is None:
        return None
    info = worker.run_coro(
        worker.gcs.call("get_actor_info", actor_id=actor_id_bytes)
    )
    # reconstruct the FULL handle from creation-time metadata: method
    # names/options (e.g. @method(concurrency_group=...) defaults) and
    # the async/max_concurrency flags all survive a by-name lookup
    meta = info.get("handle_meta") or {}
    return ActorHandle(
        ActorID(actor_id_bytes), info.get("class_name", "Actor"),
        bool(meta.get("is_async", False)),
        int(meta.get("max_concurrency", 1)),
        tuple(meta.get("method_names", ())),
        dict(meta.get("method_options") or {}))


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    handle = get_actor_or_none(name, namespace)
    if handle is None:
        raise ValueError(f"Failed to look up actor with name {name!r}")
    return handle
