"""ray_tpu.serve: online model serving (reference: ``python/ray/serve/``).

``serve.run(app)`` deploys a bound deployment graph behind the singleton
controller; ``DeploymentHandle.remote()`` routes via pow-2 choices; an
optional HTTP proxy exposes route prefixes (``serve.start(http_options=...)``).
"""

from __future__ import annotations

import dataclasses
import uuid
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve import context
from ray_tpu.serve.context import RequestContext, request_scope
from ray_tpu.serve.deployment import (
    Application,
    AutoscalingConfig,
    Deployment,
    DeploymentConfig,
    deployment,
)
from ray_tpu.serve.context import ReplicaContext, get_replica_context
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.replica import batch
from ray_tpu.serve.router import (
    DeploymentHandle,
    DeploymentResponse,
    TwoStageHandle,
)

__all__ = [
    "Application", "AutoscalingConfig", "Deployment", "DeploymentConfig",
    "DeploymentHandle", "DeploymentResponse", "ReplicaContext",
    "RequestContext", "TwoStageHandle", "batch",
    "context", "delete", "deployment",
    "get_app_handle", "get_deployment_handle", "get_multiplexed_model_id",
    "get_replica_context",
    "grpc_proxy_port", "multiplexed", "request_scope", "run",
    "shutdown", "start",
    "status",
]

_proxy = None
_grpc_proxy = None


def start(http_options: Optional[Dict[str, Any]] = None,
          grpc_options: Optional[Dict[str, Any]] = None):
    """Start serve (controller + optional HTTP and/or gRPC proxies).

    Reference runs both proxy flavors per node (``proxy.py:750`` HTTP,
    ``:530`` gRPC); here each is opt-in via its options dict.
    """
    from ray_tpu.serve.controller import get_controller

    get_controller()
    global _proxy, _grpc_proxy
    if http_options and _proxy is None:
        from ray_tpu.serve.proxy import ProxyActor

        host = http_options.get("host", "127.0.0.1")
        port = http_options.get("port", 8000)
        _proxy = ProxyActor.remote(
            host, port, http_options.get("request_timeout_s", 120.0),
            http_options.get("max_concurrent_requests", 256))
        ray_tpu.get(_proxy.ready.remote(), timeout=60)
    if grpc_options and _grpc_proxy is None:
        from ray_tpu.serve.grpc_proxy import GrpcProxyActor

        host = grpc_options.get("host", "127.0.0.1")
        port = grpc_options.get("port", 9000)
        _grpc_proxy = GrpcProxyActor.remote(host, port)
        ray_tpu.get(_grpc_proxy.ready.remote(), timeout=60)
    return _proxy


def grpc_proxy_port() -> int:
    """Bound port of the gRPC proxy (resolves port=0 ephemeral binds)."""
    if _grpc_proxy is None:
        raise RuntimeError("gRPC proxy not started; pass grpc_options to "
                           "serve.start()")
    return ray_tpu.get(_grpc_proxy.ready.remote(), timeout=30)


def run(target: Application | Deployment, *, name: str = "default",
        route_prefix: Optional[str] = "/", _blocking: bool = False
        ) -> DeploymentHandle:
    """Deploy an application graph; returns the ingress handle
    (reference ``serve.run`` at ``python/ray/serve/api.py:660``)."""
    from ray_tpu._private import serialization
    from ray_tpu.serve.controller import get_controller

    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError("serve.run expects a Deployment or bound Application")

    controller = get_controller()
    apps = target._collect()  # dependencies first
    handles: Dict[int, DeploymentHandle] = {}
    for app in apps:
        dep = app.deployment
        # replace Application args with handles to the deployed dependency
        init_args = tuple(handles[id(a)] if isinstance(a, Application) else a
                          for a in app.args)
        init_kwargs = {k: handles[id(v)] if isinstance(v, Application) else v
                       for k, v in app.kwargs.items()}
        is_ingress = app is apps[-1]
        cfg = dep.config
        config_dict = {
            "num_replicas": cfg.num_replicas,
            "max_ongoing_requests": cfg.max_ongoing_requests,
            "max_queued_requests": cfg.max_queued_requests,
            "autoscaling_config": (
                None if cfg.autoscaling_config is None
                else dataclasses.asdict(cfg.autoscaling_config)),
            "user_config": cfg.user_config,
            "ray_actor_options": cfg.ray_actor_options,
        }
        prefix = (dep.route_prefix or route_prefix) if is_ingress else None
        ray_tpu.get(controller.deploy.remote(
            dep.name, serialization.dumps(dep._target), init_args,
            init_kwargs, config_dict, prefix,
            name if is_ingress else None), timeout=120)
        handles[id(app)] = DeploymentHandle(dep.name)
    return handles[id(apps[-1])]


def get_deployment_handle(deployment_name: str, app_name: str = "default"
                          ) -> DeploymentHandle:
    return DeploymentHandle(deployment_name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    from ray_tpu.serve.controller import get_controller

    ingress = ray_tpu.get(get_controller().get_app_ingress.remote(name),
                          timeout=30)
    if ingress is None:
        raise RuntimeError(f"no application named {name!r}")
    return DeploymentHandle(ingress)


def status() -> Dict[str, Any]:
    from ray_tpu.serve.controller import get_controller

    return ray_tpu.get(get_controller().list_deployments.remote(),
                       timeout=30)


def delete(deployment_name: str):
    from ray_tpu.serve.controller import get_controller

    ray_tpu.get(get_controller().delete_deployment.remote(deployment_name),
                timeout=60)


def shutdown():
    global _proxy, _grpc_proxy
    from ray_tpu.actor import get_actor_or_none
    from ray_tpu.serve.controller import CONTROLLER_NAME

    controller = get_actor_or_none(CONTROLLER_NAME)
    if controller is not None:
        try:
            ray_tpu.get(controller.shutdown.remote(), timeout=60)
            ray_tpu.kill(controller)
        except Exception:
            pass
    for proxy in (_proxy, _grpc_proxy):
        if proxy is not None:
            try:
                ray_tpu.kill(proxy)
            except Exception:
                pass
    _proxy = None
    _grpc_proxy = None
    # drop cached per-deployment routers: they hold handles to the dead
    # controller/replicas and would poison the next serve session (stop
    # settles each router's completion-watcher thread first)
    with DeploymentHandle._routers_lock:
        for router in DeploymentHandle._routers.values():
            router.stop()
        DeploymentHandle._routers.clear()
