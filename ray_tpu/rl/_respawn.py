"""Shared respawn-or-drop accounting for actor groups.

Both rollout planes (``EnvRunnerGroup`` for gym env runners,
``rlhf.RolloutGroup`` for generation actors) settle dead members the
same way: respawn while a bounded budget lasts, past it drop the member
with a logged count and keep operating at reduced strength.  One
implementation so a fix to the pattern reaches both planes (the same
reasoning as ``_private/concurrency.py`` for the liveness loops).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, List

logger = logging.getLogger(__name__)


class RespawnBudget:
    """Tracks respawns-remaining and dropped-member counts for a group.

    ``replace(survivors, n_dead, spawn)`` appends one ``spawn()`` result
    per dead slot while the budget lasts; past it the member is dropped
    (counted + logged) and the group shrinks."""

    def __init__(self, budget: int, what: str = "runner",
                 respawn_note: str = ""):
        self.respawns_left = budget
        self.dropped = 0
        self.what = what
        self.respawn_note = respawn_note

    def replace(self, survivors: List[Any], n_dead: int,
                spawn: Callable[[], Any]) -> List[Any]:
        for _ in range(n_dead):
            if self.respawns_left > 0:
                self.respawns_left -= 1
                survivors.append(spawn())
                logger.warning(
                    "respawned dead %s (%d respawns left)%s",
                    self.what, self.respawns_left, self.respawn_note)
            else:
                self.dropped += 1
                logger.error(
                    "respawn budget exhausted — dropping the %s "
                    "(%d dropped so far)", self.what, self.dropped)
        return survivors
