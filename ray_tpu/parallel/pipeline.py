"""In-graph pipeline parallelism over the ``pp`` mesh axis.

The reference delegates pipeline parallelism to vLLM
(``python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_models.py:127``
``pipeline_parallel_size`` → placement-group bundles) and provides only the
channel substrate for inter-actor pipelining
(``python/ray/dag/dag_node_operation.py``).  Here PP is a first-class mesh
axis like dp/fsdp/tp/sp, implemented the TPU way:

- layer-stacked params are sharded over ``pp`` (each stage holds
  ``L / pp_size`` contiguous layers);
- the microbatch schedule is a ``lax.scan`` of compute+rotate ticks in
  PLAIN GSPMD: per-stage activation buffers ride a leading stage dim
  sharded over ``pp``, the per-tick stage compute is a ``vmap`` over
  that dim (each pp shard runs its own stage), and the inter-stage hop
  is ``jnp.roll`` on the sharded dim — which XLA lowers to exactly the
  ``collective-permute`` ring a manual ``ppermute`` would issue.  No
  ``shard_map`` at all: earlier revisions ran the schedule in a
  partial-manual ``shard_map`` (``pp`` manual, the rest auto), but
  mixing manual and auto subgroups is unreliable across jax/XLA
  versions — 0.4.x rejects the region's ``axis_index`` with
  "UNIMPLEMENTED: PartitionId" at execution and hard-aborts
  (``IsManualSubgroup`` check) on scalar bridges between the manual
  and auto halves.  Sharding annotations alone express the same
  program portably, and dp/fsdp/tp stay auto-partitioned inside each
  stage for free;
- reverse-mode AD transposes the roll (a roll the other way), so the
  backward pass is the mirrored pipeline schedule for free.  With
  per-layer remat the live state per stage is one microbatch activation
  + the output buffer, which is the 1F1B memory profile (activations
  for at most the in-flight microbatches, not all of them).

Bubble fraction is ``(S-1) / (M + S - 1)`` for S stages and M microbatches;
raise ``num_microbatches`` to amortize.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def pp_size(mesh: Optional[Mesh], axis: str = "pp") -> int:
    """Number of pipeline stages in the mesh (1 when no pp axis)."""
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return mesh.shape[axis]


def pipeline_apply(
    layer_fn: Callable[[jnp.ndarray, Any], jnp.ndarray],
    stacked_params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    num_microbatches: Optional[int] = None,
    axis: str = "pp",
) -> jnp.ndarray:
    """Run ``x`` through L stacked layers pipelined over the ``axis`` stages.

    ``layer_fn(x, layer_params) -> x`` is the per-layer body (already
    remat-wrapped by the caller if desired).  ``stacked_params`` is a pytree
    whose leaves have a leading layer dimension L, sharded over ``axis``
    (each stage owns a contiguous block of L/S layers).  ``x`` is
    ``[batch, ...]`` and must be divisible into ``num_microbatches``.

    Returns the activations after all L layers, same shape as ``x``.
    """
    S = pp_size(mesh, axis)
    if S == 1:
        def body(carry, lp):
            return layer_fn(carry, lp), None
        out, _ = jax.lax.scan(body, x, stacked_params)
        return out

    M = num_microbatches or S
    b = x.shape[0]
    if b % M != 0:
        raise ValueError(f"batch {b} not divisible by {M} microbatches")
    n_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_layers % S != 0:
        raise ValueError(f"{n_layers} layers not divisible by {S} stages")

    def _pp_constrain(v):
        # leading stage dim over `axis`, everything else auto (GSPMD
        # keeps partitioning the dp/fsdp/tp dims inside each stage)
        return jax.lax.with_sharding_constraint(
            v, jax.sharding.NamedSharding(mesh, P(axis)))

    micro = x.reshape((M, b // M) + x.shape[1:])
    # [L, ...] -> [S, L/S, ...]: stage s owns the contiguous layer block
    # [s*L/S, (s+1)*L/S), stage dim sharded over `axis`.
    staged_params = jax.tree.map(
        lambda p: _pp_constrain(
            p.reshape((S, n_layers // S) + p.shape[1:])),
        stacked_params)

    def stage_body(state, layers_shard):
        def body(carry, lp):
            return layer_fn(carry, lp), None
        out, _ = jax.lax.scan(body, state, layers_shard)
        return out

    # buf[i] = the activation currently sitting at stage i.
    buf = jnp.zeros((S,) + micro.shape[1:], micro.dtype)
    outputs = jnp.zeros_like(micro)

    def tick(carry, t):
        buf, outputs = carry
        # Stage 0 ingests microbatch t (clamped; masked off past M).
        inp = jax.lax.dynamic_index_in_dim(
            micro, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        buf = jax.lax.dynamic_update_index_in_dim(buf, inp, 0, axis=0)
        # One tick of every stage: vmap over the sharded stage dim puts
        # each stage's layer scan on its own pp shard.
        buf = _pp_constrain(buf)
        buf = jax.vmap(stage_body)(buf, staged_params)
        buf = _pp_constrain(buf)
        # Last stage emits microbatch t-(S-1) once the fill completes.
        out_idx = t - (S - 1)
        emitted = jax.lax.dynamic_index_in_dim(
            buf, S - 1, axis=0, keepdims=False)
        updated = jax.lax.dynamic_update_index_in_dim(
            outputs, emitted, jnp.maximum(out_idx, 0), axis=0)
        outputs = jnp.where(out_idx >= 0, updated, outputs)
        # Rotate activations one stage down the ring (roll on the
        # pp-sharded dim == XLA collective-permute).
        buf = _pp_constrain(jnp.roll(buf, 1, axis=0))
        return (buf, outputs), None

    (buf, outputs), _ = jax.lax.scan(
        tick, (buf, outputs), jnp.arange(M + S - 1))
    return outputs.reshape(x.shape)


def pipeline_microbatches(cfg_microbatches: Optional[int], mesh: Mesh,
                          axis: str = "pp") -> int:
    """Default microbatch count: 2*stages (25%→~14% bubble vs M=S)."""
    return cfg_microbatches or 2 * pp_size(mesh, axis)


def reject_pp(mesh: Optional[Mesh], family: str, rules=None):
    """Guard for model families without a pipeline apply path.

    Raises on pp>1 meshes, and — only when the caller supplied no rule
    table of their own — replicates stacked layers over pp instead of
    stage-sharding them (a stage-sharded stack under a plain lax.scan
    would all-gather every layer, every step).  Returns the rule table to
    use.
    """
    if pp_size(mesh) > 1:
        raise ValueError(
            f"{family} has no pipeline (pp) apply path; use dp/fsdp/tp/sp "
            "axes (pp is llama-only for now)"
        )
    if rules is None:
        from ray_tpu.parallel.sharding import DEFAULT_RULES

        return {**DEFAULT_RULES, "layers": None}
    return rules
