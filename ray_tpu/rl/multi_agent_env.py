"""Multi-agent environments: dict-keyed agents over batched jax dynamics.

Reference: ``rllib/env/multi_agent_env.py:30`` (``MultiAgentEnv`` — obs /
rewards / dones keyed by agent id, 808 LoC of gym-subclass machinery) and
the policy-mapping contract of ``rllib``'s multi-agent episodes.

TPU-first difference: a ``JaxMultiAgentEnv`` is a pure simultaneous-move
function over BATCHED per-agent arrays, so the whole multi-agent rollout
(every agent's action sampling + the joint env step) compiles into one
``lax.scan`` on device.  Episode boundaries are shared across agents
(simultaneous termination — the common case for team/zero-sum games and
the form that keeps the scan shape static); per-agent "agent done"
masking composes on top as an env-level reward mask if needed.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ray_tpu.rl.env import EnvSpec


class JaxMultiAgentEnv:
    """ABC: batched simultaneous-move multi-agent env on device.

    ``agent_ids`` is the ordered tuple of agent names; ``specs`` maps each
    to its (obs_dim, num_actions, max_episode_steps).
    """

    agent_ids: Tuple[str, ...]
    specs: Dict[str, EnvSpec]

    def reset(self, key, batch: int):
        """-> (state, obs: {agent_id: [B, obs_dim]})."""
        raise NotImplementedError

    def step(self, state, actions: Dict[str, "np.ndarray"], key):
        """-> (next_state, obs, rewards, terminated, truncated, final_obs).

        ``obs`` / ``rewards`` / ``final_obs`` are dicts keyed by agent id;
        ``terminated`` / ``truncated`` are SHARED ``[B]`` masks (episodes
        end jointly).  ``obs`` is post-auto-reset; ``final_obs`` is the
        pre-reset observation used for time-limit bootstrapping.
        """
        raise NotImplementedError


class PursuitTagEnv(JaxMultiAgentEnv):
    """Two-agent zero-sum tag on a bounded 1-D line.

    The *pursuer* is rewarded for closing the distance to the *evader*
    (+10 bonus on a catch, which terminates the episode); the evader gets
    the exact negative.  Optimal play is OPPOSITE per role — the test that
    independent policies actually diverge.  Actions: 0 left / 1 stay /
    2 right; obs per agent: [own_pos, other_pos, signed_diff, t/T].
    """

    agent_ids = ("pursuer", "evader")
    _spec = EnvSpec(obs_dim=4, num_actions=3, max_episode_steps=128)
    specs = {"pursuer": _spec, "evader": _spec}

    move = 0.08
    evader_move = 0.05  # slower evader: catches are possible
    catch_radius = 0.1
    bound = 1.0

    def reset(self, key, batch: int):
        import jax

        pos = jax.random.uniform(key, (batch, 2), minval=-0.8, maxval=0.8)
        steps = jax.numpy.zeros((batch,), dtype=jax.numpy.int32)
        state = (pos, steps)
        return state, self._obs(state)

    def _obs(self, state):
        import jax.numpy as jnp

        pos, steps = state
        t = steps.astype(jnp.float32) / self._spec.max_episode_steps
        p, e = pos[:, 0], pos[:, 1]
        return {
            "pursuer": jnp.stack([p, e, e - p, t], axis=1),
            "evader": jnp.stack([e, p, p - e, t], axis=1),
        }

    def step(self, state, actions, key):
        import jax
        import jax.numpy as jnp

        pos, steps = state
        d_p = (actions["pursuer"].astype(jnp.float32) - 1.0) * self.move
        d_e = (actions["evader"].astype(jnp.float32) - 1.0) * self.evader_move
        p = jnp.clip(pos[:, 0] + d_p, -self.bound, self.bound)
        e = jnp.clip(pos[:, 1] + d_e, -self.bound, self.bound)
        dist = jnp.abs(p - e)
        caught = dist < self.catch_radius
        steps = steps + 1
        terminated = caught
        truncated = (steps >= self._spec.max_episode_steps) & ~terminated
        done = terminated | truncated
        # zero-sum: pursuer earns the negative distance (+catch bonus)
        r_p = -dist + jnp.where(caught, 10.0, 0.0)
        rewards = {"pursuer": r_p, "evader": -r_p}
        final_state = (jnp.stack([p, e], axis=1), steps)
        final_obs = self._obs(final_state)
        # auto-reset finished envs
        fresh = jax.random.uniform(key, (pos.shape[0], 2),
                                   minval=-0.8, maxval=0.8)
        next_pos = jnp.where(done[:, None], fresh, final_state[0])
        next_steps = jnp.where(done, 0, steps)
        next_state = (next_pos, next_steps)
        return (next_state, self._obs(next_state), rewards, terminated,
                truncated, final_obs)
