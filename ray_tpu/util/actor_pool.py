"""Fixed-pool actor work distribution.

Parity: ``python/ray/util/actor_pool.py:13`` (``ActorPool``: map /
map_unordered / submit / get_next / get_next_unordered / has_next /
has_free / pop_idle / push).  Rebuilt over ``ray_tpu.wait``: a FIFO of
idle actors, a FIFO of not-yet-dispatched submissions (work queued when
every actor is busy dispatches as completions free actors), and a
dispatch-order deque driving the ordered fetch path.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class ActorPool:
    """Operate on a fixed pool of actors::

        pool = ActorPool([Actor.remote(), Actor.remote()])
        out = list(pool.map(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    """

    def __init__(self, actors: List[Any]):
        self._idle: collections.deque = collections.deque(actors)
        self._queued: collections.deque = collections.deque()  # (fn, value)
        self._owner: dict = {}     # in-flight ref -> actor
        self._ordered: collections.deque = collections.deque()  # dispatch order
        self._consumed: set = set()  # refs taken by get_next_unordered

    # -- submission --------------------------------------------------------

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """Schedule ``fn(actor, value)`` on the next free actor; queued
        until one frees if all are busy."""
        self._queued.append((fn, value))
        self._dispatch()

    def _dispatch(self) -> None:
        while self._idle and self._queued:
            fn, value = self._queued.popleft()
            actor = self._idle.popleft()
            ref = fn(actor, value)
            self._owner[ref] = actor
            self._ordered.append(ref)

    def _return_actor(self, ref) -> None:
        self._idle.append(self._owner.pop(ref))
        self._dispatch()

    # -- retrieval ---------------------------------------------------------

    def has_next(self) -> bool:
        return bool(self._owner) or bool(self._queued)

    def get_next(self, timeout: Optional[float] = None,
                 ignore_if_timedout: bool = False) -> Any:
        """Next result in SUBMISSION order (blocks up to ``timeout``)."""
        if not self.has_next():
            raise StopIteration("no pending results")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            while self._ordered and self._ordered[0] in self._consumed:
                self._consumed.discard(self._ordered.popleft())
            if self._ordered:
                ref = self._ordered[0]
                break
            # head-of-line task still queued: absorb a completion so an
            # actor frees and dispatch pulls it in
            if not self._wait_any(deadline):
                if ignore_if_timedout:
                    return None
                raise TimeoutError("get_next timed out")
        t = None if deadline is None else max(0.0, deadline - time.monotonic())
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=t)
        if not ready:
            if ignore_if_timedout:
                return None
            raise TimeoutError("get_next timed out")
        self._ordered.popleft()
        self._return_actor(ref)
        return ray_tpu.get(ref)

    def get_next_unordered(self, timeout: Optional[float] = None,
                           ignore_if_timedout: bool = False) -> Any:
        """Next result in COMPLETION order (blocks up to ``timeout``)."""
        if not self.has_next():
            raise StopIteration("no pending results")
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._owner:  # everything still queued: cannot happen
            if not self._wait_any(deadline):  # unless actors were popped
                if ignore_if_timedout:
                    return None
                raise TimeoutError("get_next_unordered timed out")
        t = None if deadline is None else max(0.0, deadline - time.monotonic())
        ready, _ = ray_tpu.wait(list(self._owner), num_returns=1, timeout=t)
        if not ready:
            if ignore_if_timedout:
                return None
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        self._consumed.add(ref)
        self._return_actor(ref)
        # trim consumed refs off the ordered head NOW: a pure-unordered
        # consumer never calls get_next, and without this every result
        # ref (and its payload, via refcounting) stays pinned for the
        # pool's lifetime
        while self._ordered and self._ordered[0] in self._consumed:
            self._consumed.discard(self._ordered.popleft())
        return ray_tpu.get(ref)

    def _wait_any(self, deadline) -> bool:
        if not self._owner:
            return False
        t = None if deadline is None else max(0.0, deadline - time.monotonic())
        ready, _ = ray_tpu.wait(list(self._owner), num_returns=1, timeout=t)
        return bool(ready)

    # -- bulk --------------------------------------------------------------

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]):
        """Apply over values; yields results in submission order."""
        for v in values:
            self.submit(fn, v)

        def gen():
            while self.has_next():
                yield self.get_next()

        return gen()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]):
        """Apply over values; yields results as they complete."""
        for v in values:
            self.submit(fn, v)

        def gen():
            while self.has_next():
                yield self.get_next_unordered()

        return gen()

    # -- pool management ---------------------------------------------------

    def has_free(self) -> bool:
        """True iff an actor is idle AND nothing is queued."""
        return bool(self._idle) and not self._queued

    def pop_idle(self) -> Optional[Any]:
        """Remove and return an idle actor (None if all are busy)."""
        if not self.has_free():
            return None
        return self._idle.popleft()

    def push(self, actor: Any) -> None:
        """Add an actor to the pool (queued work dispatches onto it)."""
        self._idle.append(actor)
        self._dispatch()
