"""Model multiplexing: many models per replica with LRU retention.

Reference: ``python/ray/serve/api.py:719`` (``@serve.multiplexed``) +
``python/ray/serve/multiplex.py`` (``_ModelMultiplexWrapper``) — the
many-models-per-replica pattern (LoRA-adapter serving): a replica lazily
loads models by id, retains up to ``max_num_models_per_replica`` in an
LRU, and the router prefers replicas that already hold the requested
model.

TPU-native notes: a "model" here is typically a params pytree already
resident in HBM; eviction drops the host reference and XLA frees the
device buffers.  Loading happens inside the replica's request thread —
no extra event loop.
"""

from __future__ import annotations

import collections
import contextvars
import threading
from typing import Any, Callable, List, Optional

# set by ReplicaActor.handle_request around each user-code call
_mux_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """Inside a deployment method: the model id of the current request
    (``handle.options(multiplexed_model_id=...)`` or the
    ``serve_multiplexed_model_id`` HTTP header).  Empty string if unset.
    Reference: ``serve.get_multiplexed_model_id``."""
    return _mux_model_id.get()


class _MultiplexWrapper:
    """Per-replica LRU of loaded models keyed by model id."""

    def __init__(self, fn: Callable, instance: Any, max_models: int):
        self._fn = fn
        self._instance = instance
        self._max = max_models
        self._models: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        # model_id -> Event: single-flight guard so concurrent first
        # requests for one id load ONCE (a double load of an HBM-resident
        # params pytree could transiently hold two full copies)
        self._loading: dict = {}
        self._loads = 0
        self._evictions = 0

    def load(self, model_id: str):
        while True:
            with self._lock:
                if model_id in self._models:
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
                ev = self._loading.get(model_id)
                if ev is None:
                    ev = self._loading[model_id] = threading.Event()
                    break  # this thread loads
            ev.wait()  # another thread is loading this id; re-check
        # load OUTSIDE the lock: a slow model load must not block lookups
        # of already-loaded models from other request threads
        try:
            model = self._fn(self._instance, model_id)
            with self._lock:
                self._models[model_id] = model
                self._loads += 1
                while len(self._models) > self._max:
                    evicted_id, evicted = self._models.popitem(last=False)
                    self._evictions += 1
                    del evicted  # drop the ref; HBM frees with it
                self._models.move_to_end(model_id)
                return self._models[model_id]
        finally:
            with self._lock:
                self._loading.pop(model_id, None)
            ev.set()

    def model_ids(self) -> List[str]:
        with self._lock:
            return list(self._models)

    def stats(self) -> dict:
        with self._lock:
            return {"loaded": list(self._models), "loads": self._loads,
                    "evictions": self._evictions, "max": self._max}


def multiplexed(fn: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """``@serve.multiplexed``: decorate the deployment's model-loader
    method.  Calls are LRU-cached per replica by model id::

        @serve.deployment
        class LoraServer:
            @serve.multiplexed(max_num_models_per_replica=4)
            def get_model(self, model_id: str):
                return load_adapter(model_id)

            def __call__(self, body):
                model = self.get_model(serve.get_multiplexed_model_id())
                ...
    """
    if max_num_models_per_replica < 1:
        raise ValueError("max_num_models_per_replica must be >= 1")

    def wrap(f: Callable):
        attr = f"__serve_mux_{f.__name__}"

        def call(self, model_id: str):
            wrapper = self.__dict__.get(attr)
            if wrapper is None:
                wrapper = self.__dict__.setdefault(
                    attr, _MultiplexWrapper(
                        f, self, max_num_models_per_replica))
            # registry so the replica can report loaded ids to the router
            reg = self.__dict__.setdefault("__serve_mux_wrappers__", [])
            if wrapper not in reg:
                reg.append(wrapper)
            return wrapper.load(model_id)

        call.__name__ = f.__name__
        call._is_serve_multiplexed = True
        return call

    if fn is not None:
        return wrap(fn)
    return wrap


def loaded_model_ids(instance: Any) -> List[str]:
    """All model ids currently loaded across an instance's multiplexed
    loaders (the replica reports these for model-aware routing)."""
    out: List[str] = []
    for wrapper in instance.__dict__.get("__serve_mux_wrappers__", []):
        out.extend(wrapper.model_ids())
    return out
