"""Partition-tolerance tier: the netem matrix, fencing, and at-most-once.

Four layers, mirroring the PR's three planes plus their composition:

1. **Netem unit** — the compact grammar, rule normalization, the
   partition rule builder, legacy-spec folding, and the determinism
   contract (same spec + seed ⇒ byte-identical schedule AND an
   identically-replayed decision stream).
2. **Live RPC legs** — an in-process ``RpcServer``/``RpcClient`` pair
   under drop / delay / dup rules: the req-phase loss surfaces as the
   caller's timeout, a resp-phase loss loses the reply AFTER the
   mutation applied (the hazard ``_mid`` exists for), and a duplicated
   frame re-runs the handler exactly once (the ``_netem_dup`` guard).
3. **At-most-once GCS mutations** — a retry carrying the same ``_mid``
   replays the cached reply; a fresh ``_mid`` re-executes; a FAILED
   apply is never cached (the retry runs for real).
4. **Cluster-epoch fencing** — fence lifecycle on the GCS tables, stale
   heartbeats, fenced mutations raising ``StaleNodeError`` end-to-end
   over RPC, the superseded-incarnation split-brain guard, and the full
   partition → death → heal → fence → rejoin loop on live raylets.

The cluster legs manage their own in-process servers (the test drives
partitions and node death), so this file must NOT use the shared
session cluster.
"""

import asyncio
import json
import os
import tempfile
import time

import pytest

from ray_tpu._private.rpc import (
    Netem,
    RpcClient,
    RpcServer,
    _decision,
    _legacy_rules,
    mint_mid,
    normalize_netem_rule,
    parse_netem,
    partition_rules,
)
from ray_tpu.exceptions import StaleNodeError
from ray_tpu.util import fault_injection as fi


# ---------------------------------------------------------------------------
# netem unit: grammar, builders, determinism
# ---------------------------------------------------------------------------


def test_parse_netem_grammar():
    rules = parse_netem(
        "ab12<>gcs:*:drop:at=2:for=10;"
        "*>*:request_lease:delay=0.25:p=0.3:phase=resp;"
        "n1>n2:heartbeat:dup:n=3")
    assert len(rules) == 4  # <> expands into the two directed rules
    cut_ab, cut_ba = rules[0], rules[1]
    assert (cut_ab["src"], cut_ab["dst"]) == ("ab12", "gcs")
    assert (cut_ba["src"], cut_ba["dst"]) == ("gcs", "ab12")
    for r in (cut_ab, cut_ba):
        assert r["action"] == "drop"
        assert r["start_s"] == 2.0 and r["duration_s"] == 10.0
    delay = rules[2]
    assert delay["action"] == "delay" and delay["delay_s"] == 0.25
    assert delay["prob"] == 0.3 and delay["phase"] == "resp"
    assert delay["verb"] == "request_lease"
    dup = rules[3]
    assert dup["action"] == "dup" and dup["n"] == 3
    # empty segments are skipped, not errors
    assert parse_netem("; ;") == []


def test_netem_grammar_rejects_malformed():
    with pytest.raises(ValueError):
        parse_netem("a>b:drop")  # need src>dst:verb:action
    with pytest.raises(ValueError):
        parse_netem(">b:*:drop")  # empty endpoint
    with pytest.raises(ValueError):
        parse_netem("a>b:*:teleport")  # unknown action
    with pytest.raises(ValueError):
        normalize_netem_rule({"action": "drop", "phase": "both"})


def test_partition_rules_modes():
    # frames x→y are decided at the RECEIVER: a oneway a→b cut is a's
    # requests (req phase at b) plus a's replies to b (resp phase at a)
    oneway = partition_rules("a", "b", mode="oneway", duration_s=5.0)
    assert len(oneway) == 2
    req, resp = oneway
    assert (req["src"], req["dst"], req["phase"]) == ("a", "b", "req")
    assert (resp["src"], resp["dst"], resp["phase"]) == ("b", "a", "resp")
    assert all(r["action"] == "drop" and r["duration_s"] == 5.0
               for r in oneway)
    sym = partition_rules("a", "b", mode="symmetric")
    assert len(sym) == 4
    # symmetric = closed under swapping the link direction
    links = {(r["src"], r["dst"], r["phase"]) for r in sym}
    assert links == {("a", "b", "req"), ("b", "a", "resp"),
                     ("b", "a", "req"), ("a", "b", "resp")}
    with pytest.raises(ValueError):
        partition_rules("a", "b", mode="diagonal")


def test_netem_schedule_and_decision_stream_deterministic():
    """The acceptance contract: same spec + seed ⇒ byte-identical armed
    schedule and an identically-replayed probabilistic decision stream."""
    spec = "cli>srv:echo:drop:p=0.5;*>*:lease:delay=0.1:p=0.25:phase=resp"
    n1, n2 = Netem("srv"), Netem("srv")
    n1.install(parse_netem(spec), seed=1234, epoch=time.time() - 1.0)
    n2.install(parse_netem(spec), seed=1234, epoch=time.time() - 1.0)
    assert (json.dumps(n1.schedule(), sort_keys=True)
            == json.dumps(n2.schedule(), sort_keys=True))
    assert n1._digest == n2._digest
    stream1 = [n1.apply("cli", "srv", "echo", "req") is not None
               for _ in range(64)]
    stream2 = [n2.apply("cli", "srv", "echo", "req") is not None
               for _ in range(64)]
    assert stream1 == stream2
    assert any(stream1) and not all(stream1)  # p=0.5 actually rolls
    # a different seed produces a different digest and a divergent stream
    n3 = Netem("srv")
    n3.install(parse_netem(spec), seed=99, epoch=time.time() - 1.0)
    assert n3._digest != n1._digest
    stream3 = [n3.apply("cli", "srv", "echo", "req") is not None
               for _ in range(64)]
    assert stream3 != stream1
    # and the raw draw itself is a pure function of (digest, index)
    assert _decision(n1._digest, 7) == _decision(n2._digest, 7)


def test_netem_windows_and_budget():
    n = Netem("srv")
    # window not yet open: epoch pushed into the future (the lead_s trick
    # that keeps arming RPCs off the partition they create)
    n.install(parse_netem("a>srv:*:drop:for=5"), seed=0,
              epoch=time.time() + 30.0)
    assert n.apply("a", "srv", "x", "req") is None
    # window expired
    n.install(parse_netem("a>srv:*:drop:for=5"), seed=0,
              epoch=time.time() - 30.0)
    assert n.apply("a", "srv", "x", "req") is None
    # open window, n=2 budget: exactly the first two matching frames hit
    n.install(parse_netem("a>srv:*:drop:n=2"), seed=0)
    hits = [n.apply("a", "srv", "x", "req") is not None for _ in range(4)]
    assert hits == [True, True, False, False]
    # endpoint prefix match + verb glob still gate the rule
    n.install(parse_netem("abcd>srv:lease_*:drop"), seed=0)
    assert n.apply("abcdef0123", "srv", "lease_worker", "req") is not None
    assert n.apply("zz", "srv", "lease_worker", "req") is None
    assert n.apply("abcdef0123", "srv", "heartbeat", "req") is None
    n.clear()
    assert not n.active


def test_legacy_spec_shares_one_budget_across_phases():
    """``method=N:req:resp`` folds into two netem rules sharing a single
    N-failure budget (the reference rpc_chaos semantics)."""
    rules = _legacy_rules("lease_worker=2:1.0:1.0")
    assert len(rules) == 2 and rules[0]["_budget"] is rules[1]["_budget"]
    n = Netem("srv")
    n.install(rules, seed=0)
    assert n.apply("a", "srv", "lease_worker", "req") is not None
    assert n.apply("a", "srv", "lease_worker", "resp") is not None
    # the shared budget is exhausted: BOTH phases go quiet
    assert n.apply("a", "srv", "lease_worker", "req") is None
    assert n.apply("a", "srv", "lease_worker", "resp") is None


# ---------------------------------------------------------------------------
# live RPC legs: an in-process server/client pair under netem
# ---------------------------------------------------------------------------


def _rpc_pair(test_body):
    """Run ``test_body(server, client, calls)`` against an in-process
    unix-socket pair; ``calls`` counts handler executions."""
    async def main():
        server = RpcServer("test-server", node_id="srv")
        calls = {"n": 0}

        async def echo(x=0):
            calls["n"] += 1
            return {"x": x, "n": calls["n"]}

        server.register("echo", echo)
        path = os.path.join(tempfile.mkdtemp(), "rpc.sock")
        await server.listen_unix(path)
        client = RpcClient("unix:" + path, "test-client", src_id="cli")
        try:
            await test_body(server, client, calls)
        finally:
            await client.close()
            await server.close()

    asyncio.run(main())


def test_rpc_req_drop_is_callers_timeout():
    async def body(server, client, calls):
        server._netem.install(parse_netem("cli>srv:echo:drop:n=1"), seed=0)
        with pytest.raises(asyncio.TimeoutError):
            await client.call("echo", x=1, timeout=0.4)
        assert calls["n"] == 0  # the frame never reached the handler
        # the budget is spent: the retry sails through untouched
        out = await client.call("echo", x=2, timeout=5.0)
        assert out["x"] == 2 and calls["n"] == 1

    _rpc_pair(body)


def test_rpc_resp_drop_loses_reply_after_apply():
    """The hazard the ``_mid`` layer exists for: a resp-phase loss times
    the caller out AFTER the handler already ran."""
    async def body(server, client, calls):
        server._netem.install(
            parse_netem("cli>srv:echo:drop:n=1:phase=resp"), seed=0)
        with pytest.raises(asyncio.TimeoutError):
            await client.call("echo", x=1, timeout=0.4)
        assert calls["n"] == 1  # applied, reply lost
        out = await client.call("echo", x=2, timeout=5.0)
        assert out["n"] == 2

    _rpc_pair(body)


def test_rpc_delay_and_dup():
    async def body(server, client, calls):
        server._netem.install(
            parse_netem("cli>srv:echo:delay=0.3:n=1"), seed=0)
        t0 = time.monotonic()
        await client.call("echo", x=1, timeout=5.0)
        assert time.monotonic() - t0 >= 0.3
        # req-phase dup re-runs the handler exactly once more; the
        # duplicate carries the guard flag, so it cannot cascade
        server._netem.install(parse_netem("cli>srv:echo:dup:n=1"), seed=0)
        await client.call("echo", x=2, timeout=5.0)
        for _ in range(50):
            if calls["n"] >= 3:
                break
            await asyncio.sleep(0.05)
        assert calls["n"] == 3  # 1 (delayed) + 2 (original + one dup)
        # budget spent: a further call runs once
        await client.call("echo", x=3, timeout=5.0)
        await asyncio.sleep(0.2)
        assert calls["n"] == 4

    _rpc_pair(body)


# ---------------------------------------------------------------------------
# GCS harnesses (in-process, real sockets — the test_drain topology)
# ---------------------------------------------------------------------------


def _gcs_env(test_body, flags=None):
    """Run ``test_body(gcs, client)`` against an in-process GCS with a
    raw RPC client (no raylets: nothing else issues deduped verbs, so
    the at-most-once and fencing tables are fully test-controlled)."""
    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer

    config.reload(dict({"health_check_period_s": 1.0}, **(flags or {})))

    async def main():
        sd = tempfile.mkdtemp()
        os.makedirs(os.path.join(sd, "logs"), exist_ok=True)
        g = GcsServer(sd)
        await g.start()
        client = RpcClient(g.addr, "test-client", src_id="testcli")
        try:
            await test_body(g, client)
        finally:
            await client.close()
            await g.stop()

    try:
        asyncio.run(main())
    finally:
        config.reload()


def _cluster_env(test_body, flags=None):
    """Run ``test_body(gcs, raylet1, raylet2)`` on one event loop with
    live heartbeating raylets (the drain-test topology)."""
    from ray_tpu._private.config import config
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu._private.raylet import Raylet

    config.reload(dict({"health_check_period_s": 1.0}, **(flags or {})))

    async def main():
        sd = tempfile.mkdtemp()
        os.makedirs(os.path.join(sd, "logs"), exist_ok=True)
        g = GcsServer(sd)
        await g.start()
        r1 = Raylet(sd, g.addr, {"CPU": 2})
        await r1.start()
        r2 = Raylet(sd, g.addr, {"CPU": 2})
        await r2.start()
        try:
            await test_body(g, r1, r2)
        finally:
            for r in (r1, r2):
                try:
                    await r.stop()
                except Exception:  # noqa: BLE001
                    pass
            await g.stop()

    try:
        asyncio.run(main())
    finally:
        config.reload()


_NID = "feedc0de" * 8  # synthetic node id (no live raylet behind it)


async def _register(client, node_id=_NID, cpus=1.0):
    return await client.call(
        "register_node", node_id=node_id, addr="unix:/nonexistent",
        resources={"CPU": cpus}, labels={}, _mid=mint_mid())


# ---------------------------------------------------------------------------
# at-most-once GCS mutations
# ---------------------------------------------------------------------------


def test_gcs_at_most_once_dedup():
    async def body(g, client):
        mid = mint_mid()
        first = await client.call("next_job_id", _mid=mid)
        # a retry with the SAME _mid replays the cached reply: the
        # counter does not advance
        replay = await client.call("next_job_id", _mid=mid)
        assert replay == first
        assert g._job_counter == first
        # a fresh _mid is a fresh mutation
        second = await client.call("next_job_id", _mid=mint_mid())
        assert second == first + 1
        # idempotent verbs accept and ignore a _mid (uniform stamping)
        assert await client.call("kv_put", ns="t", key="k", value=b"v",
                                 _mid=mint_mid())
        assert await client.call("kv_put", ns="t", key="k", value=b"v",
                                 _mid=mint_mid())

    _gcs_env(body)


def test_gcs_dedup_never_caches_failures():
    """A raised mutation did not apply — the retry must re-execute for
    real instead of replaying the error (docs claim for the
    ``gcs.mutation_dedup`` fault site)."""
    async def body(g, client):
        baseline = await client.call("next_job_id", _mid=mint_mid())
        mid = mint_mid()
        fi.arm("gcs.mutation_dedup")
        try:
            with pytest.raises(Exception):
                await client.call("next_job_id", _mid=mid)
        finally:
            fi.disarm()
        assert g._job_counter == baseline  # the faulted apply never ran
        retry = await client.call("next_job_id", _mid=mid)
        assert retry == baseline + 1
        # and the successful retry IS now cached under that _mid
        assert await client.call("next_job_id", _mid=mid) == retry

    _gcs_env(body)


# ---------------------------------------------------------------------------
# cluster-epoch fencing
# ---------------------------------------------------------------------------


def test_fence_lifecycle_on_gcs_tables():
    async def body(g, client):
        ack = await _register(client)
        assert ack["incarnation"] == 1
        node = g.nodes[_NID]
        assert node["fence"] == 0
        # the view workers/raylets schedule against carries the identity
        view = {n["node_id"]: n for n in g._cluster_view()}
        assert view[_NID]["incarnation"] == 1 and view[_NID]["fence"] == 0

        # every death path funnels through _mark_node_dead: fence bumps
        await g._mark_node_dead(_NID, reason="test death")
        assert not node["alive"] and node["fence"] == 1

        # the dead incarnation is fenced; an unknown node is fenced too
        with pytest.raises(StaleNodeError):
            g._check_fence(_NID, 1)
        with pytest.raises(StaleNodeError):
            g._check_fence("na" * 32, 1)
        # zombie diagnostics accrue for list_nodes / status / dashboard
        assert node["stale_contacts"] >= 1
        assert node["last_stale_contact"] <= time.time()

        # a stale heartbeat is told so (the raylet's cue to self-fence)
        reply = await g.handle_heartbeat(node_id=_NID, available={},
                                         incarnation=1)
        assert reply.get("stale")

        # a fenced mutation is rejected END-TO-END: StaleNodeError
        # round-trips the RPC boundary as itself
        with pytest.raises(StaleNodeError):
            await client.call("kv_put", ns="t", key="k", value=b"v",
                              _fence={"node_id": _NID, "incarnation": 1})

        # rejoining mints an incarnation past the fence; the new identity
        # writes freely while the old one stays dead forever
        ack2 = await _register(client)
        assert ack2["incarnation"] == 2
        g._check_fence(_NID, 2)  # no raise
        reply = await g.handle_heartbeat(node_id=_NID, available={},
                                         incarnation=2)
        assert not reply.get("stale")
        with pytest.raises(StaleNodeError):
            g._check_fence(_NID, 1)

    _gcs_env(body)


def test_superseded_incarnation_cannot_overwrite_view():
    """Split-brain: two processes claim one node id.  The older
    incarnation's heartbeats must not clobber the live one's resources."""
    async def body(g, client):
        await _register(client, cpus=4.0)
        await _register(client, cpus=4.0)  # the "new" claimant: inc 2
        node = g.nodes[_NID]
        assert node["incarnation"] == 2
        await g.handle_heartbeat(node_id=_NID, available={"CPU": 3.0},
                                 incarnation=2)
        # the zombie claimant reports wildly different availability
        reply = await g.handle_heartbeat(node_id=_NID,
                                         available={"CPU": 0.0},
                                         incarnation=1)
        assert reply.get("stale")
        assert node["available"] == {"CPU": 3.0}
        assert node["stale_contacts"] >= 1

    _gcs_env(body)


# ---------------------------------------------------------------------------
# partitions end-to-end on live raylets
# ---------------------------------------------------------------------------


async def _wait_for(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        await asyncio.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


def test_arm_netem_fans_out_to_involved_raylets():
    async def body(g, r1, r2):
        rules = partition_rules(r1.node_id, "gcs", mode="symmetric",
                                duration_s=4.0)
        # lead_s pushes the window epoch out so the arming RPCs (and
        # their replies) never ride the partition they install
        ack = await g.handle_arm_netem(rules=rules, seed=7, lead_s=30.0)
        assert ack["armed"]["gcs"] and ack["armed"][r1.node_id]
        assert r2.node_id not in ack["armed"]  # uninvolved: not armed
        assert g.server._netem.active and r1.server._netem.active
        assert not r2.server._netem.active
        # the shared epoch anchors both ends to the same instant
        assert ack["epoch"] > time.time() + 25.0
        assert ack["schedule"] == g.server._netem.schedule()
        # an empty rule set clears the GCS emulator
        await g.handle_arm_netem(rules=[])
        assert not g.server._netem.active
        r1.server._netem.clear()

    _cluster_env(body)


@pytest.mark.chaos
def test_partition_death_fence_rejoin_loop():
    """The tentpole end-to-end: a oneway partition silences a raylet,
    the GCS declares it dead and bumps its fence; the heal exposes the
    zombie, whose next heartbeat is told ``stale`` — it self-fences and
    rejoins as a fresh incarnation with clean capacity."""
    async def body(g, r1, r2):
        victim = r1.node_id
        assert g.nodes[victim]["incarnation"] == 1
        # death timeout = (1.0/5) * 2 * 5 = 2.0s; the 5s window outlives
        # it, so the death is declared MID-partition
        rules = partition_rules(victim, "gcs", mode="oneway",
                                duration_s=5.0)
        ack = await g.handle_arm_netem(rules=rules, seed=42, lead_s=1.0)
        assert ack["armed"]["gcs"] and ack["armed"][victim]

        await _wait_for(lambda: not g.nodes[victim]["alive"], 15.0,
                        "heartbeat-timeout death of the victim")
        node = g.nodes[victim]
        assert node["fence"] == 1
        assert "heartbeat" in node["death_reason"]
        # the survivor never wavered
        assert g.nodes[r2.node_id]["alive"]

        # heal: the zombie's first heartbeat through is fenced, and the
        # raylet rejoins as incarnation 2
        await _wait_for(
            lambda: (g.nodes[victim]["alive"]
                     and g.nodes[victim]["incarnation"] == 2), 20.0,
            "fenced zombie rejoining as a fresh incarnation")
        assert r1.incarnation == 2
        assert g.nodes[victim]["fence"] == 1  # old identity dead forever
        with pytest.raises(StaleNodeError):
            g._check_fence(victim, 1)
        # rejoined clean: full capacity, no inherited drain
        assert not r1.draining
        assert r1.available.to_dict() == r1.total.to_dict()

    _cluster_env(body, flags={"num_heartbeats_timeout": 2})
