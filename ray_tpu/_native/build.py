"""Build-on-first-use for the native components.

The wheel-less dev layout compiles ``store.cc`` with the system toolchain
once and caches the .so keyed by a source hash (reference builds its C++
core with Bazel into the wheel; here the toolchain is part of the runtime
environment).
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import threading
from typing import Optional

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_NATIVE_DIR, "_build")
_lock = threading.Lock()
_lib_path: Optional[str] = None
_build_error: Optional[str] = None


def lib_path() -> Optional[str]:
    """Path to the built librtpu_store.so, or None if the build failed."""
    global _lib_path, _build_error
    with _lock:
        if _lib_path is not None or _build_error is not None:
            return _lib_path
        src = os.path.join(_NATIVE_DIR, "store.cc")
        try:
            with open(src, "rb") as f:
                tag = hashlib.sha256(f.read()).hexdigest()[:16]
            out = os.path.join(_BUILD_DIR, f"librtpu_store-{tag}.so")
            if not os.path.exists(out):
                os.makedirs(_BUILD_DIR, exist_ok=True)
                tmp = out + f".tmp.{os.getpid()}"
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     "-o", tmp, src, "-lpthread", "-lrt"],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, out)  # atomic: racing builders both succeed
            _lib_path = out
        except Exception as e:  # toolchain missing / compile error
            _build_error = repr(e)
            _lib_path = None
        return _lib_path


def build_error() -> Optional[str]:
    return _build_error
