"""Tiered asynchronous sharded checkpointing for ``JaxTrainer``.

The Orbax emergency-checkpointing discipline, natively: a train step
pays only the **snapshot** (donation-safe D2H copy of the shards this
rank owns), while serialize+fsync runs on a background thread and a
copy of the shard is pushed to a peer node's RAM
(``ray_tpu.util.checkpoint_replica``).  Restore walks a preference
ladder per shard — local RAM -> peer RAM -> committed disk — so the
common failure (one preempted/SIGKILLed host in a slice) restores with
zero disk reads for the lost shards.

On-disk layout (same WAL discipline as ``checkpoint_manager``)::

    <storage>/checkpoint_000007.tmp/     # staging dir, any rank creates
        shard_r00          # each rank: write shard_rNN.tmp, fsync, rename
        shard_r01
        MANIFEST.json      # rank 0, after ALL shards landed (tmp+rename)
    <storage>/checkpoint_000007/         # single rank-0 os.rename commits

A writer SIGKILLed anywhere before the final rename leaves only a
``*.tmp`` dir that ``committed_checkpoint_dirs`` ignores and the next
``CheckpointManager`` sweeps — torn multi-rank writes are unobservable.

Shard blobs are **self-describing** (pytree skeleton + global leaf
shapes + index-bounded pieces), so restore can reassemble the full tree
from any mix of RAM and disk shards, written by any world size — a
``clamp_to``-shrunk mesh reassembles shards it didn't write
(resharding-aware restore), and a pure RAM-tier ("memory") checkpoint
that never reached disk restores the same way.

Fault sites: ``train.checkpoint.persist_async`` (background serialize+
fsync edge), ``train.checkpoint.peer_push`` (replication edge, in
``checkpoint_replica``), ``train.checkpoint.restore`` (ladder entry).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import pickle
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.config import config
from ray_tpu.train.checkpoint_manager import (
    _fsync_dir,
    committed_checkpoint_dirs,
)
from ray_tpu.util import checkpoint_replica as replica
from ray_tpu.util.fault_injection import fault_point

logger = logging.getLogger(__name__)

MANIFEST_NAME = "MANIFEST.json"

#: process-local RAM tier: ``(run, index, rank) -> blob bytes`` — the
#: first rung of the restore ladder (free for in-process restarts, e.g.
#: an elastic re-mesh that kept this worker alive)
_LOCAL_KEEP = 2
_local_lock = threading.Lock()
_local_cache: Dict[Tuple[str, int, int], bytes] = {}


def _local_put(run: str, index: int, rank: int, blob: bytes) -> None:
    with _local_lock:
        _local_cache[(run, index, rank)] = blob
        gens = sorted({k[1] for k in _local_cache if k[0] == run})
        for old in gens[:-_LOCAL_KEEP]:
            for k in [k for k in _local_cache
                      if k[0] == run and k[1] == old]:
                del _local_cache[k]


def _local_get(run: str, index: int, rank: int) -> Optional[bytes]:
    with _local_lock:
        return _local_cache.get((run, index, rank))


def shard_name(rank: int) -> str:
    return f"shard_r{rank:02d}"


# ---------------------------------------------------------------------------
# snapshot: donation-safe D2H copy of the pieces THIS rank owns
# ---------------------------------------------------------------------------


def _leaf_paths(tree: Any) -> Tuple[Any, List[str], List[Any]]:
    """(skeleton, path strings, leaves): the skeleton is the tree with
    each leaf replaced by its path string — picklable structure that
    reassembly maps back to arrays (no treedef pickling needed)."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = [jax.tree_util.keystr(p) for p, _ in flat]
    leaves = [x for _, x in flat]
    skeleton = jax.tree_util.tree_unflatten(treedef, paths)
    return skeleton, paths, leaves


def _split_bounds(dim0: int, world: int) -> List[Tuple[int, int]]:
    """Contiguous ``np.array_split``-compatible [lo, hi) bounds of a
    leading axis of size ``dim0`` over ``world`` writers."""
    base, extra = divmod(dim0, world)
    bounds, lo = [], 0
    for r in range(world):
        hi = lo + base + (1 if r < extra else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def snapshot_shards(tree: Any, rank: int, world: int,
                    run: str = "", index: int = 0,
                    meta: Optional[Dict[str, Any]] = None) -> bytes:
    """Snapshot the shard pieces ``rank`` owns as one self-describing
    blob (pickled).  Every array is **copied to host RAM** before this
    returns — the caller may immediately donate/overwrite the device
    tree (donation-safe).

    Ownership: a multi-process ``jax.Array`` contributes its addressable
    shards (``replica_id == 0`` dedups replicas — the GSPMD-native
    path); fully-addressable leaves are split contiguously along axis 0
    across the world (replicated-DP path), with small/scalar leaves
    owned by ``leaf_i % world`` alone.  Either way the union over ranks
    tiles every leaf exactly once, which reassembly verifies.
    """
    import numpy as np

    import jax

    skeleton, paths, leaves = _leaf_paths(tree)
    leaf_info: Dict[str, Tuple[List[int], str]] = {}
    pieces: List[Tuple[str, Optional[List[Tuple[int, int]]], Any]] = []
    for i, (path, x) in enumerate(zip(paths, leaves)):
        is_jax = isinstance(x, jax.Array)
        shape = tuple(x.shape) if hasattr(x, "shape") else ()
        dtype = str(x.dtype) if hasattr(x, "dtype") else "object"
        leaf_info[path] = (list(shape), dtype)
        if is_jax and not x.is_fully_addressable:
            # GSPMD global array: this process owns exactly its
            # addressable shards (dedup replicas via replica_id)
            for sh in x.addressable_shards:
                if sh.replica_id != 0:
                    continue
                bounds = [(sl.start or 0,
                           sl.stop if sl.stop is not None else dim)
                          for sl, dim in zip(sh.index, shape)]
                pieces.append((path, bounds, np.array(sh.data)))
            continue
        host = np.array(x)  # D2H (or defensive host copy): always a copy
        if host.ndim >= 1 and host.shape[0] >= world > 1:
            lo, hi = _split_bounds(host.shape[0], world)[rank]
            bounds = [(lo, hi)] + [(0, d) for d in host.shape[1:]]
            pieces.append((path, bounds, np.ascontiguousarray(host[lo:hi])))
        elif i % world == rank:
            pieces.append((path, None, host))  # sole owner, whole leaf
    return pickle.dumps({
        "format": 1,
        "run": run,
        "index": index,
        "rank": rank,
        "world": world,
        "skeleton": skeleton,
        "leaves": leaf_info,
        "pieces": pieces,
        "meta": dict(meta or {}),
    })


# ---------------------------------------------------------------------------
# disk tier: per-rank shard stage+fsync+rename, rank-0 manifest commit
# ---------------------------------------------------------------------------


def _staging_dir(storage_dir: str, index: int) -> str:
    return os.path.join(storage_dir, f"checkpoint_{index:06d}.tmp")


def _committed_dir(storage_dir: str, index: int) -> str:
    return os.path.join(storage_dir, f"checkpoint_{index:06d}")


def write_shard(storage_dir: str, index: int, rank: int,
                blob: bytes) -> str:
    """Persist one rank's shard into the generation's staging dir:
    write ``shard_rNN.tmp``, fsync, rename to ``shard_rNN``.  Any crash
    mid-write leaves only ``*.tmp`` names the manifest commit ignores."""
    stage = _staging_dir(storage_dir, index)
    os.makedirs(stage, exist_ok=True)
    final = os.path.join(stage, shard_name(rank))
    tmp = final + ".tmp"
    fault_point("train.checkpoint.persist_async")
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, final)
    _fsync_dir(stage)
    return final


def commit_manifest(storage_dir: str, index: int, world: int,
                    meta: Optional[Dict[str, Any]] = None,
                    wait_s: Optional[float] = None) -> str:
    """Rank 0's commit leg: wait (bounded) for all ``world`` shard files
    to land in the staging dir, write ``MANIFEST.json`` (tmp+fsync+
    rename), then publish the whole generation with one directory
    rename.  Raises ``TimeoutError`` if a writer died mid-persist — the
    generation then stays ``*.tmp`` (torn, unobservable to restore) and
    the next manager sweep removes it."""
    if wait_s is None:
        wait_s = config.train_checkpoint_manifest_wait_s
    stage = _staging_dir(storage_dir, index)
    want = {shard_name(r) for r in range(world)}
    deadline = time.monotonic() + wait_s
    while True:
        try:
            have = set(os.listdir(stage))
        except OSError:
            have = set()
        if want <= have:
            break
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"checkpoint_{index:06d}: shards missing after {wait_s}s: "
                f"{sorted(want - have)} (writer died mid-persist; "
                "generation stays torn/.tmp)")
        time.sleep(0.05)
    manifest = {
        "index": index,
        "world_size": world,
        "sharded": True,
        "shards": sorted(want),
        "meta": dict(meta or {}),
    }
    mtmp = os.path.join(stage, MANIFEST_NAME + ".tmp")
    with open(mtmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.rename(mtmp, os.path.join(stage, MANIFEST_NAME))
    _fsync_dir(stage)
    dest = _committed_dir(storage_dir, index)
    # the commit point (same site as the legacy whole-tree path): a kill
    # here leaves .tmp only; a committed dir is always fully durable
    fault_point("train.checkpoint.commit")
    os.rename(stage, dest)
    _fsync_dir(storage_dir)
    return dest


def read_manifest(path: str) -> Optional[Dict[str, Any]]:
    """The manifest of a committed sharded checkpoint dir (None for
    legacy whole-tree checkpoints, which have no MANIFEST.json)."""
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# reassembly (resharding-aware): full tree from any world's shard blobs
# ---------------------------------------------------------------------------


class IncompleteCheckpointError(RuntimeError):
    """A generation's shards do not tile every leaf exactly once."""


def reassemble(blobs: Dict[int, bytes]) -> Tuple[Any, Dict[str, Any]]:
    """Rebuild the full host pytree from one generation's shard blobs
    (``{writer_rank: blob}``), regardless of which mesh/world wrote
    them.  Verifies exact tiling — every element written exactly once —
    and raises :class:`IncompleteCheckpointError` otherwise."""
    import numpy as np

    import jax

    if not blobs:
        raise IncompleteCheckpointError("no shard blobs to reassemble")
    decoded = {r: pickle.loads(b) for r, b in blobs.items()}
    ref = decoded[min(decoded)]
    world = ref["world"]
    if set(decoded) != set(range(world)):
        raise IncompleteCheckpointError(
            f"have writer ranks {sorted(decoded)}, need 0..{world - 1}")
    arrays: Dict[str, Any] = {}
    filled: Dict[str, int] = {}
    for path, (shape, dtype) in ref["leaves"].items():
        arrays[path] = np.empty(shape, dtype=np.dtype(dtype))
        filled[path] = 0
    for shard in decoded.values():
        for path, bounds, piece in shard["pieces"]:
            arr = arrays[path]
            if bounds is None:
                arrays[path] = np.array(piece)
                filled[path] += int(np.asarray(piece).size) or 1
            else:
                idx = tuple(slice(lo, hi) for lo, hi in bounds)
                arr[idx] = piece
                filled[path] += int(np.asarray(piece).size)
    for path, (shape, _dtype) in ref["leaves"].items():
        want = int(np.prod(shape)) if shape else 1
        if filled[path] != want:
            raise IncompleteCheckpointError(
                f"leaf {path}: {filled[path]} of {want} elements covered "
                "(overlapping or missing shard pieces)")
    tree = jax.tree.map(lambda p: arrays[p], ref["skeleton"])
    return tree, dict(ref["meta"])


def load_disk_shards(path: str,
                     ranks: Optional[Sequence[int]] = None
                     ) -> Dict[int, bytes]:
    """Read shard blobs from a committed sharded checkpoint dir."""
    manifest = read_manifest(path)
    if manifest is None:
        return {}
    world = manifest["world_size"]
    want = range(world) if ranks is None else ranks
    out: Dict[int, bytes] = {}
    for r in want:
        try:
            with open(os.path.join(path, shard_name(r)), "rb") as f:
                out[r] = f.read()
        except OSError:
            continue
    return out


# ---------------------------------------------------------------------------
# restore ladder: local RAM -> peer RAM -> committed disk
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RestoreResult:
    tree: Any
    meta: Dict[str, Any]
    index: int
    world: int                    # world size that WROTE the checkpoint
    tier_by_rank: Dict[int, str]  # writer rank -> "local"|"peer"|"disk"
    disk_reads: int
    path: Optional[str]           # committed dir (None for memory tier)

    @property
    def tier(self) -> str:
        """The slowest tier the ladder had to touch ("memory" when no
        shard needed disk)."""
        return "disk" if self.disk_reads else "memory"


def _blob_world(blob: bytes) -> int:
    return pickle.loads(blob)["world"]


def _blob_matches(blob: bytes, run: str, index: int, rank: int) -> bool:
    """RAM/local-cache blobs are validated against the generation being
    restored: a blob whose embedded ``(run, index, rank)`` disagrees
    with the slot it was fetched from is treated as MISSING, never
    reassembled.  Defense in depth against cross-generation shard
    mixing — disk shards skip this (they live inside the committed,
    manifest-checked generation dir)."""
    try:
        hdr = pickle.loads(blob)
        return (hdr.get("run", run) == run and hdr.get("index") == index
                and hdr.get("rank") == rank)
    except Exception:  # noqa: BLE001 — corrupt blob == missing shard
        return False


def restore_tiered(storage_dir: Optional[str], run: str, *,
                   server_names: Sequence[str] = (),
                   rpc_timeout_s: Optional[float] = None
                   ) -> Optional[RestoreResult]:
    """Restore the newest complete checkpoint generation for ``run``,
    preferring RAM over disk per shard.

    Candidates are the union of committed disk generations and
    RAM-tier generations the replica plane holds (a ``memory``-tier
    drain checkpoint may exist only in peer RAM).  For each candidate,
    newest first, every writer rank's shard is fetched via the ladder —
    process-local cache, then peer RAM, then the committed disk file —
    and the first generation that reassembles completely wins.  Torn
    disk generations (``*.tmp``) are invisible by construction; a
    RAM generation missing shards (dead peer) falls through to disk or
    to the next older candidate.
    """
    fault_point("train.checkpoint.restore")
    if rpc_timeout_s is None:
        rpc_timeout_s = config.train_checkpoint_replica_rpc_timeout_s
    disk: Dict[int, str] = {}
    if storage_dir:
        for index, path in committed_checkpoint_dirs(storage_dir):
            if read_manifest(path) is not None:
                disk[index] = path
    ram = replica.ram_manifest_by_names(server_names, timeout=rpc_timeout_s) \
        if server_names else {}
    with _local_lock:
        local_gens = sorted({k[1] for k in _local_cache if k[0] == run})
    candidates = sorted(set(disk) | set(ram) | set(local_gens), reverse=True)
    for index in candidates:
        got: Dict[int, bytes] = {}
        tier_by_rank: Dict[int, str] = {}
        disk_reads = 0
        # discover the writing world: disk manifest, else any RAM blob
        world: Optional[int] = None
        path = disk.get(index)
        if path is not None:
            manifest = read_manifest(path)
            world = manifest["world_size"] if manifest else None
        probe_ranks = ram.get(index, []) or list(
            {k[2] for k in _local_cache
             if k[0] == run and k[1] == index})
        if world is None and probe_ranks:
            pr = probe_ranks[0]
            candidates_pr = [_local_get(run, index, pr)]
            if server_names:
                candidates_pr.append(
                    (replica.fetch_shard(server_names, index, pr,
                                         timeout=rpc_timeout_s)
                     or (None,))[0])
            for blob in candidates_pr:
                if blob is not None and _blob_matches(blob, run, index, pr):
                    world = _blob_world(blob)
                    got[pr] = blob
                    break
        if world is None:
            continue
        ok = True
        for r in range(world):
            if r in got:
                lb = _local_get(run, index, r)
                tier_by_rank[r] = "local" if (
                    lb is not None and _blob_matches(lb, run, index, r)
                ) else "peer"
                continue
            blob = _local_get(run, index, r)
            if blob is not None and _blob_matches(blob, run, index, r):
                got[r] = blob
                tier_by_rank[r] = "local"
                continue
            fetched = replica.fetch_shard(
                server_names, index, r,
                timeout=rpc_timeout_s) if server_names else None
            if fetched is not None and _blob_matches(
                    fetched[0], run, index, r):
                got[r] = fetched[0]
                tier_by_rank[r] = "peer"
                continue
            if path is not None:
                from_disk = load_disk_shards(path, ranks=[r])
                if r in from_disk:
                    got[r] = from_disk[r]
                    tier_by_rank[r] = "disk"
                    disk_reads += 1
                    continue
            ok = False
            break
        if not ok:
            logger.warning(
                "restore %s: generation %d incomplete across all tiers; "
                "trying older", run, index)
            continue
        try:
            tree, meta = reassemble(got)
        except IncompleteCheckpointError as e:
            logger.warning("restore %s: generation %d: %s", run, index, e)
            continue
        return RestoreResult(tree=tree, meta=meta, index=index, world=world,
                             tier_by_rank=tier_by_rank,
                             disk_reads=disk_reads, path=path)
    return None


# ---------------------------------------------------------------------------
# the async checkpointer: snapshot inline, persist+replicate in background
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TieredCheckpoint:
    """Handle for one tiered save: returned by ``AsyncCheckpointer.save``
    the moment the snapshot lands in host RAM (the persist may still be
    in flight — ``ram_acked``/``committed_path`` fill in as the
    background tiers land)."""

    run: str
    index: int
    rank: int
    world: int
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False, compare=False)
    ram_acked: bool = False
    committed_path: Optional[str] = None
    error: Optional[BaseException] = dataclasses.field(
        default=None, repr=False, compare=False)

    @property
    def tier(self) -> str:
        """Best durability tier reached so far: ``disk`` once the
        manifest committed, else ``memory`` once a peer acked, else
        ``local`` (this process's RAM only)."""
        if self.committed_path:
            return "disk"
        return "memory" if self.ram_acked else "local"

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)


class AsyncCheckpointer:
    """Per-rank tiered checkpoint writer with one-in-flight backpressure.

    ``save()`` snapshots (D2H copy + local cache) inline — the only cost
    the step pays, charged to the ``checkpoint_snapshot`` ledger bucket
    — then hands persist+replicate to a daemon thread: peer-RAM push
    first (the emergency tier lands fastest), then the fsynced shard
    write and, on rank 0, the manifest commit.  A second ``save()``
    while a persist is in flight **waits** (bounded by
    ``train_checkpoint_persist_wait_s``, charged to
    ``checkpoint_persist`` — lag surfacing inline), never silently
    drops a snapshot.  A ``preempt_ram`` hook (wired by the train
    session to the controller's memory-tier drain request) preempts
    that wait and commits the save at the peer-RAM tier inline,
    skipping the disk queue — the emergency-checkpoint leg of the
    drain protocol.
    """

    def __init__(self, storage_dir: Optional[str], run: str, rank: int,
                 world: int, *, peer_name: Optional[str] = None,
                 server_names: Sequence[str] = (),
                 ledger: Any = None, publish_status: bool = True,
                 preempt_ram: Optional[Callable[[], bool]] = None,
                 drain_avoid: Optional[Callable[[], Any]] = None):
        self.storage_dir = storage_dir
        self.run = run
        self.rank = rank
        self.world = world
        self.peer_name = peer_name
        self.server_names = list(server_names)
        # when this returns True, save() must commit at the RAM tier NOW
        # (a sub-disk-deadline drain is pending): it preempts the
        # backpressure wait and bypasses the disk queue — see save()
        self._preempt_ram = preempt_ram
        # node ids the pending drain covers: the emergency push
        # re-targets off these (a replica on a node the drain protocol
        # is about to shut down is no replica at all)
        self._drain_avoid = drain_avoid
        self._ledger = ledger
        self._publish_status = publish_status
        self._idle = threading.Event()
        self._idle.set()
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._last: Optional[TieredCheckpoint] = None
        self._next_index: Optional[int] = None
        self._lock = threading.Lock()
        self._closed = False
        self._snapshot_s = 0.0
        self._persist_s = 0.0

    # -- indexing -----------------------------------------------------------

    def _ensure_index(self) -> int:
        """First-save index discovery: one past the newest **complete**
        generation in any tier — committed disk dirs, plus RAM
        generations holding every writer rank's shard (a ``memory``-tier
        drain checkpoint lives only there).

        Completeness is load-bearing, not cosmetic.  Ranks discover at
        slightly different times; if a sibling's *in-flight* first save
        (a staged ``.tmp`` dir, a half-pushed RAM generation) bumped the
        base, the late rank would start numbering one higher and every
        generation after that would pair shards from ADJACENT training
        steps under one index — restore then reassembles a tree that
        never existed on any step.  Complete generations are the only
        fixed points every rank observes identically, so all ranks
        compute the same base and lockstep saves advance it
        identically.  (An old torn ``.tmp`` at base+1 is simply
        re-staged and committed by the new writers.)"""
        if self._next_index is None:
            base = 0
            if self.storage_dir:
                dirs = committed_checkpoint_dirs(self.storage_dir)
                if dirs:
                    base = dirs[-1][0]
            if self.server_names:
                complete = replica.ram_complete_generations(
                    self.server_names)
                if complete:
                    base = max(base, complete[-1])
            self._next_index = base + 1
        return self._next_index

    def _emergency_peer(self, avoid: Any) -> Optional[str]:
        """Push target for a memory-tier emergency save: the normal ring
        peer unless its node is covered by the drain notice, else the
        first replica server on a surviving node.  Server names encode
        their node (``_ckpt_replica::<run>::<node_id>``), so no extra
        control-plane round trip is needed at the worst possible time."""
        avoid = set(avoid or ())

        def _node(name: str) -> str:
            return name.rsplit("::", 1)[-1]

        if self.peer_name and _node(self.peer_name) not in avoid:
            return self.peer_name
        for name in self.server_names:
            if _node(name) not in avoid:
                return name
        return self.peer_name  # every node doomed: best effort

    # -- background persist -------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._persist_loop,
                name=f"ckpt-persist-{self.run}-r{self.rank}", daemon=True)
            self._thread.start()

    def _persist_loop(self) -> None:
        while True:
            try:
                # bounded wake-ups (not a hang guard — the producer is
                # this same process): lets a wedged owner's daemon
                # thread notice interpreter shutdown instead of
                # blocking in C forever
                job = self._q.get(timeout=5.0)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if job is None:
                return
            handle, blob, meta = job
            t0 = time.perf_counter()
            try:
                self._persist_one(handle, blob, meta)
            except BaseException as e:  # noqa: BLE001 — surfaced on handle
                handle.error = e
                logger.warning(
                    "async persist of %s checkpoint_%06d rank %d failed: "
                    "%s (durable tiers: %s)", self.run, handle.index,
                    self.rank, e, handle.tier)
            finally:
                dur = time.perf_counter() - t0
                self._persist_s = dur
                if self._ledger is not None:
                    # off the step critical path, but attributed — the
                    # breakdown shows persist OVERLAPPING compute
                    self._ledger.note("checkpoint_persist", dur)
                handle.done.set()
                self._idle.set()
                self._publish_kv(handle)

    def _persist_one(self, handle: TieredCheckpoint, blob: bytes,
                     meta: Dict[str, Any]) -> None:
        # emergency tier first: the peer ack is what a short-deadline
        # drain waits on, so it must not queue behind the disk write.
        # A failed push degrades (no RAM tier this generation) — it
        # must never abort the persist and take the disk tier with it
        if self.peer_name:
            try:
                handle.ram_acked = replica.push_shard(
                    self.peer_name, handle.index, self.rank, blob,
                    {"run": self.run, "world": self.world, **meta})
            except Exception as e:  # noqa: BLE001 — peer may be dead
                handle.ram_acked = False
                logger.warning(
                    "peer-RAM push of %s checkpoint_%06d rank %d to %s "
                    "failed (%s); continuing with the disk tier",
                    self.run, handle.index, self.rank, self.peer_name, e)
        if self.storage_dir:
            write_shard(self.storage_dir, handle.index, self.rank, blob)
            if self.rank == 0:
                handle.committed_path = commit_manifest(
                    self.storage_dir, handle.index, self.world, meta)
            else:
                # non-zero ranks surface commit completion too (poll,
                # bounded): lets any rank's handle report tier="disk"
                dest = _committed_dir(self.storage_dir, handle.index)
                deadline = time.monotonic() + \
                    config.train_checkpoint_manifest_wait_s
                while time.monotonic() < deadline:
                    if os.path.isdir(dest):
                        handle.committed_path = dest
                        break
                    time.sleep(0.05)

    # -- the public face ----------------------------------------------------

    def save(self, tree: Any, metrics: Optional[Dict[str, Any]] = None, *,
             wait_persist: bool = False,
             persist_wait_s: Optional[float] = None) -> TieredCheckpoint:
        """Tiered save of this rank's shards of ``tree``.

        Returns as soon as the snapshot is in host RAM (and enqueued for
        persist+replication).  ``wait_persist=True`` blocks until the
        disk tier lands too — the synchronous arm of the A/B bench, and
        what a final checkpoint before clean shutdown wants.
        """
        if persist_wait_s is None:
            persist_wait_s = config.train_checkpoint_persist_wait_s
        # backpressure: at most one persist in flight; a second save
        # WAITS for it (bounded) — never silently drops a snapshot.
        # The wait is PREEMPTIBLE by a memory-tier drain request
        # (``preempt_ram``): a slow or faulted disk persist would
        # otherwise wedge the loop in this wait right through a reclaim
        # deadline the peer-RAM ack alone could meet — the emergency
        # path below pushes inline and never touches the disk queue
        ram_only = self._preempt_ram is not None and self._preempt_ram()
        if not ram_only and not self._idle.is_set():
            t0 = time.perf_counter()
            deadline = t0 + persist_wait_s
            while not self._idle.wait(0.05):
                if self._preempt_ram is not None and self._preempt_ram():
                    ram_only = True
                    break
                if time.perf_counter() >= deadline:
                    raise TimeoutError(
                        f"checkpoint persist backpressure: previous "
                        f"persist still in flight after {persist_wait_s}s")
            if self._ledger is not None:
                self._ledger.note("checkpoint_persist",
                                  time.perf_counter() - t0)
        t0 = time.perf_counter()
        index = self._ensure_index()
        self._next_index = index + 1
        meta = dict(metrics or {})
        blob = snapshot_shards(tree, self.rank, self.world,
                               run=self.run, index=index, meta=meta)
        _local_put(self.run, index, self.rank, blob)
        snap_s = time.perf_counter() - t0
        self._snapshot_s = snap_s
        if self._ledger is not None:
            self._ledger.note("checkpoint_snapshot", snap_s)
        handle = TieredCheckpoint(run=self.run, index=index,
                                  rank=self.rank, world=self.world)
        with self._lock:
            self._last = handle
        if ram_only:
            # emergency memory-tier save: inline peer push, no disk leg
            # for this generation (it commits at the RAM tier or not at
            # all — the restarted group restores it from the replica
            # plane, and the next normal save resumes the disk cadence
            # at index+1).  The in-flight persist keeps running; this
            # handle completes without queuing behind it.
            t1 = time.perf_counter()
            target = self._emergency_peer(
                self._drain_avoid() if self._drain_avoid else ())
            if target:
                try:
                    handle.ram_acked = replica.push_shard(
                        target, index, self.rank, blob,
                        {"run": self.run, "world": self.world, **meta})
                except Exception as e:  # noqa: BLE001 — peer may be dead
                    handle.ram_acked = False
                    logger.warning(
                        "emergency peer-RAM push of %s checkpoint_%06d "
                        "rank %d to %s failed: %s", self.run, index,
                        self.rank, target, e)
            handle.done.set()
            if self._ledger is not None:
                self._ledger.note("checkpoint_persist",
                                  time.perf_counter() - t1)
            self._publish_kv(handle)
            return handle
        self._idle.clear()
        self._ensure_thread()
        self._q.put((handle, blob, meta))
        if wait_persist:
            handle.wait(persist_wait_s)
            if handle.error is not None:
                raise handle.error
        return handle

    @property
    def last(self) -> Optional[TieredCheckpoint]:
        with self._lock:
            return self._last

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Drain the persist queue (True when idle within ``timeout``)."""
        return self._idle.wait(timeout)

    def commit_ram(self, timeout: Optional[float] = None) -> bool:
        """Wait (bounded) for the LAST save's peer-RAM ack — the
        ``memory``-tier commit a short-deadline drain needs: once True,
        this rank's newest shard is durable on a peer host and a
        restarted group can restore it with zero disk reads."""
        handle = self.last
        if handle is None:
            return False
        if timeout is None:
            timeout = config.train_checkpoint_persist_wait_s
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if handle.ram_acked or handle.committed_path:
                return True
            if handle.done.is_set():
                return bool(handle.ram_acked or handle.committed_path)
            time.sleep(0.02)
        return bool(handle.ram_acked or handle.committed_path)

    def restore(self) -> Optional[RestoreResult]:
        """Walk the restore ladder with this checkpointer's plane wiring
        (see :func:`restore_tiered`).  A successful restore also PINS
        this rank's next save index to ``restored + 1``: every restarted
        rank resumes from the same generation, so pinning is the one
        cross-rank synchronization point index numbering gets — saves
        after a restart agree by construction instead of by racy
        re-discovery."""
        res = restore_tiered(self.storage_dir, self.run,
                             server_names=self.server_names)
        if res is not None:
            self._next_index = res.index + 1
        return res

    def close(self, timeout: float = 5.0) -> None:
        self._idle.wait(timeout)
        self._closed = True
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout)

    # -- per-tier status surfacing (util.state + dashboard) -----------------

    def _publish_kv(self, handle: TieredCheckpoint) -> None:
        if not self._publish_status:
            return
        try:
            import ray_tpu

            if not ray_tpu.is_initialized():
                return
            from ray_tpu._private.worker import get_global_worker

            w = get_global_worker(required=False)
            if w is None:
                return
            rec = {
                "ts": time.time(),
                "run": self.run,
                "rank": self.rank,
                "world": self.world,
                "index": handle.index,
                "tier": handle.tier,
                "ram_acked": handle.ram_acked,
                "committed_path": handle.committed_path,
                "snapshot_s": round(self._snapshot_s, 6),
                "persist_s": round(self._persist_s, 6),
                "error": repr(handle.error) if handle.error else None,
            }
            key = f"ckpt_status/{self.run}/{self.rank}"
            w.run_coro(
                w.gcs.call("kv_put", ns="train", key=key,
                           value=json.dumps(rec).encode(), overwrite=True,
                           timeout=2),
                timeout=4)
        except Exception:  # noqa: BLE001 — surfacing must never fail a save
            pass
