"""Logical-axis → mesh-axis sharding rules (GSPMD partitioning).

Model code annotates arrays with *logical* axis names ("batch", "embed",
"mlp", "heads", "seq", "vocab"); a rule table maps those to mesh axes.
Switching parallelism strategy = switching the rule table, not the model.

This replaces the reference's per-strategy engines (DDP wrap at
``torch_learner.py:432``, FSDP at ``train_loop_utils.py:176``, vLLM TP/PP)
with one declarative mechanism.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: A/B escape hatch for the multichip layout-discipline bench: ``1``
#: restores the pre-discipline constraint set (no gather-operand
#: constraints, DEFAULT_RULES-only ``_constrain``) so a round can
#: measure fixed-vs-legacy on identical hardware.  Read at TRACE time —
#: set it before the trainer's first step, not mid-run.
ENV_LEGACY_SHARDING = "RAY_TPU_LEGACY_SHARDING"


def legacy_sharding_enabled() -> bool:
    """True when the legacy (pre-layout-discipline) constraint set is
    requested via :data:`ENV_LEGACY_SHARDING`."""
    return os.environ.get(ENV_LEGACY_SHARDING, "").strip().lower() in (
        "1", "true", "yes")

# A logical axis maps to one mesh axis, a tuple of mesh axes, or None
# (replicated).
LogicalAxisRules = Dict[str, Union[str, Tuple[str, ...], None]]

# Default rules: batch over (dp, fsdp); weights sharded over fsdp on their
# largest dim and over tp Megatron-style; sequence over sp for ring attention.
# Every logical axis any models/ spec tree uses MUST appear here — an
# explicit None records a deliberate replication decision; a *missing*
# name would replicate silently, which the tooling guard
# (tests/test_sharded_train.py) rejects.
DEFAULT_RULES: LogicalAxisRules = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed": "fsdp",
    "mlp": "tp",
    "heads": "tp",
    "kv_heads": "tp",
    "qkv": None,
    "head_dim": None,
    "vocab": "tp",
    "expert": "tp",
    "layers": "pp",
    # norm scales / biases / cls tokens: O(hidden) vectors — sharding
    # them saves nothing and costs an all-gather per use
    "norm": None,
}

# Rules for inference-style TP-only sharding (no fsdp axis in use).
TP_INFERENCE_RULES: LogicalAxisRules = {
    **DEFAULT_RULES,
    "embed": None,
    "batch": "dp",
}


def logical_to_pspec(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[LogicalAxisRules] = None,
    *,
    mesh: Optional[Mesh] = None,
) -> P:
    """Map a tuple of logical axis names to a PartitionSpec.

    Axes not in the rule table (or mapped to None) are replicated.  A mesh
    axis may be consumed at most once per spec; later conflicting uses are
    replicated instead (GSPMD requires distinct mesh axes per dim).
    """
    rules = DEFAULT_RULES if rules is None else rules
    used: set = set()
    out = []
    for ax in logical_axes:
        mapped = rules.get(ax) if ax is not None else None
        if mapped is None:
            out.append(None)
            continue
        axes = (mapped,) if isinstance(mapped, str) else tuple(mapped)
        if mesh is not None:
            axes = tuple(a for a in axes if a in mesh.axis_names)
        axes = tuple(a for a in axes if a not in used)
        used.update(axes)
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def spec_tree_to_shardings(
    spec_tree: Any, mesh: Mesh, rules: Optional[LogicalAxisRules] = None
) -> Any:
    """Convert a pytree of logical-axis tuples into NamedShardings."""
    return jax.tree.map(
        lambda axes: NamedSharding(
            mesh, logical_to_pspec(axes, rules, mesh=mesh)
        ),
        spec_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(e, (str, type(None))) for e in x),
    )


def shard_tree(
    tree: Any,
    spec_tree: Any,
    mesh: Mesh,
    rules: Optional[LogicalAxisRules] = None,
) -> Any:
    """Device-put a pytree according to its logical-axis spec tree."""
    shardings = spec_tree_to_shardings(spec_tree, mesh, rules)
    return jax.tree.map(jax.device_put, tree, shardings)


def with_logical_constraint(
    x: jax.Array,
    mesh: Optional[Mesh],
    *axes: Optional[str],
    rules: Optional[LogicalAxisRules] = None,
) -> Any:
    """Constrain an intermediate value's sharding inside jit, by
    LOGICAL axis names resolved through the rule table.

    This is the one sanctioned way for model code to pin a layout: the
    same rule table that shards the params decides the activation
    layout, so a rules override (``ScalingConfig.logical_axis_rules``,
    ``ShardedTrainer(rules=...)``) moves params *and* activations
    together — mismatched halves are exactly what XLA's involuntary
    full rematerializations punished.  ``mesh=None`` is a no-op so
    model code stays mesh-optional.  The raylint ``sharding-discipline``
    rule rejects raw device-axis ``PartitionSpec`` literals in
    ``models/`` in favor of this helper.
    """
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, logical_to_pspec(axes, rules, mesh=mesh))
    )


def with_named_sharding(x: jax.Array, mesh: Mesh, *axes: Optional[str]) -> Any:
    """Back-compat alias: :func:`with_logical_constraint` under
    :data:`DEFAULT_RULES` (no rule-table override)."""
    return with_logical_constraint(x, mesh, *axes)
