"""Task events module: raw feed, summary, chrome-trace timeline.

Reference: ``dashboard/modules/job`` task views + `ray timeline`.
"""

from __future__ import annotations

import json
from typing import Any, Dict


def routes(gcs, helpers):
    jresp = helpers["jresp"]
    web = helpers["web"]

    async def api_tasks(_req):
        return jresp(gcs.task_events[-2000:])

    async def api_tasks_summary(_req):
        out: Dict[str, Any] = {}
        for e in gcs.task_events:
            s = out.setdefault(e["name"], {"count": 0, "failed": 0,
                                           "total_s": 0.0})
            s["count"] += 1
            s["failed"] += 0 if e.get("ok") else 1
            s["total_s"] += e["end"] - e["start"]
        for s in out.values():
            s["mean_s"] = s["total_s"] / max(s["count"], 1)
        return jresp(out)

    async def api_timeline(_req):
        # chrome://tracing export, one track per worker (same shape as
        # ray_tpu.timeline() / the reference's `ray timeline`)
        events = []
        for e in gcs.task_events:
            events.append({
                "name": e["name"], "cat": e.get("kind", "TASK"), "ph": "X",
                "ts": e["start"] * 1e6,
                "dur": max(e["end"] - e["start"], 1e-6) * 1e6,
                "pid": e.get("node_id", "node")[:8],
                "tid": e.get("worker_id", "worker"),
                "args": {"ok": e.get("ok"), "task_id": e.get("task_id")},
            })
        return web.Response(
            text=json.dumps(events),
            content_type="application/json",
            headers={"Content-Disposition":
                     'attachment; filename="timeline.json"'})

    return [
        ("GET", "/api/tasks", api_tasks),
        ("GET", "/api/tasks/summary", api_tasks_summary),
        ("GET", "/api/timeline", api_timeline),
    ]
