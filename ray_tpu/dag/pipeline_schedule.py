"""1F1B pipeline schedule over stage actors.

Reference: the compiled-graph scheduler interleaves overlapped
compute/comm ops per actor (``python/ray/dag/dag_node_operation.py``); the
reference's actual 1F1B lives inside vLLM/Megatron, outside Ray.  Here the
schedule is first-class: ``build_1f1b_schedule`` emits the canonical
one-forward-one-backward op order per stage (warmup forwards, steady
alternation, cooldown backwards — peak activation memory is ``S - s``
microbatches at stage ``s``, not ``M``), and ``PipelineRunner`` drives it
across stage actors using ObjectRef chaining for the cross-stage data
dependencies (per-caller actor-call ordering guarantees the intra-stage op
order).

For in-graph pipeline parallelism over the ``pp`` mesh axis — the TPU fast
path — see ``ray_tpu/parallel/pipeline.py``; this module is the
actor-level counterpart for heterogeneous / multi-process stages.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

F = "F"
B = "B"
Op = Tuple[str, int]  # ("F"|"B", microbatch index)


def build_1f1b_schedule(n_stages: int, n_microbatches: int
                        ) -> List[List[Op]]:
    """Per-stage op order for the non-interleaved 1F1B schedule.

    Stage ``s`` runs ``min(S-1-s, M)`` warmup forwards, then alternates
    1F1B for the remainder, then drains with cooldown backwards.
    """
    if n_stages < 1 or n_microbatches < 1:
        raise ValueError("need n_stages >= 1 and n_microbatches >= 1")
    S, M = n_stages, n_microbatches
    schedule: List[List[Op]] = []
    for s in range(S):
        warmup = min(S - 1 - s, M)
        ops: List[Op] = [(F, i) for i in range(warmup)]
        for i in range(M - warmup):
            ops.append((F, warmup + i))
            ops.append((B, i))
        for i in range(M - warmup, M):
            ops.append((B, i))
        schedule.append(ops)
    return schedule


def max_inflight(schedule_for_stage: Sequence[Op]) -> int:
    """Peak number of microbatches forwarded but not yet backwarded —
    the stage's activation-memory high-water mark."""
    live = peak = 0
    for kind, _ in schedule_for_stage:
        live += 1 if kind == F else -1
        peak = max(peak, live)
    return peak


@dataclasses.dataclass
class PipelineResult:
    outputs: Dict[int, Any]      # microbatch -> last-stage forward output
    input_grads: Dict[int, Any]  # microbatch -> first-stage backward output


class PipelineRunner:
    """Drives stage actors through the 1F1B schedule.

    Each stage actor must expose ``forward(mb_index, x) -> y`` and
    ``backward(mb_index, grad) -> input_grad`` remote methods (the last
    stage's backward receives its own forward output's loss-grad seed as
    ``grad=None``).  Submission follows the per-stage 1F1B order; actor
    call ordering serializes ops on each stage while ObjectRef arguments
    chain the cross-stage dependencies, so overlap across stages happens
    automatically.
    """

    def __init__(self, stage_actors: Sequence[Any]):
        if not stage_actors:
            raise ValueError("need at least one stage actor")
        self.stages = list(stage_actors)

    def run(self, microbatches: Sequence[Any], *, backward: bool = True,
            timeout: Optional[float] = None) -> PipelineResult:
        import ray_tpu

        S, M = len(self.stages), len(microbatches)
        schedule = build_1f1b_schedule(S, M)
        fwd: List[Dict[int, Any]] = [dict() for _ in range(S)]
        bwd: List[Dict[int, Any]] = [dict() for _ in range(S)]
        if not backward:
            # forward-only (inference): plain GPipe fill-drain
            for s in range(S):
                for mb in range(M):
                    x = microbatches[mb] if s == 0 else fwd[s - 1][mb]
                    fwd[s][mb] = self.stages[s].forward.remote(mb, x)
            outs = ray_tpu.get(list(fwd[-1].values()), timeout=timeout)
            return PipelineResult(dict(zip(fwd[-1].keys(), outs)), {})

        # Submit in dependency-driven rounds: an op is submittable once the
        # upstream ref it consumes exists (F needs stage s-1's F; B needs
        # stage s+1's B).  Per-stage submission still follows the schedule
        # order, which actor call ordering turns into execution order.
        idx = [0] * S
        remaining = sum(len(ops) for ops in schedule)
        while remaining:
            progress = False
            for s in range(S):
                while idx[s] < len(schedule[s]):
                    kind, mb = schedule[s][idx[s]]
                    if kind == F:
                        if s > 0 and mb not in fwd[s - 1]:
                            break
                        x = microbatches[mb] if s == 0 else fwd[s - 1][mb]
                        fwd[s][mb] = self.stages[s].forward.remote(mb, x)
                    else:
                        if s < S - 1 and mb not in bwd[s + 1]:
                            break
                        g = None if s == S - 1 else bwd[s + 1][mb]
                        bwd[s][mb] = self.stages[s].backward.remote(mb, g)
                    idx[s] += 1
                    remaining -= 1
                    progress = True
            if not progress:
                raise RuntimeError("1F1B schedule deadlocked; invalid "
                                   "schedule or stage count")
        outs = ray_tpu.get(list(fwd[-1].values()), timeout=timeout)
        grads = ray_tpu.get(list(bwd[0].values()), timeout=timeout)
        return PipelineResult(
            dict(zip(fwd[-1].keys(), outs)),
            dict(zip(bwd[0].keys(), grads)),
        )
