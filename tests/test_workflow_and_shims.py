"""Workflow tier + util shims (multiprocessing Pool, metrics, accelerators)."""

import os
import time

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode


# remote functions are defined INSIDE each test (raylint: test-hygiene):
# module-level remote defs bind to whichever cluster imports them first,
# and a module-level plain impl would cloudpickle by reference to this
# test module, which workers cannot import
def _dag_fns():
    def _add(x, y):
        return x + y

    def _mul(x, k):
        return x * k

    return ray_tpu.remote(_add), ray_tpu.remote(_mul)


@pytest.fixture
def wf_storage(tmp_path):
    return str(tmp_path / "wf")


def test_workflow_run_and_output(ray_start, wf_storage):
    _add, _mul = _dag_fns()
    with InputNode() as inp:
        dag = _add.bind(_mul.bind(inp, 3), 10)
    out = workflow.run(dag, 5, workflow_id="w1", storage=wf_storage)
    assert out == 25
    assert workflow.get_status("w1", storage=wf_storage) == \
        workflow.WorkflowStatus.SUCCESSFUL
    assert workflow.get_output("w1", storage=wf_storage) == 25
    assert ("w1", workflow.WorkflowStatus.SUCCESSFUL) in \
        workflow.list_all(storage=wf_storage)


def test_workflow_resume_skips_completed_steps(ray_start, wf_storage):
    calls = {"n": 0}

    marker = os.path.join(wf_storage, "calls.txt")

    @ray_tpu.remote
    def counted(x):
        with open(marker, "a") as f:
            f.write("x")
        return x + 1

    @ray_tpu.remote
    def boom(x, should_fail_file):
        if os.path.exists(should_fail_file):
            raise RuntimeError("transient")
        return x * 100

    os.makedirs(wf_storage, exist_ok=True)
    fail_flag = os.path.join(wf_storage, "fail")
    open(fail_flag, "w").close()

    with InputNode() as inp:
        dag = boom.bind(counted.bind(inp), fail_flag)

    with pytest.raises(Exception):
        workflow.run(dag, 1, workflow_id="w2", storage=wf_storage)
    assert workflow.get_status("w2", storage=wf_storage) == \
        workflow.WorkflowStatus.RESUMABLE
    first_calls = len(open(marker).read())
    assert first_calls == 1

    os.unlink(fail_flag)  # clear the fault
    with InputNode() as inp:
        dag2 = boom.bind(counted.bind(inp), fail_flag)
    out = workflow.resume("w2", dag2, storage=wf_storage)
    assert out == 200
    # the counted step restored from its checkpoint — not re-executed
    assert len(open(marker).read()) == first_calls


def test_workflow_metadata_counts(ray_start, wf_storage):
    _add, _mul = _dag_fns()
    with InputNode() as inp:
        dag = _add.bind(inp, 1)
    workflow.run(dag, 1, workflow_id="w3", storage=wf_storage)
    meta = workflow.get_metadata("w3", storage=wf_storage)
    assert meta["steps_executed"] == 1
    # re-run same workflow: everything restores
    with InputNode() as inp:
        dag2 = _add.bind(inp, 1)
    workflow.resume("w3", dag2, storage=wf_storage)
    meta = workflow.get_metadata("w3", storage=wf_storage)
    assert meta["steps_restored"] == 1 and meta["steps_executed"] == 0


def test_multiprocessing_pool(ray_start):
    from ray_tpu.util.multiprocessing import Pool

    # defined inside the test: cloudpickled by value, so workers don't need
    # the test module importable
    def _square(x):
        return x * x

    with Pool(processes=4) as pool:
        assert pool.map(_square, range(10)) == [x * x for x in range(10)]
        assert pool.starmap(pow, [(2, 3), (3, 2)]) == [8, 9]
        assert pool.apply(_square, (7,)) == 49
        r = pool.apply_async(_square, (8,))
        assert r.get(timeout=60) == 64
        assert list(pool.imap(_square, range(5), chunksize=2)) == [
            0, 1, 4, 9, 16]
        assert sorted(pool.imap_unordered(_square, range(5))) == [
            0, 1, 4, 9, 16]
    with pytest.raises(ValueError):
        pool.map(_square, [1])


def test_joblib_backend(ray_start):
    import math

    import joblib
    from joblib import Parallel, delayed

    from ray_tpu.util.joblib import register_ray_tpu

    register_ray_tpu()
    with joblib.parallel_config(backend="ray_tpu"):
        out = Parallel(n_jobs=4)(
            delayed(math.factorial)(i) for i in range(12)
        )
    assert out == [math.factorial(i) for i in range(12)]
    # n_jobs=-1 resolves to the cluster's CPU count.
    with joblib.parallel_config(backend="ray_tpu"):
        out2 = Parallel(n_jobs=-1)(
            delayed(lambda x: x * x)(i) for i in range(8)
        )
    assert out2 == [i * i for i in range(8)]
    # task exceptions propagate instead of hanging Parallel
    def _boom(i):
        raise RuntimeError("boom")

    with pytest.raises(Exception, match="boom"):
        with joblib.parallel_config(backend="ray_tpu"):
            Parallel(n_jobs=2)(delayed(_boom)(i) for i in range(4))
    # joblib's negative convention: -2 = all but one CPU
    from ray_tpu.util.joblib import RayTpuBackend

    be = RayTpuBackend()
    n_all = be.effective_n_jobs(-1)
    assert be.effective_n_jobs(-2) == max(1, n_all - 1)


def test_metrics_registry(ray_start):
    from ray_tpu.util import metrics

    c = metrics.Counter("test_requests", "reqs", tag_keys=("route",))
    c.inc(1.0, {"route": "/a"})
    c.inc(2.0, {"route": "/a"})
    g = metrics.Gauge("test_depth")
    g.set(7.0)
    h = metrics.Histogram("test_lat", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    snap = metrics.collect_local()
    assert snap["test_requests"]["series"][0]["value"] == 3.0
    assert snap["test_depth"]["series"][0]["value"] == 7.0
    hist = snap["test_lat"]["histogram"][0]
    assert hist["counts"] == [1, 1, 1]
    text = metrics.prometheus_text(snap)
    assert 'test_requests{route="/a"} 3.0' in text
    assert "# TYPE test_depth gauge" in text
    # valid histogram exposition: cumulative buckets + sum + count
    assert 'test_lat_bucket{le="0.1"} 1' in text
    assert 'test_lat_bucket{le="1.0"} 2' in text
    assert 'test_lat_bucket{le="+Inf"} 3' in text
    assert "test_lat_count 3" in text
    assert "test_lat_sum 5.55" in text


def test_accelerator_detection_env(monkeypatch):
    from ray_tpu._private.accelerators import TPUAcceleratorManager, detect_resources

    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
    monkeypatch.setenv("TPU_NAME", "my-slice")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
    assert TPUAcceleratorManager.get_current_node_accelerator_type() == \
        "TPU-v5litepod"
    assert TPUAcceleratorManager.get_current_pod_worker_count() == 2
    res = TPUAcceleratorManager.slice_resources()
    assert res.get("TPU-v5litepod-16-head") == 1.0
    # worker 1 is not a head
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    assert TPUAcceleratorManager.slice_resources() == {}
    env = {}
    TPUAcceleratorManager.set_visible_chips(env, [0, 2])
    assert env["TPU_VISIBLE_CHIPS"] == "0,2"


class TestActorPool:
    def test_map_ordered_and_unordered(self, ray_start):
        @ray_tpu.remote
        class Doubler:
            def double(self, v):
                return 2 * v

        from ray_tpu.util import ActorPool

        pool = ActorPool([Doubler.remote(), Doubler.remote()])
        out = list(pool.map(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
        assert out == [2, 4, 6, 8]  # submission order, > pool size
        out2 = sorted(pool.map_unordered(
            lambda a, v: a.double.remote(v), [5, 6, 7]))
        assert out2 == [10, 12, 14]

    def test_submit_get_next_and_pool_management(self, ray_start):
        import pytest as _pytest

        @ray_tpu.remote
        class Echo:
            def echo(self, v):
                return v

        a1, a2 = Echo.remote(), Echo.remote()
        from ray_tpu.util import ActorPool

        pool = ActorPool([a1, a2])
        assert pool.has_free() and not pool.has_next()
        pool.submit(lambda a, v: a.echo.remote(v), "x")
        assert pool.has_next()
        assert pool.get_next(timeout=30) == "x"
        with _pytest.raises(StopIteration):
            pool.get_next()
        # pop an idle actor out, push it back, queued work dispatches
        popped = pool.pop_idle()
        assert popped is not None
        pool.submit(lambda a, v: a.echo.remote(v), 1)
        pool.submit(lambda a, v: a.echo.remote(v), 2)
        pool.submit(lambda a, v: a.echo.remote(v), 3)  # queues (1 actor)
        pool.push(popped)
        assert sorted(pool.get_next_unordered(timeout=30)
                      for _ in range(3)) == [1, 2, 3]


class TestQueue:
    def test_fifo_put_get(self, ray_start):
        from ray_tpu.util.queue import Queue

        q = Queue()
        for i in range(5):
            q.put(i)
        assert len(q) == 5 and not q.empty()
        assert [q.get(timeout=30) for _ in range(5)] == list(range(5))
        assert q.empty()
        q.shutdown(force=True)

    def test_maxsize_full_empty_and_batches(self, ray_start):
        import pytest as _pytest

        from ray_tpu.util.queue import Empty, Full, Queue

        q = Queue(maxsize=2)
        q.put(1)
        q.put(2)
        assert q.full()
        with _pytest.raises(Full):
            q.put_nowait(3)
        with _pytest.raises(Full):
            q.put(3, timeout=0.2)
        assert q.get_nowait() == 1
        q.put_nowait(3)
        assert q.get_nowait_batch(2) == [2, 3]
        with _pytest.raises(Empty):
            q.get_nowait()
        with _pytest.raises(Empty):
            q.get(timeout=0.2)
        with _pytest.raises(Empty):
            q.get_nowait_batch(1)
        q.put_nowait_batch([7, 8])
        with _pytest.raises(Full):
            q.put_nowait_batch([9])  # all-or-nothing over maxsize
        assert q.get_nowait_batch(2) == [7, 8]
        q.shutdown()

    def test_queue_shared_across_tasks(self, ray_start):
        from ray_tpu.util.queue import Queue

        q = Queue()

        @ray_tpu.remote
        def producer(q, n):
            for i in range(n):
                q.put(i)
            return n

        assert ray_tpu.get(producer.remote(q, 4), timeout=60) == 4
        assert sorted(q.get(timeout=30) for _ in range(4)) == [0, 1, 2, 3]
        q.shutdown(force=True)

    def test_map_drains_stale_results(self, ray_start):
        @ray_tpu.remote
        class Echo2:
            def echo(self, v):
                return v

        from ray_tpu.util import ActorPool

        pool = ActorPool([Echo2.remote()])
        pool.submit(lambda a, v: a.echo.remote(v), 99)  # never consumed
        out = list(pool.map(lambda a, v: a.echo.remote(v), [1, 2]))
        assert out == [1, 2]  # the stale 99 is NOT in the map output

    def test_no_actor_pool_errors_loudly(self, ray_start):
        import pytest as _pytest

        @ray_tpu.remote
        class Echo3:
            def echo(self, v):
                return v

        from ray_tpu.util import ActorPool

        pool = ActorPool([Echo3.remote()])
        pool.pop_idle()
        pool.submit(lambda a, v: a.echo.remote(v), 1)
        with _pytest.raises(RuntimeError, match="no actors"):
            pool.get_next(timeout=5)
        with _pytest.raises(RuntimeError, match="no actors"):
            pool.get_next_unordered(timeout=5)

    def test_map_stale_work_still_executes_without_blocking(self, ray_start):
        """A hung-looking earlier submission must not hang map(), yet its
        side effects must still land (it executes; only its result is
        discarded)."""
        import time as _time

        @ray_tpu.remote
        class Counter4:
            def __init__(self):
                self.calls = []

            def slow(self, v):
                _time.sleep(1.0)
                self.calls.append(v)
                return v

            def fast(self, v):
                self.calls.append(v)
                return v

            def get_calls(self):
                return list(self.calls)

        from ray_tpu.util import ActorPool

        actor = Counter4.remote()
        pool = ActorPool([actor])
        pool.submit(lambda a, v: a.slow.remote(v), "stale")
        t0 = _time.monotonic()
        out = list(pool.map(lambda a, v: a.fast.remote(v), ["a", "b"]))
        assert out == ["a", "b"]
        assert _time.monotonic() - t0 < 30
        # the stale submission still executed (side effect present)
        calls = ray_tpu.get(actor.get_calls.remote(), timeout=30)
        assert calls[0] == "stale" and set(calls) == {"stale", "a", "b"}

    def test_map_discards_stale_queued_results_but_runs_them(self, ray_start):
        """Queued-but-undispatched earlier submissions also execute
        (side effects preserved) without appearing in map output."""
        @ray_tpu.remote
        class Recorder5:
            def __init__(self):
                self.seen = []

            def rec(self, v):
                self.seen.append(v)
                return v

            def get_seen(self):
                return list(self.seen)

        from ray_tpu.util import ActorPool

        actor = Recorder5.remote()
        pool = ActorPool([actor])
        # first submit dispatches; the next two queue behind it
        for v in ["q1", "q2", "q3"]:
            pool.submit(lambda a, v: a.rec.remote(v), v)
        out = list(pool.map(lambda a, v: a.rec.remote(v), ["m1", "m2"]))
        assert out == ["m1", "m2"]
        seen = ray_tpu.get(actor.get_seen.remote(), timeout=30)
        assert set(seen) == {"q1", "q2", "q3", "m1", "m2"}
