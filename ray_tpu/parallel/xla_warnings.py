"""XLA SPMD resharding-warning capture: make layout bugs *countable*.

XLA's SPMD partitioner reports inefficient sharding transitions — the
"involuntary full rematerialization" / "SPMD will replicate the tensor"
messages — from C++ directly onto **file descriptor 2**, bypassing
``sys.stderr`` entirely.  Python-level redirection
(``contextlib.redirect_stderr``) never sees them, which is how the
multichip bench shipped five rounds of silent full-layout round trips on
its hottest gather: the warnings scrolled past in the tail text and no
record field ever counted them.

:func:`capture_stderr_fd` dup2-swaps fd 2 onto a temp file for the
scope of a compile, restores it, and **re-emits the captured bytes** to
the real stderr afterwards — nothing is swallowed, it just becomes
readable to the process that produced it.  :func:`count_sharding_warnings`
turns the captured text into the ``xla_sharding_warnings`` number the
bench records and the golden-sharding guard test gate on.
"""

from __future__ import annotations

import contextlib
import os
import sys
import tempfile
from typing import Dict, Iterator, List

#: substrings that mark one SPMD layout-transition warning line.  Two
#: classes exist in practice: the partitioner's "involuntary full
#: rematerialization" (it copied the whole tensor through a fresh
#: layout) and the "last resort" replicate-then-repartition fallback on
#: a sharding_constraint it could not honor efficiently.
SHARDING_WARNING_MARKERS = (
    "Involuntary full rematerialization",
    "SPMD will replicate the tensor",
)

#: while a capture is live, names the on-disk file fd 2 is redirected
#: into — the post-mortem pointer for a hard crash inside the scope
_ENV_CAPTURE_PATH = "RAY_TPU_FD2_CAPTURE_PATH"

_capture_seq = 0


def count_sharding_warnings(text: str) -> int:
    """Number of SPMD layout-transition warning LINES in ``text`` (a
    line matching several markers still counts once)."""
    return sum(
        1 for line in text.splitlines()
        if any(m in line for m in SHARDING_WARNING_MARKERS))


def sharding_warning_lines(text: str) -> List[str]:
    return [line for line in text.splitlines()
            if any(m in line for m in SHARDING_WARNING_MARKERS)]


@contextlib.contextmanager
def capture_stderr_fd(replay: bool = True) -> Iterator[Dict[str, str]]:
    """Capture everything written to fd 2 (C++ included) in the scope.

    Yields a dict that gains ``"text"`` (the captured bytes, decoded
    with replacement) when the scope exits.  With ``replay=True`` the
    captured bytes are written back to the original stderr on exit, so
    wrapping a compile in this capture never hides its diagnostics —
    it only makes them *also* available to the caller.

    Nesting is safe (each level saves its own duplicate of the current
    fd 2).  If fd plumbing fails (no fd 2 — some embedded interpreters),
    the scope degrades to a no-op capture with ``"text": ""``.

    Crash safety: the capture file is NAMED
    (``<tmpdir>/ray_tpu_fd2_capture_<pid>_<n>.log``, also exported via
    ``RAY_TPU_FD2_CAPTURE_PATH`` while a capture is live) and deleted
    only on orderly exit — a hard abort mid-scope (XLA check failure,
    SIGABRT) leaves its final words on disk at that path instead of in
    an unlinked anonymous file nobody can read post-mortem.
    """
    out: Dict[str, str] = {}
    try:
        sys.stderr.flush()
    except Exception:  # noqa: BLE001 — a closed stderr must not break capture
        pass
    try:
        saved_fd = os.dup(2)
    except OSError:
        out["text"] = ""
        yield out
        return
    global _capture_seq
    _capture_seq += 1
    path = os.path.join(
        tempfile.gettempdir(),
        f"ray_tpu_fd2_capture_{os.getpid()}_{_capture_seq}.log")
    try:
        tmp = open(path, "w+b")
    except OSError:
        # unwritable/full tmpdir: same degrade-to-no-op contract as a
        # missing fd 2 — a bench round must never die over diagnostics
        os.close(saved_fd)
        out["text"] = ""
        yield out
        return
    prev_path = os.environ.get(_ENV_CAPTURE_PATH)
    os.environ[_ENV_CAPTURE_PATH] = path
    try:
        os.dup2(tmp.fileno(), 2)
        try:
            yield out
        finally:
            try:
                sys.stderr.flush()
            except Exception:  # noqa: BLE001
                pass
            os.dup2(saved_fd, 2)
            tmp.seek(0)
            data = tmp.read()
            out["text"] = data.decode("utf-8", errors="replace")
            if replay and data:
                try:
                    os.write(saved_fd, data)
                except OSError:
                    pass
    finally:
        os.close(saved_fd)
        tmp.close()
        try:
            os.unlink(path)  # orderly exit: bytes are replayed/returned
        except OSError:
            pass
        if prev_path is None:
            os.environ.pop(_ENV_CAPTURE_PATH, None)
        else:
            os.environ[_ENV_CAPTURE_PATH] = prev_path


@contextlib.contextmanager
def sharding_warning_capture(replay: bool = True) -> Iterator[Dict]:
    """Count SPMD resharding warnings emitted inside the scope.

    Yields a dict that gains ``"count"`` and ``"lines"`` on exit::

        with sharding_warning_capture() as w:
            trainer.compile(state, batch)
        record["xla_sharding_warnings"] = w["count"]
    """
    with capture_stderr_fd(replay=replay) as cap:
        out = cap
        yield out
    out["count"] = count_sharding_warnings(out.get("text", ""))
    out["lines"] = sharding_warning_lines(out.get("text", ""))
