"""BASELINE row (b): Data.map_batches batch inference — batches/s.

Reference target: "Data map_batches ImageNet inference — batches/s"
(`BASELINE.md:72-81`; the reference's driver class is the
`release/nightly_tests/dataset/` image-inference suite).  The reference
repo publishes no absolute number, so the checked-in result is this
box's absolute batches/s and images/s through the full framework path:

  synthetic ImageNet-shaped blocks (uint8 [B, 224, 224, 3])
  -> ``ray_tpu.data`` lazy plan -> streaming executor (byte-budget
  backpressure) -> ``map_batches`` on a TPU actor (ActorPoolStrategy)
  running ViT-B/16 bf16 inference, weights resident in HBM.

Run: ``python benchmarks/data_inference_bench.py [--blocks N] [--batch B]``
"""

import argparse
import json
import time

import numpy as np


class ViTInfer:
    """map_batches actor: owns the chip, weights stay in HBM."""

    def __init__(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.vit import ViTConfig, vit_apply, vit_init

        cfg = ViTConfig(dtype=jnp.bfloat16)  # ViT-B/16, 86M params
        self.cfg = cfg
        self.params = vit_init(jax.random.PRNGKey(0), cfg)
        self._apply = jax.jit(lambda p, x: jnp.argmax(
            vit_apply(p, x, cfg), axis=-1))
        self._jnp = jnp

    def __call__(self, batch):
        x = self._jnp.asarray(batch["image"], self._jnp.bfloat16) / 127.5 - 1.0
        pred = self._apply(self.params, x)
        return {"pred": np.asarray(pred)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", type=int, default=24)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()

    import ray_tpu
    import ray_tpu.data as rd
    from ray_tpu.data import ActorPoolStrategy

    ray_tpu.init(num_cpus=4, num_tpus=1)
    try:
        rng = np.random.default_rng(0)
        items = [{"image": rng.integers(
            0, 255, (args.batch, 224, 224, 3), dtype=np.uint8)}
            for _ in range(args.blocks)]
        ds = rd.from_items(items, parallelism=args.blocks)
        ds = ds.map_batches(
            ViTInfer, compute=ActorPoolStrategy(size=1), batch_size=None,
            num_tpus=1)
        # warm pass 1 block (compile + actor start excluded from timing)
        _ = ds.limit(1).take_all()
        t0 = time.perf_counter()
        out = ds.take_all()
        dt = time.perf_counter() - t0
        n_imgs = sum(np.asarray(r["pred"]).size
                     for r in out) if out and hasattr(
            out[0]["pred"], "__len__") else len(out)
        n_imgs = args.blocks * args.batch
        print(json.dumps({
            "benchmark": "data_map_batches_inference",
            "model": "ViT-B/16 bf16 (ImageNet-shaped 224x224)",
            "batches_per_s": round(args.blocks / dt, 2),
            "images_per_s": round(n_imgs / dt, 1),
            "batch_size": args.batch,
            "blocks": args.blocks,
            "wall_s": round(dt, 2),
        }))
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
