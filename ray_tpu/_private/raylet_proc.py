"""Standalone raylet process — an additional "node" joining an existing GCS.

Used by ``ray_tpu.cluster_utils.Cluster.add_node`` to build multi-node
topologies on one host (reference: ``python/ray/cluster_utils.py:135,202``).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--gcs-addr", required=True)
    parser.add_argument("--resources", required=True)
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--node-name", default="")
    args = parser.parse_args()

    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    from ray_tpu._private.raylet import Raylet

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    raylet = Raylet(
        args.session_dir,
        gcs_addr=args.gcs_addr,
        resources=json.loads(args.resources),
        labels=json.loads(args.labels),
        node_name=args.node_name,
    )
    # a cluster-wide shutdown_node must end this PROCESS, not just the
    # raylet object (the launcher's `down` relies on it)
    raylet.on_shutdown = lambda: loop.call_later(0.2, loop.stop)
    loop.run_until_complete(raylet.start())
    # readiness marker for the parent
    marker = os.path.join(args.session_dir, f"raylet_{raylet.node_id[:12]}.ready")
    with open(marker, "w") as f:
        f.write(raylet.addr)
    print(json.dumps({"node_id": raylet.node_id, "addr": raylet.addr}), flush=True)

    # graceful SIGTERM: unregister from the GCS before exiting so the node
    # flips to dead immediately instead of after the heartbeat timeout
    # (the autoscaler/slice-provider terminate path sends SIGTERM)
    import signal

    def _term(_sig, _frm):
        async def _stop_and_exit():
            try:
                await asyncio.wait_for(raylet.stop(), timeout=8.0)
            except Exception:  # noqa: BLE001
                pass
            loop.stop()

        asyncio.ensure_future(_stop_and_exit())

    loop.add_signal_handler(signal.SIGTERM, _term, signal.SIGTERM, None)
    try:
        loop.run_forever()
    except KeyboardInterrupt:
        pass
    sys.exit(0)


if __name__ == "__main__":
    main()
