"""Native (C++) runtime components, built on first use (see build.py)."""
