"""Elementwise/norm/rotary building blocks (XLA-fused; no kernels needed).

These stay as plain jnp: XLA fuses them into adjacent matmuls, so a Pallas
kernel would only add boundary overhead.  Computation is done in fp32 and
cast back, the standard TPU-stability recipe for bf16 activations.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """RMSNorm in fp32, output in x.dtype. scale has shape [dim]."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jnp.reciprocal(jnp.sqrt(var + eps))
    return (x * scale.astype(jnp.float32)).astype(dtype)


def rope_frequencies(
    head_dim: int, max_seq_len: int, theta: float = 10000.0
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Precompute rotary cos/sin tables [max_seq_len, head_dim // 2] (fp32)."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    positions: Optional[jnp.ndarray] = None,
    precise: bool = False,
) -> jnp.ndarray:
    """Rotary position embedding.

    x: [batch, seq, heads, head_dim]; cos/sin: [max_seq, head_dim//2];
    positions: optional [batch, seq] int32 (defaults to arange).

    By default the rotation runs in x.dtype: cos/sin are in [-1, 1], so
    bf16 rotation loses <0.4% relative precision while cutting the fp32
    intermediate HBM traffic that otherwise dominates this op's cost
    (measured +2% end-to-end MFU on v5e).  precise=True keeps fp32.
    """
    b, s, h, d = x.shape
    ct = jnp.float32 if precise else x.dtype
    if positions is None:
        cos_g = cos[:s][None, :, None, :].astype(ct)
        sin_g = sin[:s][None, :, None, :].astype(ct)
    else:
        cos_g = cos[positions][:, :, None, :].astype(ct)
        sin_g = sin[positions][:, :, None, :].astype(ct)
    x1, x2 = jnp.split(x.astype(ct), 2, axis=-1)
    out = jnp.concatenate(
        [x1 * cos_g - x2 * sin_g, x2 * cos_g + x1 * sin_g], axis=-1
    )
    return out.astype(x.dtype)


def swiglu(gate: jnp.ndarray, up: jnp.ndarray) -> jnp.ndarray:
    """SwiGLU activation: silu(gate) * up."""
    g = gate.astype(jnp.float32)
    return (g * jnp.reciprocal(1.0 + jnp.exp(-g))).astype(gate.dtype) * up
