"""Autoscaler e2e on real subprocess raylets (reference model:
``test_autoscaler_fake_multinode``)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    LocalSubprocessNodeProvider,
    NodeTypeConfig,
)


@pytest.fixture
def scaler(ray_isolated):
    from ray_tpu import __init__ as _  # noqa: F401
    import ray_tpu as rt

    services = rt._node_services
    provider = LocalSubprocessNodeProvider(services.session_dir,
                                           services.gcs_addr)
    cfg = AutoscalerConfig(
        node_types={"cpu-worker": NodeTypeConfig(
            resources={"CPU": 2.0}, min_workers=0, max_workers=3)},
        idle_timeout_s=3.0, upscale_interval_s=0.5)
    a = Autoscaler(services.gcs_addr, provider, cfg)
    yield a, provider
    a.stop()
    for pid in provider.non_terminated_nodes():
        provider.terminate_node(pid)


def _alive_nodes():
    return [n for n in ray_tpu.nodes() if n["alive"]]


def test_scale_up_on_demand_then_down_when_idle(scaler):
    a, provider = scaler
    assert len(_alive_nodes()) == 1  # head only

    # saturate the head (8 CPUs) and queue more work than fits
    @ray_tpu.remote(num_cpus=2)
    def hold(t):
        time.sleep(t)
        return 1

    refs = [hold.remote(8.0) for _ in range(8)]  # demand: 16 CPUs
    time.sleep(1.5)  # let heartbeats carry the pending demand
    summary = a.reconcile_once()
    assert summary["pending"] > 0
    assert summary["launched"], f"no launch despite demand: {summary}"

    deadline = time.time() + 30
    while len(_alive_nodes()) < 2 and time.time() < deadline:
        a.reconcile_once()
        time.sleep(0.5)
    assert len(_alive_nodes()) >= 2

    ray_tpu.get(refs, timeout=120)  # work completes across the grown cluster

    # idle scale-down
    deadline = time.time() + 60
    while provider.non_terminated_nodes() and time.time() < deadline:
        a.reconcile_once()
        time.sleep(0.5)
    assert not provider.non_terminated_nodes(), "idle nodes not terminated"


def test_min_workers_maintained(scaler):
    a, provider = scaler
    a.config.node_types["cpu-worker"] = NodeTypeConfig(
        resources={"CPU": 2.0}, min_workers=2, max_workers=3)
    a.reconcile_once()
    assert len(provider.non_terminated_nodes()) == 2
    # idle timeout never drops below min_workers
    a.config.idle_timeout_s = 0.0
    time.sleep(1.0)
    a.reconcile_once()
    a.reconcile_once()
    assert len(provider.non_terminated_nodes()) == 2


def test_max_workers_cap(scaler):
    a, provider = scaler
    a.config.node_types["cpu-worker"] = NodeTypeConfig(
        resources={"CPU": 2.0}, min_workers=0, max_workers=1)

    @ray_tpu.remote(num_cpus=2)
    def hold(t):
        time.sleep(t)

    refs = [hold.remote(6.0) for _ in range(10)]
    time.sleep(1.5)
    for _ in range(4):
        a.reconcile_once()
    assert len(provider.non_terminated_nodes()) <= 1
    ray_tpu.get(refs, timeout=120)


def test_cluster_launcher_up_down(tmp_path):
    """VERDICT r2 #6: `raytpu up/down cluster.yaml` stands a whole
    cluster up from config (head bootstrap + worker join) and tears it
    down (reference scripts.py:706 + commands.py)."""
    import json
    import os
    import subprocess
    import sys
    import time

    from ray_tpu.autoscaler.launcher import (cluster_down, cluster_status,
                                             cluster_up)

    cfg = tmp_path / "cluster.yaml"
    cfg.write_text(
        "cluster_name: launcher-e2e\n"
        "provider:\n"
        "  type: subprocess\n"
        "head:\n"
        "  resources: {CPU: 4}\n"
        "worker_types:\n"
        "  smallcpu:\n"
        "    resources: {CPU: 2}\n"
        "    min_workers: 2\n"
        "    max_workers: 2\n")
    state = cluster_up(str(cfg), no_monitor=True)
    try:
        assert state["head_pid"] and len(state["workers"]) == 2
        # a fresh driver connects by address and sees 3 nodes
        prog = tmp_path / "probe.py"
        prog.write_text(
            "import os, sys\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import ray_tpu\n"
            f"ray_tpu.init(address={state['gcs_addr']!r})\n"
            "import ray_tpu.util.state as st\n"
            "nodes = [n for n in st.list_nodes() if n['alive']]\n"
            "assert len(nodes) == 3, nodes\n"
            "@ray_tpu.remote(num_cpus=2)\n"
            "def where():\n"
            "    return os.environ.get('RAY_TPU_NODE_ID', '?')\n"
            "spots = set(ray_tpu.get([where.remote() for _ in range(4)]))\n"
            "print('NODES_OK', len(spots))\n"
            "ray_tpu.shutdown()\n")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        out = subprocess.run([sys.executable, str(prog)],
                             capture_output=True, text=True, timeout=180,
                             env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        assert "NODES_OK" in out.stdout
        assert cluster_status("launcher-e2e")["head_alive"]
    finally:
        assert cluster_down(str(cfg))
    # everything is dead: head + workers
    deadline = time.time() + 20
    while time.time() < deadline:
        alive = [w for w in state["workers"]
                 if w.get("pid") and _pid_alive(w["pid"])]
        if not alive and not _pid_alive(state["head_pid"]):
            break
        time.sleep(0.5)
    assert not _pid_alive(state["head_pid"])
    assert all(not _pid_alive(w["pid"]) for w in state["workers"]
               if w.get("pid"))
    assert cluster_status("launcher-e2e") is None


def _pid_alive(pid):
    import os

    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
