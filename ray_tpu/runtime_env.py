"""Per-task/actor runtime environments.

Reference: ``python/ray/_private/runtime_env/`` (+ public ``ray.runtime_env
.RuntimeEnv``) — per-task conda/pip/working_dir/env_vars installed by a
per-node agent.  Implemented fields here:

- ``env_vars``:   applied around task execution (process-wide for actors,
  which own their worker process; scoped-with-a-lock for pooled task
  workers);
- ``working_dir``: local path OR packaged URI — local directories are
  zipped at submission into a content-addressed package uploaded to the
  GCS KV (``pkg://<hash>``), and executing workers download + extract it
  into a session cache (reference: ``runtime_env/packaging.py`` gcs://
  URIs + ``working_dir`` plugin);
- ``py_modules``: list of local paths or packaged URIs, prepended to
  ``sys.path`` after the same package/extract cycle;
- ``pip``: OFFLINE per-env provisioning (reference ``PipProcessor``,
  ``python/ray/_private/runtime_env/pip.py:45``): a venv is created with
  ``--system-site-packages`` (jax and the sealed image stay visible) and
  packages install with ``pip install --no-index --find-links`` from a
  local wheel directory.  The wheel dir rides the same content-addressed
  ``pkg://`` packaging as ``working_dir`` so any node can provision, and
  the venv itself is cached by a digest of (packages, wheel content) —
  the second task reusing an env pays zero provisioning cost;
- plugins: extra fields validated/applied through ``register_plugin``
  (the reference's plugin protocol, ``runtime_env/plugin.py``).

``conda``/``uv``/``container`` provisioning is intentionally absent: the
execution substrate ships as a sealed image with no network (SURVEY.md
environment notes); the validation below rejects them loudly rather than
pretending.
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import logging
import os
import sys
import threading
import zipfile
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_SUPPORTED = {"env_vars", "working_dir", "py_modules", "pip"}
_UNSUPPORTED = {"conda", "uv", "container", "image_uri"}

# pooled task workers share a process: env mutations are exclusive
_apply_lock = threading.Lock()

# ---------------------------------------------------------------- plugins

# name -> (validate_fn(value) -> value, apply_fn(value) -> None | context)
_PLUGINS: Dict[str, Tuple[Callable, Optional[Callable]]] = {}


def register_plugin(name: str, validate_fn: Callable[[Any], Any],
                    apply_fn: Optional[Callable[[Any], Any]] = None):
    """Extend runtime_env with a custom field (reference plugin protocol,
    ``python/ray/_private/runtime_env/plugin.py``).  ``validate_fn`` runs
    at submission; ``apply_fn`` (optional) runs in the executing worker —
    it may return a context manager to scope the application."""
    if name in _SUPPORTED or name in _UNSUPPORTED:
        raise ValueError(f"cannot override built-in field {name!r}")
    _PLUGINS[name] = (validate_fn, apply_fn)


# -------------------------------------------------------------- packaging

_PKG_PREFIX = "pkg://"
_PKG_MAX_BYTES = 100 * 1024 * 1024  # reference GCS package size cap
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
# submit-side cache: (gcs_addr, abs_path, manifest_digest) -> uploaded uri;
# keyed by the cluster so a fresh cluster (empty KV) never reuses an URI
# that was only uploaded to a previous one
_pkg_cache: Dict[Tuple[str, str, str], str] = {}
_pkg_lock = threading.Lock()


def _zip_dir(path: str) -> Tuple[bytes, str]:
    """Deterministic zip of a directory; returns (bytes, content_hash)."""
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for f in sorted(files):
            full = os.path.join(root, f)
            entries.append((os.path.relpath(full, path), full))
    h = hashlib.blake2b(digest_size=16)
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for rel, full in entries:
            try:
                with open(full, "rb") as f:
                    data = f.read()
            except OSError:
                # vanished mid-walk / broken symlink: skip, like the
                # manifest scan does
                logger.debug("skipping unreadable %s while packaging", full)
                continue
            h.update(rel.encode())
            h.update(data)
            # fixed date_time -> byte-stable archives for equal content
            info = zipfile.ZipInfo(rel, date_time=(2020, 1, 1, 0, 0, 0))
            z.writestr(info, data)
    blob = buf.getvalue()
    if len(blob) > _PKG_MAX_BYTES:
        raise ValueError(
            f"runtime_env package of {path!r} is {len(blob)} bytes, over "
            f"the {_PKG_MAX_BYTES} limit; exclude large data from "
            f"working_dir/py_modules")
    return blob, h.hexdigest()


def _manifest_digest(path: str) -> str:
    """Cheap change detector: hash of the sorted (relpath, size, mtime)
    manifest — catches deletions and preserved-mtime additions that a
    newest-mtime key would miss, without reading file contents."""
    h = hashlib.blake2b(digest_size=16)
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for f in sorted(files):
            full = os.path.join(root, f)
            try:
                st = os.stat(full)
            except OSError:
                continue
            h.update(os.path.relpath(full, path).encode())
            h.update(st.st_size.to_bytes(8, "little"))
            h.update(st.st_mtime_ns.to_bytes(12, "little", signed=True))
    return h.hexdigest()


def _upload_dir(path: str, worker) -> str:
    """Package a local dir and upload to the GCS KV; returns a pkg:// URI.
    Cached by (cluster, path, manifest digest) so repeated submissions
    don't re-zip and a fresh cluster never reuses a stale upload."""
    cluster = getattr(worker.gcs, "addr", "")
    key = (cluster, os.path.abspath(path), _manifest_digest(path))
    with _pkg_lock:
        hit = _pkg_cache.get(key)
    if hit is not None:
        return hit
    blob, digest = _zip_dir(path)
    uri = f"{_PKG_PREFIX}{digest}"
    exists = worker.run_coro(worker.gcs.call(
        "kv_exists", ns="packages", key=uri))
    if not exists:
        worker.run_coro(worker.gcs.call(
            "kv_put", ns="packages", key=uri, value=blob))
        logger.info("uploaded runtime_env package %s (%d bytes) from %s",
                    uri, len(blob), path)
    with _pkg_lock:
        _pkg_cache[key] = uri
    return uri


def package_local_dirs(env: Optional[Dict[str, Any]],
                       worker) -> Optional[Dict[str, Any]]:
    """Submission side: replace local working_dir/py_modules paths with
    content-addressed package URIs so any node can materialize them."""
    if not env:
        return env
    out = dict(env)
    wd = out.get("working_dir")
    if wd and not wd.startswith(_PKG_PREFIX) and os.path.isdir(wd):
        out["working_dir"] = _upload_dir(wd, worker)
    mods = out.get("py_modules")
    if mods:
        packed = []
        for m in mods:
            if not m.startswith(_PKG_PREFIX) and os.path.isdir(m):
                packed.append(_upload_dir(m, worker))
            else:
                packed.append(m)
        out["py_modules"] = packed
    pip = out.get("pip")
    if pip:
        # wheel dirs ride the same content-addressed packaging, so a
        # worker on ANY node can provision the env
        packed = []
        for fl in pip["find_links"]:
            if not fl.startswith(_PKG_PREFIX) and os.path.isdir(fl):
                packed.append(_upload_dir(fl, worker))
            else:
                packed.append(fl)
        out["pip"] = {"packages": pip["packages"], "find_links": packed}
    return out


def _resolve_uri(value: str) -> str:
    """Executing side: materialize a pkg:// URI into a cached local dir."""
    if not value.startswith(_PKG_PREFIX):
        return value
    from ray_tpu._private.worker import get_global_worker

    worker = get_global_worker()
    digest = value[len(_PKG_PREFIX):]
    base = os.path.join(worker.session_dir, "runtime_resources")
    dest = os.path.join(base, digest)
    if os.path.isdir(dest):
        return dest
    os.makedirs(base, exist_ok=True)
    if threading.current_thread() is getattr(worker, "_loop_thread", None):
        # actor creation runs ON the worker's IO loop: blocking run_coro
        # there would deadlock — fetch over a short-lived side connection
        from ray_tpu._private.rpc import RpcClient, run_sync

        async def _fetch():
            c = RpcClient(worker.gcs.addr)
            try:
                return await c.call("kv_get", ns="packages", key=value)
            finally:
                await c.close()

        blob = run_sync(_fetch())
    else:
        blob = worker.run_coro(worker.gcs.call(
            "kv_get", ns="packages", key=value))
    if blob is None:
        raise FileNotFoundError(f"runtime_env package {value} not found "
                                f"in the cluster KV store")
    tmp = f"{dest}.tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)  # an empty package is a valid dir
    with zipfile.ZipFile(io.BytesIO(blob)) as z:
        z.extractall(tmp)
    try:
        os.rename(tmp, dest)  # atomic: concurrent extractors both win
    except OSError:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


# ------------------------------------------------------------ pip / venv


def _normalize_pip(spec: Any) -> Dict[str, Any]:
    """Accept ``["pkg==1", ...]`` or ``{"packages": [...], "find_links":
    "dir" | ["dir", ...]}``; offline install needs at least one wheel
    source."""
    if isinstance(spec, (list, tuple)):
        spec = {"packages": list(spec)}
    if not isinstance(spec, dict):
        raise TypeError(
            f"runtime_env pip must be a list of requirements or a dict, "
            f"got {type(spec).__name__}")
    packages = spec.get("packages")
    if not isinstance(packages, (list, tuple)) or not packages \
            or not all(isinstance(p, str) for p in packages):
        raise ValueError("runtime_env pip needs a non-empty LIST of "
                         "requirement strings under 'packages' (a bare "
                         "string would be iterated per character)")
    fl = spec.get("find_links") or []
    if isinstance(fl, str):
        fl = [fl]
    if not fl:
        raise ValueError(
            "runtime_env pip is OFFLINE on this substrate (no network): "
            "provide find_links=<local wheel dir> holding the wheels "
            "(reference PipProcessor resolves from PyPI instead)")
    unknown = set(spec) - {"packages", "find_links"}
    if unknown:
        raise ValueError(f"unknown pip fields: {sorted(unknown)}")
    return {"packages": [str(p) for p in packages],
            "find_links": [str(p) for p in fl]}


def _pip_env_digest(pip: Dict[str, Any]) -> str:
    """Content-addressed venv identity: the requirement list plus the
    wheel sources' content (a pkg:// URI IS a content hash; a local dir
    contributes its wheel manifest)."""
    h = hashlib.blake2b(digest_size=16)
    for p in sorted(pip["packages"]):
        h.update(p.encode())
        h.update(b"\x00")
    for fl in pip["find_links"]:
        if fl.startswith(_PKG_PREFIX):
            h.update(fl.encode())
        else:
            h.update(_manifest_digest(fl).encode())
    return h.hexdigest()


def _venv_site_packages(venv_dir: str) -> str:
    import glob as _glob

    hits = _glob.glob(os.path.join(venv_dir, "lib", "python*",
                                   "site-packages"))
    if not hits:
        raise FileNotFoundError(
            f"venv {venv_dir} has no site-packages directory")
    return hits[0]


def provision_pip_env(pip: Dict[str, Any], session_dir: str) -> str:
    """Create (or reuse) the content-addressed venv for ``pip``; returns
    its directory.  Concurrency-safe: built in a tmp dir and atomically
    renamed, so racing workers both win and the loser's build is
    discarded."""
    import shutil
    import subprocess

    digest = _pip_env_digest(pip)
    base = os.path.join(session_dir, "runtime_resources", "venvs")
    dest = os.path.join(base, digest)
    if os.path.isdir(dest):
        return dest  # cache hit: second use pays nothing
    os.makedirs(base, exist_ok=True)
    find_links = [_resolve_uri(fl) for fl in pip["find_links"]]
    tmp = f"{dest}.tmp.{os.getpid()}"
    t0 = __import__("time").perf_counter()
    try:
        # --system-site-packages: the sealed image's jax/numpy stay
        # visible; the env only ADDS wheels (reference PipProcessor
        # layers similarly on the base env).  --without-pip skips the
        # ~5s ensurepip bootstrap — the parent's pip is reachable through
        # system site-packages; fall back to a full venv if it is not.
        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages",
             "--without-pip", tmp],
            check=True, capture_output=True)

        def _install():
            cmd = [os.path.join(tmp, "bin", "python"), "-m", "pip",
                   "install", "--no-index", "--disable-pip-version-check",
                   "--no-warn-script-location"]
            for fl in find_links:
                cmd += ["--find-links", fl]
            cmd += pip["packages"]
            return subprocess.run(cmd, check=False, capture_output=True,
                                  text=True)

        out = _install()
        if out.returncode != 0 and "No module named pip" in (
                out.stderr or ""):
            shutil.rmtree(tmp, ignore_errors=True)
            subprocess.run(
                [sys.executable, "-m", "venv", "--system-site-packages",
                 tmp],
                check=True, capture_output=True)
            out = _install()
        if out.returncode != 0:
            raise RuntimeError(
                f"offline pip install failed for {pip['packages']} "
                f"(wheel dirs {find_links}):\n{out.stdout[-2000:]}"
                f"\n{out.stderr[-2000:]}")
        os.rename(tmp, dest)  # atomic: concurrent provisioners both win
        logger.info("provisioned pip runtime env %s (%d pkgs, %.1fs)",
                    digest[:12], len(pip["packages"]),
                    __import__("time").perf_counter() - t0)
    except Exception:
        shutil.rmtree(tmp, ignore_errors=True)
        if os.path.isdir(dest):  # lost the race to a peer: theirs is fine
            return dest
        raise
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def _activate_pip_env(pip: Dict[str, Any]) -> None:
    """Provision (cached) and activate in THIS process: site-packages at
    the front of sys.path, VIRTUAL_ENV set, venv bin on PATH.  Callers
    scope the mutations themselves (apply_permanent keeps them; applied()
    restores sys.path and the saved env keys)."""
    from ray_tpu._private.worker import get_global_worker

    worker = get_global_worker()
    venv = provision_pip_env(pip, worker.session_dir)
    site = _venv_site_packages(venv)
    if site not in sys.path:
        sys.path.insert(0, site)
    os.environ["VIRTUAL_ENV"] = venv
    os.environ["PATH"] = (os.path.join(venv, "bin") + os.pathsep
                          + os.environ.get("PATH", ""))


class RuntimeEnv(dict):
    """Validated runtime-env mapping (reference ``ray.runtime_env.RuntimeEnv``)."""

    def __init__(self, *, env_vars: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None,
                 py_modules: Optional[List[str]] = None,
                 pip: Optional[Any] = None, **extra):
        bad = set(extra) & _UNSUPPORTED
        if bad:
            raise ValueError(
                f"runtime_env fields {sorted(bad)} are not supported (the "
                f"runtime ships as a sealed image; use env_vars/working_dir/"
                f"py_modules)")
        unknown = set(extra) - _UNSUPPORTED - set(_PLUGINS)
        if unknown:
            raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
        super().__init__()
        if env_vars:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be Dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if working_dir:
            self["working_dir"] = str(working_dir)
        if py_modules:
            self["py_modules"] = [str(p) for p in py_modules]
        if pip:
            self["pip"] = _normalize_pip(pip)
        for name in set(extra) & set(_PLUGINS):
            validate_fn, apply_fn = _PLUGINS[name]
            value = validate_fn(extra[name])
            if apply_fn is not None:
                # the executing worker has no plugin registry: ship the
                # apply function with the env (cloudpickled, same trust
                # domain as the task function itself)
                from ray_tpu._private import serialization

                self[name] = {"__plugin_apply__":
                              serialization.dumps(apply_fn),
                              "value": value}
            else:
                self[name] = {"value": value}


def validate(runtime_env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    if not runtime_env:
        return None
    if isinstance(runtime_env, RuntimeEnv):
        return dict(runtime_env)
    return dict(RuntimeEnv(**runtime_env))


def apply_permanent(runtime_env: Optional[Dict[str, Any]]) -> None:
    """Apply to this process for good (actor workers own their process)."""
    if not runtime_env:
        return
    os.environ.update(runtime_env.get("env_vars") or {})
    # pip first: working_dir/py_modules are inserted AFTER so the user's
    # own modules shadow same-named wheel modules (the reference's
    # precedence — the task's code wins over its dependencies)
    pip = runtime_env.get("pip")
    if pip:
        _activate_pip_env(pip)
    wd = runtime_env.get("working_dir")
    if wd:
        wd = _resolve_uri(wd)
        os.chdir(wd)
        if wd not in sys.path:
            sys.path.insert(0, wd)
    for p in runtime_env.get("py_modules") or []:
        p = _resolve_uri(p)
        if p not in sys.path:
            sys.path.insert(0, p)
    # permanent application: context managers returned by plugins are
    # entered and never exited (the actor owns its process)
    for cm in _apply_plugins(runtime_env):
        cm.__enter__()


def _apply_plugins(runtime_env: Dict[str, Any]) -> list:
    """Run shipped plugin apply fns; returns any context managers they
    return so the caller can scope them (entered-for-good by
    apply_permanent, stacked by applied())."""
    from ray_tpu._private import serialization

    cms = []
    for name, entry in runtime_env.items():
        if name in _SUPPORTED or not isinstance(entry, dict):
            continue
        payload = entry.get("__plugin_apply__")
        if payload is not None:
            out = serialization.loads(payload)(entry.get("value"))
            if hasattr(out, "__enter__"):
                cms.append(out)
    return cms


@contextlib.contextmanager
def applied(runtime_env: Optional[Dict[str, Any]]):
    """Scoped application for pooled task workers.  Exclusive: the worker
    runs at most one runtime-env'd task at a time (env vars and cwd are
    process-global state)."""
    if not runtime_env:
        yield
        return
    with _apply_lock:
        # snapshot BEFORE any mutation, and mutate inside the try: a failing
        # chdir (bad working_dir) must not leak env vars into the worker
        saved_keys = set(runtime_env.get("env_vars") or {})
        if runtime_env.get("pip"):
            saved_keys |= {"VIRTUAL_ENV", "PATH"}
        saved_env: Dict[str, Optional[str]] = {
            k: os.environ.get(k) for k in saved_keys}
        saved_cwd = os.getcwd()
        saved_path = list(sys.path)
        try:
            for k, v in (runtime_env.get("env_vars") or {}).items():
                os.environ[k] = v
            # pip before working_dir/py_modules: the user's own modules
            # must shadow same-named wheel modules
            pip = runtime_env.get("pip")
            if pip:
                _activate_pip_env(pip)
            wd = runtime_env.get("working_dir")
            if wd:
                wd = _resolve_uri(wd)
                os.chdir(wd)
                sys.path.insert(0, wd)
            for p in runtime_env.get("py_modules") or []:
                sys.path.insert(0, _resolve_uri(p))
            with contextlib.ExitStack() as stack:
                for cm in _apply_plugins(runtime_env):
                    stack.enter_context(cm)  # scoped to this task
                yield
        finally:
            for k, v in saved_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            os.chdir(saved_cwd)
            sys.path[:] = saved_path
