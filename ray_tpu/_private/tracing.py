"""Causal distributed tracing: a zero-dependency trace-context layer.

Dapper-style context propagation (reference: ``ray.util.tracing``'s
OpenTelemetry integration, here dependency-free): a ``trace_id`` names one
causal tree (a serve request, a training step, a driver session), every
unit of work gets a ``span_id``, and ``parent_span_id`` links the tree.
The context rides

* ``TaskSpec.trace_ctx`` for task/actor submissions (minted in
  ``remote_function.remote`` / ``actor._invoke``, installed by the
  executor around the user function, so nested submissions chain);
* ``serve.context.RequestContext.trace_ctx`` for the serving plane;
* the contextvar in this module for everything in-process (collective
  ops, compiled-DAG submits, RLHF loop phases).

Finished spans land in a bounded per-process buffer, published through
the GCS internal KV (namespace ``"trace"``, key ``spans/<worker>``) by a
background publisher — the same channel the metrics registry uses — and
merged into the chrome://tracing export by ``util.state.timeline()``,
which also synthesizes submit/queue/execute phase spans from the task
event feed (``_record_task_event`` stamps the trace context onto every
event).

Overhead contract: with ``RAY_TPU_TRACING=0`` every hook is one dict/env
check (no allocation, no lock); the bench measures this at <2% of a
training step.  Enabled, a span is one ``time.time()`` pair plus a deque
append.

Span-hygiene (enforced by the ``span-hygiene`` raylint rule): prefer the
``span()`` context manager.  ``start_span()`` returns a handle that MUST
reach ``.end()`` on every path; stashing it in an attribute without a
closing path leaks an open span.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, Iterator, List, Optional

ENV_ENABLED = "RAY_TPU_TRACING"
ENV_BUFFER = "RAY_TPU_TRACE_BUFFER"
# shared cadence with the metrics publisher (util/metrics.py)
ENV_PUBLISH_INTERVAL = "RAY_TPU_METRICS_INTERVAL_S"

KV_NAMESPACE = "trace"
KV_PREFIX = "spans/"
# dashboard/state-side cutoff: span records from publishers silent longer
# than this are swept (matches the metrics/data namespace policy)
KV_STALE_S = 600.0


def is_enabled() -> bool:
    return os.environ.get(ENV_ENABLED, "1") not in ("0", "false", "no")


def _buffer_cap() -> int:
    try:
        return max(64, int(os.environ.get(ENV_BUFFER, "4096") or 4096))
    except ValueError:
        return 4096


def new_trace_id() -> str:
    return os.urandom(8).hex()


def new_span_id() -> str:
    return os.urandom(6).hex()


class SpanContext:
    """Immutable (trace_id, span_id, parent_span_id) triple."""

    __slots__ = ("trace_id", "span_id", "parent_span_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_span_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id

    def child(self) -> "SpanContext":
        return SpanContext(self.trace_id, new_span_id(), self.span_id)

    def to_dict(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_span_id": self.parent_span_id}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]
                  ) -> Optional["SpanContext"]:
        if not d or not d.get("trace_id") or not d.get("span_id"):
            return None
        return cls(d["trace_id"], d["span_id"], d.get("parent_span_id"))

    def __repr__(self):
        return (f"SpanContext({self.trace_id}, {self.span_id}, "
                f"parent={self.parent_span_id})")


_current: contextvars.ContextVar[Optional[SpanContext]] = \
    contextvars.ContextVar("ray_tpu_trace_ctx", default=None)

_buffer_lock = threading.Lock()
_finished: deque = deque(maxlen=_buffer_cap())
# manually-opened spans (start_span) + the lazy process root, by span_id;
# published with their current duration and ``open: True`` so a trace is
# never missing an ancestor just because it has not closed yet
_open: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
_root_ctx: Optional[SpanContext] = None
_publisher_started = False

# pluggable duration sinks: the train step ledger registers here so
# layers that must not import train/ (collective supervision, the data
# iterator) can still attribute wall time to step buckets.  Keyed by an
# opaque token for removal.
_sink_lock = threading.Lock()
_duration_sinks: Dict[int, Callable[[str, float], None]] = {}
_sink_token = 0


def register_duration_sink(fn: Callable[[str, float], None]) -> int:
    """Register ``fn(bucket, seconds)`` to receive attributed durations
    (collective-wait, data-wait, H2D, ...).  Returns a token for
    :func:`unregister_duration_sink`."""
    global _sink_token
    with _sink_lock:
        _sink_token += 1
        _duration_sinks[_sink_token] = fn
        return _sink_token


def unregister_duration_sink(token: int) -> None:
    with _sink_lock:
        _duration_sinks.pop(token, None)


def note_duration(bucket: str, seconds: float) -> None:
    """Attribute ``seconds`` of wall time to ``bucket`` in every
    registered sink.  One dict check when nothing is registered — safe
    on hot paths."""
    if not _duration_sinks:
        return
    with _sink_lock:
        sinks = list(_duration_sinks.values())
    for fn in sinks:
        try:
            fn(bucket, seconds)
        except Exception:  # noqa: BLE001 — attribution must never fail work
            pass


# ---------------------------------------------------------------------------
# context accessors
# ---------------------------------------------------------------------------


def current() -> Optional[SpanContext]:
    """The in-flight span context, or None outside any traced scope."""
    return _current.get()


def set_current(ctx: Optional[SpanContext]):
    """Install ``ctx`` as the current span context; returns the reset
    token (pair with :func:`reset_current`)."""
    return _current.set(ctx)


def reset_current(token) -> None:
    _current.reset(token)


def _process_kind() -> str:
    try:
        from ray_tpu._private.worker import global_worker

        if global_worker is not None:
            from ray_tpu._private.worker import WorkerMode

            return ("driver" if global_worker.mode == WorkerMode.DRIVER
                    else "worker")
    except Exception:  # noqa: BLE001 — no runtime yet
        pass
    return "process"


def _ensure_root() -> SpanContext:
    """The lazy per-process root span: work submitted outside any scope
    (a bare driver script) still forms one connected tree per process."""
    global _root_ctx
    if _root_ctx is not None:
        return _root_ctx
    with _buffer_lock:
        if _root_ctx is None:
            ctx = SpanContext(new_trace_id(), new_span_id(), None)
            _open[ctx.span_id] = {
                "name": f"{_process_kind()}-root", "kind": "root",
                "trace_id": ctx.trace_id, "span_id": ctx.span_id,
                "parent_span_id": None, "start": time.time(), "end": None,
                "pid": os.getpid(),
            }
            _root_ctx = ctx
    return _root_ctx


def current_or_root() -> SpanContext:
    return _current.get() or _ensure_root()


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------


def record_span(name: str, start: float, end: float,
                ctx: SpanContext, *, kind: str = "",
                attrs: Optional[Dict[str, Any]] = None) -> None:
    """Append one completed span to the process buffer."""
    if not is_enabled():
        return
    entry: Dict[str, Any] = {
        "name": name, "kind": kind, "trace_id": ctx.trace_id,
        "span_id": ctx.span_id, "parent_span_id": ctx.parent_span_id,
        "start": start, "end": end, "pid": os.getpid(),
    }
    if attrs:
        entry["attrs"] = attrs
    with _buffer_lock:
        _finished.append(entry)
    _ensure_publisher()


class Span:
    """A manually-managed span (``start_span``).  Must reach :meth:`end`
    on every path — the ``span-hygiene`` lint rule flags handles stashed
    in attributes without a closing path."""

    __slots__ = ("name", "kind", "ctx", "start", "attrs", "_ended")

    def __init__(self, name: str, kind: str, ctx: SpanContext,
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.kind = kind
        self.ctx = ctx
        self.attrs = attrs
        self.start = time.time()
        self._ended = False
        with _buffer_lock:
            _open[ctx.span_id] = {
                "name": name, "kind": kind, "trace_id": ctx.trace_id,
                "span_id": ctx.span_id,
                "parent_span_id": ctx.parent_span_id,
                "start": self.start, "end": None, "pid": os.getpid(),
            }
            while len(_open) > _buffer_cap():  # leak backstop
                _open.popitem(last=False)

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        with _buffer_lock:
            _open.pop(self.ctx.span_id, None)
        record_span(self.name, self.start, time.time(), self.ctx,
                    kind=self.kind, attrs=self.attrs)


def start_span(name: str, *, kind: str = "",
               parent: Optional[SpanContext] = None,
               attrs: Optional[Dict[str, Any]] = None) -> Optional[Span]:
    """Open a span with a non-lexical lifetime.  Returns None when
    tracing is disabled (callers guard with ``if s is not None``, or use
    :func:`span` which handles it)."""
    if not is_enabled():
        return None
    ctx = (parent or current_or_root()).child()
    _ensure_publisher()
    return Span(name, kind, ctx, attrs)


@contextlib.contextmanager
def span(name: str, *, kind: str = "",
         attrs: Optional[Dict[str, Any]] = None) -> Iterator[Optional[SpanContext]]:
    """Record a span around the block and make it the current context, so
    work submitted inside (tasks, collectives) parents to it."""
    if not is_enabled():
        yield None
        return
    ctx = current_or_root().child()
    token = _current.set(ctx)
    start = time.time()
    try:
        yield ctx
    finally:
        _current.reset(token)
        record_span(name, start, time.time(), ctx, kind=kind, attrs=attrs)


@contextlib.contextmanager
def trace(name: str, *, attrs: Optional[Dict[str, Any]] = None
          ) -> Iterator[Optional[SpanContext]]:
    """Start a FRESH trace (new ``trace_id``) rooted at this block — one
    causal tree per request/step/iteration::

        with tracing.trace("rlhf-iteration", attrs={"iter": it}):
            ...  # everything submitted here shares one trace_id
    """
    if not is_enabled():
        yield None
        return
    ctx = SpanContext(new_trace_id(), new_span_id(), None)
    token = _current.set(ctx)
    start = time.time()
    try:
        yield ctx
    finally:
        _current.reset(token)
        record_span(name, start, time.time(), ctx, kind="root", attrs=attrs)


# ---------------------------------------------------------------------------
# task-submission face (TaskSpec.trace_ctx)
# ---------------------------------------------------------------------------


def mint_task_context(name: str) -> Optional[Dict[str, Any]]:
    """The wire dict a submission stamps onto ``TaskSpec.trace_ctx``:
    a fresh span for the task, parented to the submitter's current
    context (or the lazy process root).  ``submitted_at`` anchors the
    submit→queue→execute phase synthesis in the timeline export."""
    if not is_enabled():
        return None
    parent = current_or_root()
    _ensure_publisher()
    return {
        "trace_id": parent.trace_id, "span_id": new_span_id(),
        "parent_span_id": parent.span_id, "name": name,
        "submitted_at": time.time(),
    }


@contextlib.contextmanager
def task_scope(trace_ctx: Optional[Dict[str, Any]]) -> Iterator[None]:
    """Executor-side: install the spec-carried context around the user
    function so nested submissions/collectives parent to this task."""
    ctx = SpanContext.from_dict(trace_ctx)
    if ctx is None:
        yield
        return
    token = _current.set(ctx)
    try:
        yield
    finally:
        _current.reset(token)


# ---------------------------------------------------------------------------
# buffer access + KV publication
# ---------------------------------------------------------------------------


def local_spans(include_open: bool = True) -> List[Dict[str, Any]]:
    """Snapshot of this process's span buffer (finished + open)."""
    now = time.time()
    with _buffer_lock:
        out = [dict(e) for e in _finished]
        if include_open:
            for e in _open.values():
                d = dict(e)
                d["end"] = now
                d["open"] = True
                out.append(d)
    return out


def clear_local() -> None:
    """Drop buffered spans (test isolation)."""
    global _root_ctx
    with _buffer_lock:
        _finished.clear()
        _open.clear()
        _root_ctx = None


def publish_kv() -> None:
    """Best-effort publish of the local span buffer into the GCS KV.
    Bounded (5s) so a wedged control plane can never turn a shutdown
    flush into a hang."""
    import ray_tpu

    if not ray_tpu.is_initialized():
        return
    from ray_tpu._private.worker import get_global_worker

    w = get_global_worker(required=False)
    if w is None:
        return
    spans = local_spans()
    if not spans:
        return
    wid = w.worker_id.hex()[:12]
    payload = json.dumps({"ts": time.time(), "worker": wid, "spans": spans})
    w.run_coro(
        w.gcs.call("kv_put", ns=KV_NAMESPACE, key=f"{KV_PREFIX}{wid}",
                   value=payload.encode(), overwrite=True, timeout=2),
        timeout=4)


def flush() -> None:
    """Synchronous best-effort publish (used by ``timeline()`` for the
    local process and by worker shutdown so short-lived workers' spans
    are not lost to the publish interval)."""
    try:
        publish_kv()
    except Exception:  # noqa: BLE001 — flush must never fail the caller
        pass


def publish_interval_s() -> float:
    # ONE cadence knob: the metrics module owns the parse (env name,
    # floor, default); a drifted duplicate here would silently
    # desynchronize the two publishers
    from ray_tpu.util.metrics import publish_interval_s as _interval

    return _interval()


def _ensure_publisher() -> None:
    global _publisher_started
    if _publisher_started:
        return
    with _buffer_lock:
        if _publisher_started:
            return
        _publisher_started = True

    def loop():
        while True:
            time.sleep(publish_interval_s())
            flush()

    threading.Thread(target=loop, daemon=True, name="rtpu-trace-pub").start()


def chrome_trace_events(task_events: List[Dict[str, Any]],
                        spans: List[Dict[str, Any]] = (),
                        ) -> List[Dict[str, Any]]:
    """Render task events + published spans as chrome://tracing events.

    Trace-stamped task events become a causally-linked tree: one ph=X box
    for the task (``ts`` anchored at SUBMIT time, so owner-side latency is
    visible) plus synthesized ``submit`` / ``queue`` / ``execute`` phase
    children — submit is the owner-side pipeline (enqueue + lease + push
    flight), queue is the executor-side wait for a thread/loop slot,
    execute is the user function.  Phase spans carry deterministic ids
    (``<task-span>.<phase>``) so parent links always resolve.  Events
    without a trace context render exactly as before (execution box only).
    """
    events: List[Dict[str, Any]] = []
    for e in task_events:
        pid = e.get("node_id", "node")[:8]
        tid = e.get("worker_id", "worker")
        base_args = {"ok": e.get("ok"), "task_id": e.get("task_id")}
        tr = e.get("trace") or {}
        if not tr.get("trace_id"):
            events.append({
                "name": e["name"], "cat": e.get("kind", "TASK"), "ph": "X",
                "ts": e["start"] * 1e6,
                "dur": max(e["end"] - e["start"], 1e-6) * 1e6,
                "pid": pid, "tid": tid, "args": base_args,
            })
            continue
        sid = tr["span_id"]
        # clocks cross hosts: clamp each phase boundary into [prev, end]
        submitted = min(tr.get("submitted_at") or e["start"], e["start"])
        received = min(max(tr.get("received_at") or e["start"], submitted),
                       e["start"])
        events.append({
            "name": e["name"], "cat": e.get("kind", "TASK"), "ph": "X",
            "ts": submitted * 1e6,
            "dur": max(e["end"] - submitted, 1e-6) * 1e6,
            "pid": pid, "tid": tid,
            "args": {**base_args, "trace_id": tr["trace_id"],
                     "span_id": sid,
                     "parent_span_id": tr.get("parent_span_id"),
                     "phase": "task"},
        })
        for phase, t0, t1 in (("submit", submitted, received),
                              ("queue", received, e["start"]),
                              ("execute", e["start"], e["end"])):
            events.append({
                "name": phase, "cat": "PHASE", "ph": "X",
                "ts": t0 * 1e6, "dur": max(t1 - t0, 1e-6) * 1e6,
                "pid": pid, "tid": tid,
                "args": {"task": e["name"], "task_id": e.get("task_id"),
                         "trace_id": tr["trace_id"],
                         "span_id": f"{sid}.{phase}",
                         "parent_span_id": sid, "phase": phase},
            })
    for s in spans:
        args = {"trace_id": s.get("trace_id"), "span_id": s.get("span_id"),
                "parent_span_id": s.get("parent_span_id"),
                "phase": s.get("kind") or "span"}
        if s.get("open"):
            args["open"] = True
        if s.get("attrs"):
            args.update(s["attrs"])
        events.append({
            "name": s["name"], "cat": s.get("kind") or "SPAN", "ph": "X",
            "ts": s["start"] * 1e6,
            "dur": max((s.get("end") or s["start"]) - s["start"], 1e-6) * 1e6,
            "pid": f"spans-{s.get('pid', 0)}", "tid": s.get("pid", 0),
            "args": args,
        })
    return events


def merge_span_payloads(raw_payloads) -> List[Dict[str, Any]]:
    """Merge raw KV span records (JSON bytes/str) into a deduplicated
    span list: a span republished across publish ticks keeps one record,
    and an open span is superseded by its closed record.  Shared by the
    state-API timeline (worker-side KV reads) and the dashboard (direct
    head-side table reads) so the two exports can never diverge."""
    by_id: Dict[str, Dict[str, Any]] = {}
    for raw in raw_payloads:
        try:
            payload = json.loads(raw)
        except (ValueError, TypeError):
            continue
        for s in payload.get("spans", []):
            sid = s.get("span_id")
            if not sid:
                continue
            prev = by_id.get(sid)
            if prev is None or (prev.get("open") and not s.get("open")):
                by_id[sid] = s
    return list(by_id.values())


def collect_cluster_spans() -> List[Dict[str, Any]]:
    """All published spans cluster-wide (see :func:`merge_span_payloads`)."""
    from ray_tpu.experimental.internal_kv import _internal_kv_get_prefix

    try:
        table = _internal_kv_get_prefix(KV_PREFIX, namespace=KV_NAMESPACE)
    except Exception:  # noqa: BLE001 — no cluster
        return []
    return merge_span_payloads((table or {}).values())
