"""Resource accounting + node-selection policies.

Equivalent of the reference's scheduling primitives
(``src/ray/common/scheduling/resource_set.h``, fixed-point fractional
resources in ``fixed_point.h``) and the policy set in
``src/ray/raylet/scheduling/policy/`` (hybrid pack-until-threshold-then-
spread, spread, node-affinity, label matching — ``hybrid_scheduling_policy.cc``,
``spread_scheduling_policy.cc``).

Fractional resources use integer milli-units internally (the reference's
FixedPoint uses 1/10000); TPU chips join CPU/GPU/memory as first-class
resource names, and pod-slice topology is expressed through node labels
(``tpu-slice-name``, ``tpu-worker-index``, ``tpu-pod-type``) that policies can
match on — replacing the reference's string-resource hack
(``python/ray/_private/accelerators/tpu.py:326-372`` ``TPU-{type}-head``).
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterable, List, Optional, Tuple

GRANULARITY = 10000  # milli-resource fixed point, reference fixed_point.h

# Node labels expressing pod-slice topology.  ``tpu-slice-name`` is the
# canonical key (accelerators.py metadata detection); ``tpu-slice`` is
# the provider-layer alias (tpu_slice_provider.py) — both are honored so
# real-metadata nodes and provider-launched fake hosts group the same.
SLICE_LABEL_KEYS = ("tpu-slice-name", "tpu-slice")
WORKER_INDEX_LABEL = "tpu-worker-index"


def to_fixed(v: float) -> int:
    return int(round(v * GRANULARITY))


def from_fixed(v: int) -> float:
    return v / GRANULARITY


class ResourceSet:
    """A named vector of fixed-point resource quantities."""

    __slots__ = ("_res",)

    def __init__(self, resources: Optional[Dict[str, float]] = None):
        self._res: Dict[str, int] = {}
        if resources:
            for k, v in resources.items():
                fv = to_fixed(v)
                if fv != 0:
                    self._res[k] = fv

    @classmethod
    def _from_fixed_map(cls, m: Dict[str, int]) -> "ResourceSet":
        rs = cls()
        rs._res = {k: v for k, v in m.items() if v != 0}
        return rs

    def to_dict(self) -> Dict[str, float]:
        return {k: from_fixed(v) for k, v in self._res.items()}

    def get(self, name: str) -> float:
        return from_fixed(self._res.get(name, 0))

    def is_superset_of(self, demand: "ResourceSet") -> bool:
        return all(self._res.get(k, 0) >= v for k, v in demand._res.items())

    def subtract(self, demand: "ResourceSet"):
        for k, v in demand._res.items():
            self._res[k] = self._res.get(k, 0) - v

    def add(self, other: "ResourceSet"):
        for k, v in other._res.items():
            self._res[k] = self._res.get(k, 0) + v

    def copy(self) -> "ResourceSet":
        return ResourceSet._from_fixed_map(dict(self._res))

    def is_empty(self) -> bool:
        return not any(v > 0 for v in self._res.values())

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._res == other._res


class NodeView:
    """Scheduler-visible snapshot of one node."""

    def __init__(self, node_id: str, total: Dict[str, float], available: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None, alive: bool = True):
        self.node_id = node_id
        self.total = ResourceSet(total)
        self.available = ResourceSet(available)
        self.labels = labels or {}
        self.alive = alive

    def utilization(self) -> float:
        """Max utilization over resources with nonzero totals (critical-resource
        utilization, reference ``scorer.cc`` NodeScorer)."""
        best = 0.0
        for k, tot in self.total._res.items():
            if tot <= 0:
                continue
            avail = self.available._res.get(k, 0)
            best = max(best, 1.0 - avail / tot)
        return best


def slice_of(node: "NodeView") -> Optional[str]:
    """The pod-slice name a node belongs to, or None for slice-less nodes."""
    for key in SLICE_LABEL_KEYS:
        name = node.labels.get(key)
        if name:
            return name
    return None


def ici_order(nodes: List["NodeView"]) -> List["NodeView"]:
    """Order one slice's hosts so consecutive picks are ICI neighbors.

    Within a slice, worker indexes are assigned along the physical torus
    (reference tpu.py worker numbering), so sorting by
    ``tpu-worker-index`` yields an adjacency-preferring chain: bundles
    placed in this order land on hosts whose chips share ICI links, and
    tier-B device-frame channels negotiate instead of falling to host
    shm.  Nodes without an index sort after indexed ones, by node id."""
    def key(n: "NodeView"):
        raw = n.labels.get(WORKER_INDEX_LABEL)
        try:
            return (0, int(raw), n.node_id)
        except (TypeError, ValueError):
            return (1, 0, n.node_id)

    return sorted(nodes, key=key)


def slice_groups(nodes: List["NodeView"]) -> Dict[str, List["NodeView"]]:
    """slice name -> member nodes (alive only), each group ICI-ordered."""
    groups: Dict[str, List[NodeView]] = {}
    for n in nodes:
        if not n.alive:
            continue
        name = slice_of(n)
        if name is not None:
            groups.setdefault(name, []).append(n)
    return {name: ici_order(members) for name, members in groups.items()}


_spread_rr = itertools.count()


def feasible(node: NodeView, demand: ResourceSet, labels: Dict[str, str]) -> bool:
    if not node.alive:
        return False
    if not node.total.is_superset_of(demand):
        return False
    for k, v in labels.items():
        if node.labels.get(k) != v:
            return False
    return True


def available_now(node: NodeView, demand: ResourceSet) -> bool:
    return node.available.is_superset_of(demand)


def pick_node(
    nodes: List[NodeView],
    demand: ResourceSet,
    strategy_kind: str = "DEFAULT",
    local_node_id: Optional[str] = None,
    affinity_node_id: Optional[str] = None,
    soft: bool = False,
    label_selector: Optional[Dict[str, str]] = None,
    spread_threshold: float = 0.5,
    exclude_node_ids: Optional[Iterable[str]] = None,
) -> Optional[str]:
    """Select a node for a resource demand; None means infeasible right now.

    Hybrid policy (DEFAULT): prefer the local node while its critical-resource
    utilization stays under ``spread_threshold``; then pack onto the
    lowest-utilization feasible remote node; reference
    ``hybrid_scheduling_policy.cc``.

    ``exclude_node_ids`` is a SOFT avoidance set: nodes a retrying owner
    just saw a worker die on (likely mid-death, heartbeat not yet timed
    out).  They are skipped while alternatives exist, but a cluster whose
    only feasible node is excluded still schedules there — avoidance must
    never turn a flaky worker into a deadlock.  Hard NODE_AFFINITY wins
    over avoidance (explicit user placement).
    """
    labels = label_selector or {}
    cands = [n for n in nodes if feasible(n, demand, labels) and available_now(n, demand)]
    if exclude_node_ids:
        excl = set(exclude_node_ids)
        kept = [n for n in cands if n.node_id not in excl]
        if kept:
            cands = kept
            if local_node_id in excl:
                local_node_id = None
            if affinity_node_id in excl and soft:
                affinity_node_id = None

    if strategy_kind == "NODE_AFFINITY":
        for n in nodes:
            if n.node_id == affinity_node_id:
                if feasible(n, demand, labels) and available_now(n, demand):
                    return n.node_id
                break
        if not soft:
            return None
        strategy_kind = "DEFAULT"

    if not cands:
        return None

    if strategy_kind == "SPREAD":
        # round-robin over feasible nodes, preferring least-utilized
        cands.sort(key=lambda n: (n.utilization(), n.node_id))
        return cands[next(_spread_rr) % len(cands)].node_id

    # DEFAULT / hybrid
    if local_node_id is not None:
        local = next((n for n in cands if n.node_id == local_node_id), None)
        if local is not None and local.utilization() < spread_threshold:
            return local.node_id
    under = [n for n in cands if n.utilization() < spread_threshold]
    pool = under if under else cands
    pool.sort(key=lambda n: (n.utilization(), n.node_id))
    return pool[0].node_id


def pack_bundles(
    nodes: List[NodeView],
    bundles: List[Dict[str, float]],
    strategy: str,
    exclude_node_ids: Optional[Iterable[str]] = None,
) -> Optional[List[str]]:
    """Place placement-group bundles onto nodes.

    Strategies (reference ``bundle_scheduling_policy.cc`` /
    ``python/ray/util/placement_group.py``): PACK (minimize nodes, best
    effort), STRICT_PACK (all on one node), SPREAD (best-effort one-per-node),
    STRICT_SPREAD (hard one-per-node), STRICT_PACK_SLICE (all bundles on
    nodes sharing one pod-slice label, ICI-adjacency-preferring order —
    the TPU-native gang shape).  Returns node_id per bundle or None.

    ``exclude_node_ids`` is the same SOFT avoidance set as
    :func:`pick_node`'s: DRAINING nodes (advance-notice preemption) are
    skipped while a placement exists without them, but a group that fits
    only with a draining node still places there — avoidance must never
    turn a drain notice into an unplaceable gang.
    """
    if exclude_node_ids:
        excl = set(exclude_node_ids)
        kept = [n for n in nodes if n.node_id not in excl]
        if kept:
            placement = pack_bundles(kept, bundles, strategy)
            if placement is not None:
                return placement
    demands = [ResourceSet(b) for b in bundles]
    avail = {n.node_id: n.available.copy() for n in nodes if n.alive}
    order = sorted(avail, key=lambda nid: -next(n for n in nodes if n.node_id == nid).utilization())

    def fits(nid, d):
        return avail[nid].is_superset_of(d)

    if strategy == "STRICT_PACK_SLICE":
        # Gang-schedule one contiguous slice: every bundle lands inside a
        # single slice-labelled node group, filling hosts in ICI order so
        # neighboring bundles share ICI links.  A gang that straddles two
        # slices is REJECTED (split-slice), not silently spread — the
        # whole point is that the mesh forms over one ICI domain.
        groups = slice_groups([n for n in nodes if n.alive])
        if not groups:
            # slice-less cluster (dev box, CPU proxy): every node is its
            # own one-host "slice" — degenerates to STRICT_PACK, which
            # is what topology-requesting callers got before slices
            groups = {n.node_id: [n] for n in nodes if n.alive}
        # deterministic slice preference: smallest slice that fits
        # (leave big slices for big gangs), then name for stable ties
        for name in sorted(groups, key=lambda s: (len(groups[s]), s)):
            members = groups[name]
            trial = {n.node_id: avail[n.node_id].copy() for n in members
                     if n.node_id in avail}
            placement = []
            ok = True
            for d in demands:
                pick = None
                for n in members:  # ICI order: fill along the chain
                    t = trial.get(n.node_id)
                    if t is not None and t.is_superset_of(d):
                        pick = n.node_id
                        break
                if pick is None:
                    ok = False
                    break
                trial[pick].subtract(d)
                placement.append(pick)
            if ok:
                return placement
        return None

    if strategy == "STRICT_PACK":
        for nid in avail:
            trial = avail[nid].copy()
            ok = True
            for d in demands:
                if trial.is_superset_of(d):
                    trial.subtract(d)
                else:
                    ok = False
                    break
            if ok:
                return [nid] * len(demands)
        return None

    if strategy in ("SPREAD", "STRICT_SPREAD"):
        placement: List[str] = []
        used: set = set()
        for d in demands:
            pick = None
            for nid in sorted(avail, key=lambda x: (x in used, )):
                if nid in used and strategy == "STRICT_SPREAD":
                    continue
                if fits(nid, d):
                    pick = nid
                    break
            if pick is None:
                if strategy == "STRICT_SPREAD":
                    return None
                for nid in avail:
                    if fits(nid, d):
                        pick = nid
                        break
                if pick is None:
                    return None
            avail[pick].subtract(d)
            used.add(pick)
            placement.append(pick)
        return placement

    # PACK (default): fill one node, overflow to next
    placement = []
    for d in demands:
        pick = None
        for nid in order:
            if fits(nid, d):
                pick = nid
                break
        if pick is None:
            return None
        avail[pick].subtract(d)
        placement.append(pick)
    return placement
